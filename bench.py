"""Headline benchmark: ResNet-50 training-step throughput (images/sec/chip).

The reference publishes no numbers (BASELINE.md); the driver-set north star
is >=70% of the MLPerf-reference ResNet-50 throughput per chip
(`BASELINE.json`). This bench measures the full jitted training step —
forward + backward + Adam update, bfloat16 compute, batch-norm in training
mode — on one chip with a device-resident batch, which is the per-chip
number the data-parallel strategies multiply out (gradient all-reduce is
the only addition at scale and rides ICI).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env overrides: PDDL_BENCH_BATCH (default 256), PDDL_BENCH_STEPS (default 60
— shorter windows under-report by a few % through the tunneled transport),
PDDL_BENCH_IMAGE (default 224), PDDL_BENCH_STEM ("space_to_depth" default /
"keras" for the import-parity-shaped stem), PDDL_BENCH_HBM_GBPS (chip HBM
bandwidth, default the v5e spec).

Baseline derivation (the ``vs_baseline`` denominator): the reference
publishes nothing ("published": {} in BASELINE.json), so the target is
derived from physics, not assumed: ResNet-50 training at these shapes is
HBM-bandwidth-bound (measured: MXU ~26%, >90% of spec bandwidth), so the
per-chip reference throughput is the memory roofline

    roofline img/s = HBM_bytes_per_sec / REFERENCE_bytes_per_image,

with the bandwidth from the published chip spec by device kind (Google
Cloud TPU docs; v5e = 819 GB/s HBM2) and bytes-per-image a FIXED recorded
constant of the reference formulation (328.7 MB at image 224, from XLA
cost analysis of the keras-stem step on v5e; area-scaled for other image
sizes) — deliberately NOT re-derived from the live step, so a change that
regresses bytes moved shows up in vs_baseline instead of re-rating its
own target; the live cost analysis is printed alongside for comparison.
vs_baseline = achieved / (0.7 * roofline), 0.7 per the BASELINE.json
north star ("≥70% of reference images/sec/chip").

Round-over-round comparability: round 1 graded against an ASSUMED fixed
3000 img/s MLPerf-class reference (vs_baseline = achieved / (0.7*3000));
round 2 switched the denominator to the physics roofline above. So both
ratios are emitted — ``vs_baseline`` (roofline, the headline) and
``vs_baseline_mlperf3000`` (the round-1 convention, kept so the series
BENCH_r01→rNN stays interpretable) — plus the ``stem`` used, since the
default stem also changed (keras → space_to_depth, measured neutral).

Tuning history (measured on one v5e chip, batch 256): rematerialization
variants (full-block and save-convs-only nn.remat) both LOSE (~2330 ->
~1920/~2020 img/s) — XLA's schedule already trades FLOPs for bytes better
than manual checkpointing here; batches 224/288/384/512 are all worse
than 256. The space-to-depth stem (models/resnet.py, MLPerf-style:
block-2 space-to-depth + 4x4/s1 conv, mathematically identical to the
padded 7x7/s2 stem) is the default bench variant; measured, it is
throughput-NEUTRAL here (2350 vs 2346 img/s, keras stem) because the
stem is noise against the step's ~330 MB/image total traffic — the
measurement that shows why "3000 img/s" is not reachable for this
formulation on this chip: the physical ceiling is the roofline above
(~2480 img/s at 819 GB/s), and the bench already runs at ~96% of it
(2380-2392 img/s at the 60-step window). Past that ceiling the lever is
not scheduling but changing the formulation's bytes (e.g. smaller
images, different normalization), which would change the trained model.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

# Published per-chip HBM bandwidth by device kind (Google Cloud TPU
# system-architecture docs), matched against jax's device_kind string.
HBM_BYTES_PER_SEC = {
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,   # v5e: 16 GB HBM2 @ 819 GB/s
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,       # v5p
    "TPU v6 lite": 1640e9,  # v6e / Trillium
    "TPU v6e": 1640e9,
}
DEFAULT_HBM_BYTES_PER_SEC = 819e9  # unrecognized device: assume v5e

# The REFERENCE formulation's traffic: bytes-per-image of the compiled
# keras-stem step at image 224, batch 256, recorded from XLA cost
# analysis on v5e (84.1 GB/step = 328.7 MB/image). This is a FIXED
# constant on purpose: deriving the denominator from the live step's own
# cost analysis would make vs_baseline self-referential (a change that
# doubles bytes moved would halve throughput AND halve the roofline,
# hiding the regression). The live cost analysis is still printed for
# comparison. For non-224 images the constant scales by area (conv
# activation traffic is proportional to pixel count to first order).
REFERENCE_BYTES_PER_IMAGE_224 = 328.7e6
# BASELINE.json north star: ">=70% of reference images/sec/chip".
TARGET_FRACTION = 0.7


def _live_bytes_per_image(compiled, batch: int) -> float | None:
    """Bytes the compiled step actually moves per image (diagnostics)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        total = float(cost["bytes accessed"])
        return total / batch if total > 0 else None
    except Exception:
        return None


def main() -> None:
    batch = int(os.environ.get("PDDL_BENCH_BATCH", "256"))
    steps = int(os.environ.get("PDDL_BENCH_STEPS", "60"))
    image = int(os.environ.get("PDDL_BENCH_IMAGE", "224"))
    stem = os.environ.get("PDDL_BENCH_STEM", "space_to_depth")

    from pddl_tpu.models.resnet import ResNet50
    from pddl_tpu.train.state import TrainState

    device = jax.devices()[0]
    hbm = float(os.environ.get("PDDL_BENCH_HBM_GBPS", "0")) * 1e9
    if not hbm:
        hbm = HBM_BYTES_PER_SEC.get(device.device_kind, 0)
        if not hbm:
            hbm = DEFAULT_HBM_BYTES_PER_SEC
            print(f"bench: WARNING unknown device_kind "
                  f"{device.device_kind!r}; assuming v5e HBM "
                  f"({hbm / 1e9:.0f} GB/s) — set PDDL_BENCH_HBM_GBPS",
                  file=sys.stderr)
    print(f"bench: device={device} ({device.device_kind}), batch={batch}, "
          f"image={image}, steps={steps}, stem={stem}", file=sys.stderr)

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem)
    tx = optax.adam(1e-3)
    rng = jax.random.key(0)

    images = jax.device_put(
        jax.random.normal(rng, (batch, image, image, 3), jnp.float32), device
    )
    labels = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch,), 0, 1000), device
    )

    def init(rng):
        variables = model.init(rng, images[:1], train=False)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(variables["params"]),
        )

    t0 = time.perf_counter()
    state = jax.jit(init)(rng)
    jax.block_until_ready(state)
    print(f"bench: init {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    def train_step(state, images, labels):
        def loss_of(params):
            logits, updates = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
            return loss, updates["batch_stats"]

        (loss, batch_stats), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state.params
        )
        new_state = state.apply_gradients(tx, grads, batch_stats)
        return new_state, loss

    step = jax.jit(train_step, donate_argnums=(0,))
    t0 = time.perf_counter()
    # Explicit AOT lower+compile: the same executable is then CALLED
    # directly (calling the jit wrapper would compile a second time).
    step = step.lower(state, images, labels).compile()
    print(f"bench: compile {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    ref_bpi = REFERENCE_BYTES_PER_IMAGE_224 * (image / 224) ** 2
    roofline = hbm / ref_bpi
    live_bpi = _live_bytes_per_image(step, batch)
    live_note = (f"live {live_bpi / 1e6:.1f} MB/image (cost analysis)"
                 if live_bpi else "cost analysis unavailable")
    print(f"bench: reference {ref_bpi / 1e6:.1f} MB/image -> roofline "
          f"{roofline:.0f} img/s at {hbm / 1e9:.0f} GB/s; {live_note}",
          file=sys.stderr)

    t0 = time.perf_counter()
    state, loss = step(state, images, labels)
    # Sync via scalar fetch: under the axon tunnel block_until_ready can
    # return before execution finishes; float(loss) cannot.
    float(loss)
    print(f"bench: first step {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    for _ in range(3):  # warmup
        state, loss = step(state, images, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, images, labels)
    loss = float(loss)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    print(f"bench: {dt:.3f}s for {steps} steps, loss={loss:.3f}", file=sys.stderr)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            images_per_sec / (TARGET_FRACTION * roofline), 4),
        # Round-1 convention (assumed 3000 img/s reference) so the
        # BENCH_r* series stays comparable across the denominator change.
        "vs_baseline_mlperf3000": round(
            images_per_sec / (TARGET_FRACTION * 3000.0), 4),
        "stem": stem,
    }))


if __name__ == "__main__":
    main()
