"""Headline benchmark: ResNet-50 training-step throughput (images/sec/chip).

The reference publishes no numbers (BASELINE.md); the driver-set north star
is >=70% of the MLPerf-reference ResNet-50 throughput per chip
(`BASELINE.json`). This bench measures the full jitted training step —
forward + backward + Adam update, bfloat16 compute, batch-norm in training
mode — on one chip with a device-resident batch, which is the per-chip
number the data-parallel strategies multiply out (gradient all-reduce is
the only addition at scale and rides ICI).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env overrides: PDDL_BENCH_BATCH (default 256), PDDL_BENCH_STEPS (default 30),
PDDL_BENCH_IMAGE (default 224).

Roofline note (measured on TPU v5e, batch 256): the compiled step moves
~84 GB at ~765 GB/s — 92% of the chip's ~819 GB/s HBM bandwidth, with the
MXU at ~26% — so ResNet-50 training here is bandwidth-bound and the
current number sits at the memory roofline. Rematerialization variants
(full-block and save-convs-only nn.remat) were measured and both LOSE
(~2330 -> ~1920/~2020 img/s): XLA's own schedule already trades FLOPs for
bytes better than manual checkpointing for this net. Batch 512 is also
slightly worse. Further gains need model-level surgery (e.g. the MLPerf
space-to-depth stem), which would break exact Keras-v1 weight parity.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

# "MLPerf reference" per-chip throughput assumed for vs_baseline scaling:
# ~3000 images/sec/chip for ResNet-50 on a current TPU chip; the north-star
# target is 70% of that (BASELINE.json). vs_baseline = value / (0.7 * 3000).
MLPERF_REFERENCE_IMAGES_PER_SEC_PER_CHIP = 3000.0
BASELINE_TARGET = 0.7 * MLPERF_REFERENCE_IMAGES_PER_SEC_PER_CHIP


def main() -> None:
    batch = int(os.environ.get("PDDL_BENCH_BATCH", "256"))
    steps = int(os.environ.get("PDDL_BENCH_STEPS", "30"))
    image = int(os.environ.get("PDDL_BENCH_IMAGE", "224"))

    from pddl_tpu.models.resnet import ResNet50
    from pddl_tpu.train.state import TrainState

    device = jax.devices()[0]
    print(f"bench: device={device}, batch={batch}, image={image}, steps={steps}",
          file=sys.stderr)

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.adam(1e-3)
    rng = jax.random.key(0)

    images = jax.device_put(
        jax.random.normal(rng, (batch, image, image, 3), jnp.float32), device
    )
    labels = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch,), 0, 1000), device
    )

    def init(rng):
        variables = model.init(rng, images[:1], train=False)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(variables["params"]),
        )

    t0 = time.perf_counter()
    state = jax.jit(init)(rng)
    jax.block_until_ready(state)
    print(f"bench: init {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    def train_step(state, images, labels):
        def loss_of(params):
            logits, updates = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
            return loss, updates["batch_stats"]

        (loss, batch_stats), grads = jax.value_and_grad(loss_of, has_aux=True)(
            state.params
        )
        new_state = state.apply_gradients(tx, grads, batch_stats)
        return new_state, loss

    step = jax.jit(train_step, donate_argnums=(0,))

    t0 = time.perf_counter()
    state, loss = step(state, images, labels)
    # Sync via scalar fetch: under the axon tunnel block_until_ready can
    # return before execution finishes; float(loss) cannot.
    float(loss)
    print(f"bench: compile+first step {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    for _ in range(3):  # warmup
        state, loss = step(state, images, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step(state, images, labels)
    loss = float(loss)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    print(f"bench: {dt:.3f}s for {steps} steps, loss={loss:.3f}", file=sys.stderr)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(images_per_sec / BASELINE_TARGET, 4),
    }))


if __name__ == "__main__":
    main()
