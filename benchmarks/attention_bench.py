"""Flash-attention kernel sweep vs the fused-XLA reference (real chip).

This is the harness behind the tuned ``block_q=512, block_k=1024``
defaults in ``pddl_tpu/ops/attention.py``. Timing uses a scalar fetch as
the sync point: under tunneled TPU transports ``block_until_ready`` can
return before execution finishes, silently turning a benchmark into a
dispatch-rate measurement.

    python benchmarks/attention_bench.py [--seqs 2048,4096,8192]

Representative v5e numbers (B4 H16 D64 bf16, causal, forward):

    S=2048  fl128x128 17.5  fl512x512 13.8  fl512x1024 10.3   ref 15.0
    S=4096  fl128x128 39.2  fl512x512 16.8  fl512x1024 10.6   ref 28.6
    S=8192  fl128x128 125.1 fl512x512 33.9  fl512x1024 25.3   ref OOM

(ms/call; at S=8192 the reference's O(S²) scores exceed HBM.)
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp

from pddl_tpu.ops.attention import attention_reference, flash_attention

BLOCKS = ((128, 128), (256, 512), (512, 512), (512, 1024), (1024, 1024),
          (256, 1024), (1024, 512))


def bench(make_fn, *arrs, iters: int = 10) -> float:
    f = jax.jit(make_fn)
    float(f(*arrs))  # compile + genuine sync (scalar fetch)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*arrs)
    float(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", default="2048,4096,8192")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--backward", action="store_true",
                   help="time fwd+bwd instead of forward only")
    args = p.parse_args()

    B, H, D = args.batch, args.heads, args.head_dim
    for S in (int(s) for s in args.seqs.split(",")):
        q, k, v = (
            jax.random.normal(jax.random.key(i), (B, H, S, D), jnp.bfloat16)
            for i in range(3)
        )
        row = [f"S={S}"]

        def run(attn, **kw):
            if args.backward:
                return bench(lambda a, b, c: jax.grad(
                    lambda aa: attn(aa, b, c, causal=True, **kw)
                    .astype(jnp.float32).sum()
                )(a).astype(jnp.float32).sum(), q, k, v)
            return bench(lambda a, b, c: attn(a, b, c, causal=True, **kw)
                         .astype(jnp.float32).sum(), q, k, v)

        for bq, bk in BLOCKS:
            try:
                row.append(f"fl{bq}x{bk} {run(flash_attention, block_q=bq, block_k=bk):6.1f}")
            except Exception:
                row.append(f"fl{bq}x{bk}    ERR")
        try:
            row.append(f"ref {run(attention_reference):6.1f}")
        except Exception:
            row.append("ref OOM/ERR")
        print("  ".join(row), flush=True)


if __name__ == "__main__":
    main()
