"""Decode-tick attribution: where every millisecond of serving lives.

Round 4 reported single-stream greedy decode at 26-32% of the
weight-read roofline (``artifacts/gpt_bench/r04_decode.json``) without
locating the other ~70%. Two findings from building this attribution:

1. **The r04 ratio conflated transport with chip time.** r04 divided
   tokens by the WHOLE ``generate()`` wall clock — prefill dispatch,
   tunnel round trips, scalar fetch — not the decode scan. Measured
   program-level (prefill program timed separately and subtracted), the
   on-chip decode tick is several times faster than the r04 numbers
   implied.

2. **In-situ differences, not synthetic kernels.** A first attempt
   timed hand-built "matmul-only"/"attention-only" scan programs; their
   parts summed to MORE than the whole (a scalar-carry chain serializes
   what the real program overlaps). This harness instead times REAL
   decode programs that differ by exactly one component — the method
   that settled the training-step attribution (docs/ARCHITECTURE.md
   §7b) — so every line is a fusion-faithful marginal cost:

   - ``full``       — the real greedy decode scan (sampling included);
   - ``no_sample``  — same scan, next token replaced by a constant
                      (drops argmax + the sampled-token data path);
   - ``no_head``    — + ``features_only=True`` (drops final norm +
                      LM-head matmul);
   - ``no_attn``    — + ``decode_attention`` stubbed to identity (drops
                      the cache READ sweep; cache writes remain).

   marginal costs: sampling = full−no_sample, head = no_sample−no_head,
   attention read = no_head−no_attn, everything-else = no_attn (block
   matmuls, RoPE/norm vector work, cache writes, scan machinery).

Programs are jitted directly from ``_decode_fns``-style closures (the
``_decode_programs`` LRU is bypassed: the attention stub monkeypatches a
module global, which the cache key cannot see).

    PYTHONPATH=. python benchmarks/decode_attribution.py \
        [--out artifacts/gpt_bench/r05_decode_attrib.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

from pddl_tpu.models.gpt import GPT_Small, _decode_cache_shapes
from pddl_tpu.models.llama import Llama_Small

PROMPT = 64
NEW = 256
HBM_GBPS = 819.0


def _fresh_cache(dec, batch):
    return jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                        _decode_cache_shapes(dec, batch))


def _programs(dec, *, sample: bool, head: bool):
    """(prefill, decode_scan) jitted fresh — no LRU, no donation."""

    def step_fn(params, cache, tok, features_only=False):
        out, mutated = dec.apply(
            {"params": params, "cache": cache}, tok,
            train=False, mutable=["cache"], features_only=features_only,
        )
        return mutated["cache"], out[:, -1]

    def prefill(params, cache, prompt):
        return step_fn(params, cache, prompt)

    def decode_all(params, cache, logits):
        def body(carry, _):
            cache, prev = carry
            if sample:
                tok = jnp.argmax(prev, axis=-1).astype(jnp.int32)[:, None]
            else:
                # constant next token: same shapes, no sampling data path
                tok = jnp.full((prev.shape[0], 1), 1, jnp.int32)
            cache, out = step_fn(params, cache, tok,
                                 features_only=not head)
            return (cache, out if sample else prev), out[:, :1]

        (_, _), outs = jax.lax.scan(body, (cache, logits), None, length=NEW)
        return outs

    return jax.jit(prefill), jax.jit(decode_all)


def _scalar_sync(out):
    """Force REAL completion: fetch a scalar reduced from the output.

    ``block_until_ready`` is not a trustworthy sync under the tunneled
    device transport (it can return before execution finishes, making a
    256-tick decode appear to run in microseconds); a value fetch is.
    """
    leaf = jax.tree.leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def _time(fn, *args, iters=5):
    _scalar_sync(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _scalar_sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _decode_ms_per_tick(dec, params, batch, *, sample, head):
    prefill, decode_all = _programs(dec, sample=sample, head=head)
    prompt = jax.random.randint(jax.random.key(0), (batch, PROMPT), 0, 1000)
    cache, logits = prefill(params, _fresh_cache(dec, batch), prompt)
    t = _time(decode_all, params, cache,
              logits if logits.ndim == 2 else logits[..., 0])
    return t / NEW * 1e3


class _AttnStub:
    """Context manager replacing decode_attention with an identity in the
    model modules (they import it by name at module load)."""

    def __enter__(self):
        import pddl_tpu.models.llama as ml
        import pddl_tpu.models.vit as mv

        self._saved = [(ml, ml.decode_attention), (mv, mv.decode_attention)]

        def stub(q, k_cache, v_cache, index, **kw):
            if kw.get("return_lse"):
                return q, jnp.zeros(q.shape[:-1], jnp.float32)
            return q

        for mod, _ in self._saved:
            mod.decode_attention = stub
        return self

    def __exit__(self, *exc):
        for mod, fn in self._saved:
            mod.decode_attention = fn
        return False


def _weight_bytes(params, *, head_keys=("lm_head",)):
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    head = body = 0
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        if "embed" in name.lower():
            continue  # gathered, not streamed
        if leaf.ndim < 2:
            continue
        b = leaf.size * leaf.dtype.itemsize
        if any(k in name for k in head_keys):
            head += b
        else:
            body += b
    return body, head


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="")
    args = p.parse_args()

    models = {
        "gpt_small": GPT_Small(vocab_size=50257, max_len=1024,
                               dtype=jnp.bfloat16,
                               param_dtype=jnp.bfloat16),
        "llama_small": Llama_Small(vocab_size=32000, max_len=1024,
                                   dtype=jnp.bfloat16,
                                   param_dtype=jnp.bfloat16),
    }
    record = {
        "metric": "decode_tick_attribution_ms",
        "method": "in-situ marginal costs: real decode-scan programs "
                  "differing by one component; prefill timed separately "
                  "and excluded",
        "config": {"prompt_len": PROMPT, "new_tokens": NEW,
                   "dtype": "bfloat16"},
        "device": jax.devices()[0].device_kind,
        "results": {},
    }
    for name, model in models.items():
        dec = model.clone(decode=True)
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, PROMPT), jnp.int32),
            train=False)
        params = variables["params"]
        body_b, head_b = _weight_bytes(params)
        hkv = getattr(model, "num_kv_heads", None) or model.num_heads
        d = model.embed_dim // model.num_heads
        kv_avg = 2 * model.depth * hkv * d * 2 * (PROMPT + NEW / 2)
        for batch in (1, 8):
            full = _decode_ms_per_tick(dec, params, batch,
                                       sample=True, head=True)
            nosample = _decode_ms_per_tick(dec, params, batch,
                                           sample=False, head=True)
            nohead = _decode_ms_per_tick(dec, params, batch,
                                         sample=False, head=False)
            with _AttnStub():
                noattn = _decode_ms_per_tick(dec, params, batch,
                                             sample=False, head=False)
            roof = (body_b + head_b + batch * kv_avg) / (HBM_GBPS * 1e9) * 1e3
            res = {
                "full_ms": round(full, 4),
                "sampling_ms": round(full - nosample, 4),
                "head_ms": round(nosample - nohead, 4),
                "attention_read_ms": round(nohead - noattn, 4),
                "rest_ms": round(noattn, 4),
                "weight_read_roofline_ms": round(roof, 4),
                "head_read_roofline_ms": round(
                    head_b / (HBM_GBPS * 1e9) * 1e3, 4),
                "body_read_roofline_ms": round(
                    body_b / (HBM_GBPS * 1e9) * 1e3, 4),
                "kv_read_roofline_ms": round(
                    batch * kv_avg / (HBM_GBPS * 1e9) * 1e3, 4),
                "full_vs_roofline": round(full / roof, 3),
                "tokens_per_sec_decode_only": round(batch / full * 1e3, 1),
            }
            record["results"][f"{name}_b{batch}"] = res
            print(name, f"b{batch}", json.dumps(res), flush=True)
    js = json.dumps(record)
    print(js)
    if args.out:
        with open(args.out, "w") as f:
            f.write(js + "\n")


if __name__ == "__main__":
    sys.exit(main() or 0)
