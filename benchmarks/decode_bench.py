"""Autoregressive decode throughput on one chip (generation serving path).

Measures :func:`pddl_tpu.models.gpt.generate` — batched prefill + the
ENTIRE decode as one on-device ``lax.scan`` dispatch (sampling included)
— for the GPT and Llama families at small-model shapes. The scan design
is what makes this number meaningful under tunneled/remote transports: a
host-side token loop would measure dispatch latency, not the model.

Reports new-tokens/sec (prompt excluded) for greedy decoding, single
stream (B1) and batched (B8). Representative v5e numbers are pinned in
``artifacts/gpt_bench/r03_decode.json``.

    PYTHONPATH=. python benchmarks/decode_bench.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp

from pddl_tpu.models.gpt import GPT_Small, generate
from pddl_tpu.models.llama import Llama_1B, Llama_Small
from pddl_tpu.utils.bench_artifact import provenance, timed_stats


# Peak HBM bandwidth per chip, GB/s — the denominator of the decode
# roofline (single-stream decode is weight+KV-read bound).
HBM_GBPS = {"TPU v5 lite": 819.0, "TPU v5e": 819.0}


def _roofline_tokens_per_sec(model, variables, prompt_len: int,
                             new_tokens: int) -> float | None:
    """Weight+KV bandwidth roofline for single-stream greedy decode.

    Every decoded token must read all MATMUL parameters once plus the
    live KV prefix (k and v, kv-head granularity, storage dtype) in each
    layer; the prefix is averaged over the decode. Input-embedding (and
    position) tables are excluded from the per-token weight read — decode
    GATHERS one row per token, it does not stream the table — with the
    gathered rows added back. Anything above the returned rate would
    exceed the chip's HBM bandwidth.
    """
    bw = HBM_GBPS.get(jax.devices()[0].device_kind)
    if bw is None:
        return None
    params = dict(variables["params"])
    gathered_rows = 0
    for name in ("token_embed", "embed", "pos_embed"):  # gather, not stream
        node = params.pop(name, None)
        if node is not None:
            leaves = jax.tree.leaves(node)
            gathered_rows += sum(  # one row per decoded token
                leaf.shape[-1] * leaf.dtype.itemsize for leaf in leaves)
    param_bytes = sum(leaf.size * leaf.dtype.itemsize
                      for leaf in jax.tree.leaves(params))
    hkv = getattr(model, "num_kv_heads", None) or model.num_heads
    head_dim = model.embed_dim // model.num_heads
    avg_prefix = prompt_len + new_tokens / 2
    itemsize = jnp.dtype(model.dtype).itemsize
    kv_bytes = 2 * model.depth * hkv * head_dim * itemsize * avg_prefix
    return bw * 1e9 / (param_bytes + gathered_rows + kv_bytes)


def _bench_generate(model, variables, batch: int, prompt_len: int,
                    new_tokens: int, n_repeats: int = 3,
                    param_transform=None):
    """(median tokens/s, spread_pct) over ``n_repeats`` timed runs —
    the artifact-discipline shape (median headline + drift-detecting
    spread; `pddl_tpu/utils/bench_artifact.py`)."""
    prompt = jax.random.randint(jax.random.key(0), (batch, prompt_len),
                                0, model.vocab_size)
    kw = dict(max_new_tokens=new_tokens, param_transform=param_transform)
    out = generate(model, variables, prompt, **kw)
    int(out[0, -1])  # scalar fetch = sync under tunneled transports
    stats = timed_stats(
        lambda: generate(model, variables, prompt, **kw),
        lambda o: int(o[0, -1]), n_repeats=n_repeats)
    return batch * new_tokens / stats["median_s"], stats["spread_pct"]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--prompt-len", type=int, default=64)
    p.add_argument("--new-tokens", type=int, default=256)
    p.add_argument("--models", default="",
                   help="comma-joined subset of gpt_small,llama_small,"
                        "llama_1b (default: the two smalls)")
    p.add_argument("--int8", action="store_true",
                   help="also measure weight-only int8 storage "
                        "(ops/quant.py) — halves the B1 weight-read "
                        "floor IF XLA streams the int8 (the comparison "
                        "against the int8 roofline is the check)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed repetitions per series (>= 3; median is "
                        "the headline, spread the drift detector)")
    p.add_argument("--out", default="")
    args = p.parse_args()

    # param_dtype=bf16: the serving configuration — decode is weight-
    # bandwidth-bound, so f32 storage would halve throughput for nothing.
    all_models = {
        "gpt_small": lambda: GPT_Small(vocab_size=50257, max_len=1024,
                                       dtype=jnp.bfloat16,
                                       param_dtype=jnp.bfloat16),
        "llama_small": lambda: Llama_Small(vocab_size=32000, max_len=1024,
                                           dtype=jnp.bfloat16,
                                           param_dtype=jnp.bfloat16),
        # The 1B-on-one-chip headline's serving twin (2.2 GB of bf16
        # weights: B1 decode is purely weight-read-bound, the int8 case
        # that matters most).
        "llama_1b": lambda: Llama_1B(vocab_size=128256, max_len=1024,
                                     dtype=jnp.bfloat16,
                                     param_dtype=jnp.bfloat16),
    }
    names = args.models.split(",") if args.models else [
        "gpt_small", "llama_small"]
    unknown = set(names) - set(all_models)
    if unknown:
        raise SystemExit(f"unknown --models {sorted(unknown)}; "
                         f"choose from {sorted(all_models)}")
    models = {n: all_models[n]() for n in names}
    record = {
        "metric": "greedy_decode_new_tokens_per_sec",
        "unit": "tokens/sec/chip",
        "config": {"prompt_len": args.prompt_len,
                   "new_tokens": args.new_tokens, "dtype": "bfloat16"},
        "provenance": provenance(args.repeats),
        "results": {},
        "device": jax.devices()[0].device_kind,
    }
    for name, model in models.items():
        variables = jax.jit(model.init)(
            jax.random.key(0),
            jnp.zeros((1, args.prompt_len), jnp.int32), train=False)
        variables = {"params": variables["params"]}
        roof = _roofline_tokens_per_sec(model, variables,
                                        args.prompt_len, args.new_tokens)
        for batch in (1, 8):
            tps, spread = _bench_generate(model, variables, batch,
                                          args.prompt_len,
                                          args.new_tokens,
                                          n_repeats=args.repeats)
            record["results"][f"{name}_b{batch}"] = round(tps, 1)
            record["results"][f"{name}_b{batch}_spread_pct"] = round(
                spread, 2)
            if batch == 1 and roof is not None:
                record["results"][f"{name}_roofline_b1"] = round(roof, 1)
                record["results"][f"{name}_roofline_ratio_b1"] = round(
                    tps / roof, 3)
            print(f"{name} B{batch}: {tps:,.0f} new tokens/s "
                  f"(spread {spread:.1f}%)"
                  + (f" ({tps / roof:.0%} of {roof:,.0f} roofline)"
                     if batch == 1 and roof else ""),
                  file=sys.stderr, flush=True)
        if args.int8:
            from pddl_tpu.ops.quant import dequantize, quantize_int8

            qvars = {"params": quantize_int8(variables["params"])}
            # Same roofline formula over the STORED (int8) bytes: the
            # q-leaf dicts flatten to int8 + scale + dtype-carrier
            # leaves, so the weight-read numerator is what HBM actually
            # holds.
            roof8 = _roofline_tokens_per_sec(model, qvars,
                                             args.prompt_len,
                                             args.new_tokens)
            for batch in (1, 8):
                tps8, spread8 = _bench_generate(model, qvars, batch,
                                                args.prompt_len,
                                                args.new_tokens,
                                                n_repeats=args.repeats,
                                                param_transform=dequantize)
                record["results"][f"{name}_int8_b{batch}"] = round(tps8, 1)
                record["results"][f"{name}_int8_b{batch}_spread_pct"] = (
                    round(spread8, 2))
                if batch == 1 and roof8 is not None:
                    record["results"][f"{name}_int8_roofline_b1"] = round(
                        roof8, 1)
                    record["results"][f"{name}_int8_roofline_ratio_b1"] = (
                        round(tps8 / roof8, 3))
                print(f"{name} int8 B{batch}: {tps8:,.0f} new tokens/s "
                      f"(spread {spread8:.1f}%)"
                      + (f" ({tps8 / roof8:.0%} of {roof8:,.0f} int8 "
                         "roofline)" if batch == 1 and roof8 else ""),
                      file=sys.stderr, flush=True)

    line = json.dumps(record)
    print(line)
    if args.out:
        out_dir = os.path.dirname(args.out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
