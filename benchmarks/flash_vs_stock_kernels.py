"""Head-to-head: our flash kernel vs the JAX-shipped TPU attention kernels.

Answers the round-2 verdict's standing question about the flash kernel's
13%-of-bf16-peak efficiency at GPT shapes (head_dim=64): is the kernel
leaving performance on the table, or is that the hardware floor for dense
causal attention at this geometry? The comparison runs the same shape
through three implementations, timed identically (scalar-fetch sync — see
``benchmarks/attention_bench.py`` on why ``block_until_ready`` alone is
not a sync point under tunneled transports):

- ``ours``        — :func:`pddl_tpu.ops.attention.flash_attention`
- ``stock_flash`` — ``jax.experimental.pallas.ops.tpu.flash_attention``
- ``splash``      — ``jax.experimental.pallas.ops.tpu.splash_attention``
  (the production MaxText kernel, causal mask, no sharding)

Representative v5e result at the GPT-2-small training shape
(B8 H12 S2048 D64, bf16, causal) — committed under
``artifacts/gpt_bench/r03_kernel_head_to_head.json``:

    fwd:      ours 4.9 ms   stock_flash 11.0 ms   splash 13.1 ms
    fwd+bwd:  ours 9.4 ms   stock_flash 32.5 ms   splash 31.9 ms

Our kernel is 2.2x (forward) to 3.4x (train step's fwd+bwd) faster than
both stock kernels, so the measured 47.5% train-step MFU is a property
of dense causal attention at head_dim=64 on this generation, not of
this implementation.

    python benchmarks/flash_vs_stock_kernels.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp

from pddl_tpu.ops.attention import flash_attention


def _bench(op, q, k, v, iters: int = 30, grad: bool = False,
           reps: int = 3) -> float:
    if grad:
        # The fetched scalar must depend on dq AND dk AND dv: pallas calls
        # are pure at the jaxpr level, so an unused dk/dv would let JAX DCE
        # delete the whole dkv backward kernel and time only half the pass.
        f = jax.jit(lambda q, k, v: sum(
            g[0, 0, 0, 0].astype(jnp.float32) for g in jax.grad(
                lambda a, b, c: op(a, b, c).astype(jnp.float32).sum(),
                argnums=(0, 1, 2))(q, k, v)))
    else:
        f = jax.jit(lambda q, k, v: op(q, k, v)[0, 0, 0, 0].astype(jnp.float32))
    float(f(q, k, v))  # compile + sync
    # Best of `reps` batches: single-batch timing on the tunneled chip is
    # exposed to multi-ms transient slowdowns (observed ~30% run-to-run);
    # min-of-batches recovers the stable rate all impls are compared at.
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(q, k, v)
        float(out)  # scalar fetch drains the dispatch queue
        best = min(best, (time.perf_counter() - t0) / iters * 1e3)
    return best


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    B, H, S, D = args.batch, args.heads, args.seq, args.head_dim
    q, k, v = (jax.random.normal(jax.random.key(i), (B, H, S, D), jnp.bfloat16)
               for i in range(3))
    scale = D ** -0.5

    impls = {"ours": lambda q, k, v: flash_attention(q, k, v, causal=True)}

    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock_flash)
        impls["stock_flash"] = lambda q, k, v: stock_flash(q, k, v, causal=True)
    except ImportError:
        pass
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk, splash_attention_mask as sm)
        mask = sm.MultiHeadMask([sm.CausalMask((S, S)) for _ in range(H)])
        kernel = sk.make_splash_mha(mask=mask, head_shards=1, q_seq_shards=1)
        impls["splash"] = lambda q, k, v: jax.vmap(kernel)(q * scale, k, v)
    except ImportError:
        pass

    rec = {
        "shape": {"batch": B, "heads": H, "seq": S, "head_dim": D,
                  "dtype": "bfloat16", "causal": True},
        "device": jax.devices()[0].device_kind,
        "ms": {},
    }
    for name, op in impls.items():
        fwd = _bench(op, q, k, v)
        fb = _bench(op, q, k, v, grad=True)
        rec["ms"][name] = {"fwd": round(fwd, 2), "fwd_bwd": round(fb, 2)}
        print(f"{name:12s} fwd {fwd:6.2f} ms   fwd+bwd {fb:6.2f} ms", flush=True)

    line = json.dumps(rec)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
