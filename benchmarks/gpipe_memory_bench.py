"""GPipe's activation-memory envelope vs microbatch count (VERDICT r3 #8).

Measured law (this bench; see the committed artifact): at FIXED global
batch, the AD-derived backward saves one stage-internal activation set
per scan tick, and there are ``M + S - 1`` ticks of microbatches sized
``B/M`` — so the envelope is ``temp ≈ c · B · (M + S - 1) / M``, which
SHRINKS toward ``c·B`` as M grows. Raising M therefore improves the
bubble AND the memory at once; the folklore "GPipe memory grows with
microbatch count" applies only at fixed MICRObatch size (i.e. growing
the global batch with M). What actually caps model size under PP is the
constant ``c`` — every block-internal activation of the full global
batch — and that is what ``remat_stages`` attacks: per-tick
``jax.checkpoint`` of the stage call keeps only tick-boundary
microbatches and recomputes stage internals in the backward (~10x
measured reduction at every M).

Methodology: the full GPipeLlama train-step gradient is AOT-compiled per
(M, remat) on an 8-device ``data=2 x stage=4`` mesh and XLA's own
compiled-program memory analysis reports the TEMP allocation size — the
activation/workspace pool, exactly the thing that grows with M (params
and inputs are constant across the sweep). Runs on the fake CPU mesh
(the sharded program's buffer assignment is what's being measured, not
wall clock) — chip HBM stats corroborate the same law where a multi-chip
mesh exists.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/gpipe_memory_bench.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402


def temp_bytes(model, variables, tokens) -> int:
    """TEMP allocation of the compiled (loss, grad) step, bytes."""

    def loss_fn(params, tokens):
        logits = model.apply({"params": params}, tokens[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:]).mean()

    step = jax.jit(jax.value_and_grad(loss_fn))
    mem = step.lower(variables["params"], tokens).compile().memory_analysis()
    return int(mem.temp_size_in_bytes)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=129)  # 128 modeled positions
    p.add_argument("--out", default="")
    args = p.parse_args()

    from pddl_tpu.core.mesh import MeshConfig, build_mesh
    from pddl_tpu.models.llama import GPipeLlama

    mesh = build_mesh(MeshConfig(data=2, stage=4))
    tokens = jax.random.randint(jax.random.key(0), (args.batch, args.seq),
                                0, 256)

    results = {}
    for remat in (False, True):
        for m in (2, 4, 8, 16):
            model = GPipeLlama(
                vocab_size=256, n_stages=4, blocks_per_stage=2,
                n_microbatches=m, mesh=mesh, embed_dim=256, num_heads=8,
                num_kv_heads=4, remat_stages=remat)
            variables = model.init(jax.random.key(0), tokens[:, :-1])
            key = f"{'remat' if remat else 'plain'}_m{m}"
            results[key] = temp_bytes(model, variables, tokens)
            print(f"{key}: temp {results[key] / 1e6:.1f} MB",
                  file=sys.stderr, flush=True)

    # The law: fit temp ~= a + b * (M + S - 1)/M (tick count x microbatch
    # size at fixed global batch) for both variants.
    n_stages = 4

    def fit(prefix):
        ms = [2, 4, 8, 16]
        xs = [(m + n_stages - 1) / m for m in ms]
        ys = [results[f"{prefix}_m{m}"] for m in ms]
        n = len(ms)
        xb = sum(xs) / n
        yb = sum(ys) / n
        b = (sum((x - xb) * (y - yb) for x, y in zip(xs, ys))
             / sum((x - xb) ** 2 for x in xs))
        a = yb - b * xb
        resid = max(abs(a + b * x - y) / y for x, y in zip(xs, ys))
        return a, b, resid

    a_p, b_p, r_p = fit("plain")
    a_r, b_r, r_r = fit("remat")
    record = {
        "metric": "gpipe_train_step_temp_bytes_vs_microbatches",
        "unit": "bytes",
        "config": {"mesh": "data=2 x stage=4", "model": "GPipeLlama",
                   "embed_dim": 256, "blocks_per_stage": 2,
                   "batch": args.batch, "seq": args.seq,
                   "backend": jax.default_backend()},
        "results": results,
        "law": "temp ~= a + b*(M+S-1)/M at fixed global batch",
        "fit_plain": {"a": round(a_p), "b": round(b_p),
                      "max_rel_residual": round(r_p, 3)},
        "fit_remat": {"a": round(a_r), "b": round(b_r),
                      "max_rel_residual": round(r_r, 3)},
        "remat_reduction_per_m": {
            f"m{m}": round(results[f"plain_m{m}"] / results[f"remat_m{m}"],
                           1)
            for m in (2, 4, 8, 16)},
    }
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
