"""End-to-end GPT training throughput on one chip (tokens/sec, MFU).

The harness behind the architecture doc's long-context numbers
(v5e, GPT-2-small shape, B8 S2048 bf16 flash + fused-CE head; round 4
with the fused single-sweep attention backward: ~101k tokens/s, 50.6%
6ND MFU against the 197 TFLOP/s bf16 peak — chip-state variance of a
few percent per run is normal; decomposition of the remainder:
docs/ARCHITECTURE.md §7b, artifacts/gpt_bench/r04_b8_s2048.json).

Long context on ONE chip (``--remat dots``, round 4): S=8192 at ~48k
tokens/s, S=16384 at ~30k tokens/s (B1) — where the
materialized-scores attention could not even hold a single layer's S²
matrix (``r04_b1_s8192.json``, ``r04_b1_s16384.json``).

``--family llama`` benches the modern-decoder family at the same shape
(RoPE/SwiGLU/RMSNorm, GQA ``--kv-heads``, llama-tokenizer 32000 vocab):
125M params at B8 S2048 bf16 train at ~112.7k tokens/s/chip with the
GQA-native kernels — 145.4 vs GPT's 161.8 ms/step, pinned as
``artifacts/gpt_bench/r04_llama_b8_s2048.json`` vs ``r04_b8_s2048.json``.

    PYTHONPATH=. python benchmarks/gpt_train_bench.py [--seq 2048 --batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

from pddl_tpu.models.gpt import GPT, fused_lm_loss
from pddl_tpu.train.state import TrainState

V5E_BF16_PEAK_FLOPS = 197e12


def _write_record(path: str, record: dict) -> None:
    """The one artifact-writing convention (both legs use it)."""
    if not path:
        return
    out_dir = os.path.dirname(path)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(record, f, indent=1)


def _checkpoint_overhead_leg(args, state, jstep, tokens, targets) -> None:
    """Paired leg: the same N-step loop with verified step-granular
    checkpointing on vs off (`utils/bench_artifact.py` discipline:
    >=3 repeats, median + spread, provenance). The checkpointed leg
    pays what `CheckpointEveryN` pays in training: a host fetch of the
    state for per-leaf checksums at each save, the (async) Orbax write
    overlapping subsequent steps, and one wait at the end — against
    compute that keeps running between saves. Writes ONE JSON record
    (the `--out` artifact: `artifacts/gpt_bench/r10_train_faults.json`).
    """
    import shutil
    import tempfile

    from pddl_tpu.ckpt.checkpoint import Checkpointer
    from pddl_tpu.utils.bench_artifact import provenance, timed_stats

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="pddl_ckpt_bench_")
    holder = {"state": state}

    def run_clean():
        for _ in range(args.steps):
            holder["state"], loss = jstep(holder["state"], tokens, targets)
        return loss

    saves_per_repeat = args.steps // args.ckpt_every
    # ONE manager across repeats, warmed with a throwaway save: the
    # first Orbax save pays directory/manager setup that a long-running
    # training job amortizes to nothing — timing it would charge the
    # steady-state cadence for a one-time cost.
    ckpt = Checkpointer(ckpt_dir, max_to_keep=2, async_save=True)
    ckpt.save(holder["state"], force=True, checksum=True)
    ckpt.wait()

    def run_ckpt():
        for i in range(args.steps):
            holder["state"], loss = jstep(holder["state"], tokens,
                                          targets)
            if (i + 1) % args.ckpt_every == 0:
                ckpt.save(holder["state"], force=True, checksum=True)
        ckpt.wait()
        return loss

    sync = lambda loss: float(loss)  # noqa: E731 - scalar fetch = sync
    clean = timed_stats(run_clean, sync, n_repeats=args.repeats)
    ckpt_on = timed_stats(run_ckpt, sync, n_repeats=args.repeats)
    ckpt.close()
    if not args.ckpt_dir:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    B, S = args.batch, args.seq
    toks_clean = B * S * args.steps / clean["median_s"]
    toks_ckpt = B * S * args.steps / ckpt_on["median_s"]
    ratio = toks_ckpt / toks_clean
    n_params = sum(x.size for x in jax.tree.leaves(holder["state"].params))
    per_save_ms = ((ckpt_on["median_s"] - clean["median_s"])
                   / max(saves_per_repeat, 1) * 1e3)
    print(f"checkpoint-overhead ({n_params / 1e6:.0f}M params, "
          f"every {args.ckpt_every} of {args.steps} steps, "
          f"{args.repeats} repeats):", file=sys.stderr)
    print(f"  off: {toks_clean:,.0f} tok/s  on: {toks_ckpt:,.0f} tok/s "
          f"-> {ratio:.3f}x retained "
          f"(~{per_save_ms:.1f} ms amortized per verified save)",
          file=sys.stderr)
    record = {
        "metric": "train_checkpoint_throughput_retained",
        "value": round(ratio, 4),
        "unit": "ratio (checkpoint-every-N on / off, tokens/sec)",
        "clean_tokens_per_sec": round(toks_clean, 1),
        "checkpointed_tokens_per_sec": round(toks_ckpt, 1),
        "amortized_ms_per_save": round(per_save_ms, 2),
        "clean": clean,
        "checkpointed": ckpt_on,
        "config": {"family": args.family, "batch": B, "seq": S,
                   "depth": args.depth, "width": args.width,
                   "heads": args.heads, "vocab": args.vocab,
                   "params_m": round(n_params / 1e6, 1),
                   "attention": args.attention,
                   "steps": args.steps, "ckpt_every": args.ckpt_every,
                   "saves_per_repeat": saves_per_repeat,
                   "checksums": True, "async_save": True},
        "device": jax.devices()[0].device_kind,
        "provenance": provenance(args.repeats),
    }
    print(json.dumps(record))
    _write_record(args.out, record)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--family", default="gpt", choices=["gpt", "llama"],
                   help="gpt: learned-pos/GELU/LayerNorm GPT-2 shape; "
                        "llama: RoPE/SwiGLU/RMSNorm with GQA "
                        "(--kv-heads), llama-tokenizer vocab default")
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--depth", type=int, default=12)
    p.add_argument("--width", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--kv-heads", type=int, default=4,
                   help="llama family only: grouped-query KV heads")
    p.add_argument("--intermediate", type=int, default=None,
                   help="llama family only: SwiGLU hidden dim "
                        "(default: the ~8E/3 convention)")
    p.add_argument("--vocab", type=int, default=None,
                   help="default: 50257 (gpt) / 32000 (llama)")
    p.add_argument("--experts", type=int, default=0,
                   help="llama family only: >0 routes every block's MLP "
                        "over this many SwiGLU experts (Mixtral-style)")
    p.add_argument("--moe-top-k", type=int, default=2,
                   help="experts per token (with --experts)")
    p.add_argument("--moe-capacity", type=float, default=2.0,
                   help="train capacity factor (with --experts)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--remat", default="none",
                   choices=["none", "dots", "full"],
                   help="activation checkpointing (long sequences: dots)")
    p.add_argument("--param-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="parameter storage dtype; bfloat16 halves "
                        "weight+optimizer HBM (how the 1B shape fits "
                        "one chip)")
    p.add_argument("--param-update", default="plain",
                   choices=["plain", "stochastic_round", "f32_master"],
                   help="bf16-storage update rule "
                        "(train/mixed_precision.py); the 1B headline "
                        "uses stochastic_round — same memory as plain, "
                        "f32-equivalent convergence (docs/CONVERGENCE.md)")
    p.add_argument("--chunk-size", type=int, default=None,
                   help="fused-CE vocab chunk (memory valve)")
    p.add_argument("--fused-ce", type=int, default=1,
                   help="1 (default): fused head+CE via fused_lm_loss; "
                        "0: materialized logits + sparse CE")
    p.add_argument("--attention", default="flash",
                   choices=["flash", "reference"],
                   help="training attention path (reference lets the "
                        "bench run on hosts whose jax lacks the Mosaic "
                        "kernel prerequisites, e.g. CPU CI)")
    p.add_argument("--checkpoint-overhead", action="store_true",
                   help="paired leg: the SAME step loop with verified "
                        "step-granular checkpointing (Checkpointer.save "
                        "with per-leaf checksums, CheckpointEveryN "
                        "cadence) on vs off, >=3 timed repeats each — "
                        "the cost of the crash-resilience layer "
                        "(docs/OPERATIONS.md 'Failure modes & recovery "
                        "(training)')")
    p.add_argument("--ckpt-every", type=int, default=5,
                   help="save cadence in steps for --checkpoint-overhead")
    p.add_argument("--repeats", type=int, default=5,
                   help="timed repeats per leg for --checkpoint-overhead")
    p.add_argument("--ckpt-dir", default="",
                   help="checkpoint directory for --checkpoint-overhead "
                        "(default: a temp dir)")
    p.add_argument("--out", default="",
                   help="also write the JSON record to this path")
    args = p.parse_args()

    if args.experts and args.family != "llama":
        # GPT's MoE knob exists but takes the module defaults (no
        # capacity/eval controls); benching it here would emit an
        # MoE-labeled record for a config the flags don't describe.
        p.error("--experts requires --family llama")
    if args.vocab is None:
        args.vocab = 50257 if args.family == "gpt" else 32000
    param_dtype = jnp.bfloat16 if args.param_dtype == "bfloat16" \
        else jnp.float32
    if args.family == "gpt":
        model = GPT(vocab_size=args.vocab, max_len=args.seq,
                    embed_dim=args.width, depth=args.depth,
                    num_heads=args.heads, attention=args.attention,
                    remat=args.remat, dtype=jnp.bfloat16,
                    param_dtype=param_dtype)
    else:
        from pddl_tpu.models.llama import Llama

        model = Llama(vocab_size=args.vocab, max_len=args.seq,
                      embed_dim=args.width, depth=args.depth,
                      num_heads=args.heads, num_kv_heads=args.kv_heads,
                      intermediate_dim=args.intermediate,
                      attention=args.attention, remat=args.remat,
                      moe_experts=args.experts, moe_top_k=args.moe_top_k,
                      moe_capacity_factor=args.moe_capacity,
                      dtype=jnp.bfloat16, param_dtype=param_dtype)
    B, S = args.batch, args.seq
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, args.vocab)
    targets = jax.random.randint(jax.random.key(1), (B, S), 0, args.vocab)
    tx = optax.adamw(1e-4)
    if args.param_update != "plain":
        from pddl_tpu.train.mixed_precision import wrap_param_update

        tx = wrap_param_update(tx, args.param_update)

    def init(rng):
        params = model.init(rng, tokens[:1], train=False)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          batch_stats={}, opt_state=tx.init(params))

    state = jax.jit(init)(jax.random.key(0))

    def step(state, tokens, targets):
        def loss_of(params):
            if args.fused_ce:
                # Fused head + CE (models/gpt.py fused_lm_loss): only
                # logsumexp rows cross the fwd/bwd boundary — head+CE
                # measured 33.7 vs 39.7 ms standalone, ~4.7 ms/step
                # end-to-end (the one-chunk default trades a transient
                # f32 logits chunk for speed; chunk_size < vocab is the
                # memory valve).
                return fused_lm_loss(model, {"params": params}, tokens,
                                     targets, train=True,
                                     chunk_size=args.chunk_size)
            logits = model.apply({"params": params}, tokens, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        return state.apply_gradients(tx, grads), loss

    jstep = jax.jit(step, donate_argnums=(0,))
    state, loss = jstep(state, tokens, targets)
    float(loss)  # scalar fetch = real sync under tunneled transports
    if args.checkpoint_overhead:
        _checkpoint_overhead_leg(args, state, jstep, tokens, targets)
        return
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = jstep(state, tokens, targets)
    float(loss)
    dt = (time.perf_counter() - t0) / args.steps

    toks = B * S / dt
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    # MoE: 6ND must count ACTIVE params per token — each token runs
    # top_k of the n experts, so expert weights contribute top_k/n of
    # their size (router + dense weights count fully). For dense models
    # n_active == n_params.
    expert_params = sum(
        leaf.size
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            state.params)[0]
        if "moe" in jax.tree_util.keystr(path)
        and "router" not in jax.tree_util.keystr(path))
    n_active = n_params - expert_params
    if args.experts:
        n_active += expert_params * args.moe_top_k // args.experts
    mfu = 6 * n_active * toks / V5E_BF16_PEAK_FLOPS
    # Human-readable lines on stderr, ONE JSON line on stdout (the
    # bench.py contract: callers may json.loads captured stdout).
    print(f"{n_params / 1e6:.0f}M params, B{B} S{S} bf16 "
          f"{args.remat} remat, fused_ce={bool(args.fused_ce)}:",
          file=sys.stderr)
    print(f"  {dt * 1e3:.1f} ms/step = {toks:,.0f} tokens/sec/chip",
          file=sys.stderr)
    print(f"  ~{mfu * 100:.0f}% MFU (6ND / {V5E_BF16_PEAK_FLOPS / 1e12:.0f}"
          " TFLOP/s v5e bf16 peak)", file=sys.stderr)
    gb = n_params / 1e9
    rounded = max(1, round(gb))
    # Integer tag only when honest (within 15%); 600M is "0.6b", not "1b".
    size_tag = ("small" if n_params < 5e8
                else f"{rounded}b" if abs(gb - rounded) / rounded <= 0.15
                else f"{gb:.1f}b")
    family_tag = (f"{args.family}_moe{args.experts}top{args.moe_top_k}"
                  if args.experts else args.family)
    record = {
        "metric": f"{family_tag}_{size_tag}_train_tokens_per_sec_per_chip",
        "value": round(toks, 1),
        "unit": "tokens/sec/chip",
        "mfu_6nd": round(mfu, 4),
        "ms_per_step": round(dt * 1e3, 2),
        "config": {"family": args.family, "batch": B, "seq": S,
                   "depth": args.depth,
                   "width": args.width, "heads": args.heads,
                   "vocab": args.vocab, "params_m": round(n_params / 1e6, 1),
                   "remat": args.remat, "fused_ce": bool(args.fused_ce),
                   "attention": "flash", "dtype": "bfloat16",
                   "param_dtype": args.param_dtype,
                   "param_update": args.param_update,
                   "chunk_size": args.chunk_size if args.fused_ce else None,
                   "steps": args.steps},
        "device": jax.devices()[0].device_kind,
    }
    if args.experts:
        record["config"]["experts"] = args.experts
        record["config"]["moe_top_k"] = args.moe_top_k
        record["config"]["moe_capacity_factor"] = args.moe_capacity
        record["config"]["params_active_m"] = round(n_active / 1e6, 1)
    if args.family == "llama":
        record["config"]["kv_heads"] = args.kv_heads
        # Record the RESOLVED SwiGLU width (the model's ~8E/3 convention
        # when the flag is unset) so the artifact is self-describing.
        record["config"]["intermediate"] = (
            args.intermediate
            if args.intermediate is not None
            else -(-(8 * args.width // 3) // 128) * 128)
    print(json.dumps(record))
    _write_record(args.out, record)


if __name__ == "__main__":
    main()
