"""Chunked large-vocab CE vs the materialized-logits loss (real chip).

The harness behind the numbers in ``ops/large_vocab.py`` /
``docs/ARCHITECTURE.md`` — measures loss+grad wall-clock and XLA's peak
temp allocation for both paths on a GPT-2-small-shape model.

    PYTHONPATH=. python benchmarks/large_vocab_bench.py [--chunk 4096]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import optax

from pddl_tpu.models.gpt import GPT
from pddl_tpu.ops.large_vocab import chunked_cross_entropy


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--vocab", type=int, default=50257)
    p.add_argument("--chunk", type=int, default=4096)
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()

    model = GPT(vocab_size=args.vocab, max_len=args.seq, embed_dim=768,
                depth=12, num_heads=12, attention="flash",
                dtype=jnp.bfloat16)
    B, S = args.batch, args.seq
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, args.vocab)
    targets = jax.random.randint(jax.random.key(1), (B, S), 0, args.vocab)
    params = jax.jit(
        lambda r: model.init(r, tokens[:1], train=False)["params"]
    )(jax.random.key(0))

    def loss_logits(params):
        logits = model.apply({"params": params}, tokens, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    def loss_chunked(params):
        _, state = model.apply(
            {"params": params}, tokens, train=True,
            capture_intermediates=lambda m, _: m.name == "ln_final",
        )
        feats = jax.tree.leaves(
            state["intermediates"]["ln_final"]["__call__"])[0]
        head = params["lm_head"]
        return chunked_cross_entropy(feats, head["kernel"], targets,
                                     head["bias"], chunk_size=args.chunk)

    for name, fn in (("logits ", loss_logits), ("chunked", loss_chunked)):
        g = jax.jit(jax.value_and_grad(fn))
        mem = g.lower(params).compile().memory_analysis()
        loss, _ = g(params)
        float(loss)  # scalar fetch = real sync under tunneled transports
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss, grads = g(params)
        float(loss)
        dt = (time.perf_counter() - t0) / args.steps
        print(f"{name}: loss {float(loss):.3f}  {dt * 1e3:7.1f} ms/step  "
              f"peak temp alloc {mem.temp_size_in_bytes / 1e9:.2f} GB")


if __name__ == "__main__":
    main()
