"""Llama-family train-step ablation: where the 42.8-vs-50.6 MFU gap lives.

Round 4 measured llama-small at 42.8% 6ND MFU vs GPT-small's 50.6% at
the identical B8/S2048 budget — while being FASTER in wall-clock (145.4
vs 161.8 ms/step). This is the §7b decomposition for the llama family
(the GPT twin is ``artifacts/gpt_bench/r03_ablation.json``), built from
in-situ marginal costs of real train-step programs:

- ``full``            — the shipped step (flash GQA + fused CE + adamw);
- ``no_optimizer``    — value_and_grad only, no update;
- ``head_ce``         — full − a variant whose loss is a feature-mean
                        (drops final norm + LM head + CE fwd/bwd);
- ``attention``       — full − a variant with the attention op stubbed
                        to identity (drops QK^T/PV and their backward;
                        q/k/v/o projections remain);
- ``depth slope``     — per-layer cost from depth 12 vs 6 (amortizes
                        embed/head/fixed costs out).

Plus the 6ND bookkeeping that explains the MFU arithmetic: llama's
smaller parameter count (GQA K/V, 32k vocab; N=125M vs GPT's 164M)
shrinks the 6ND numerator by 24% while the attention S² work — which
6ND does not credit AND which runs at the D=64 kernel's MXU ceiling
(§7b) — is identical between the families.

    PYTHONPATH=. python benchmarks/llama_ablation.py \
        [--out artifacts/gpt_bench/r05_llama_ablation.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

from pddl_tpu.models.gpt import fused_lm_loss
from pddl_tpu.models.llama import Llama
from pddl_tpu.train.state import TrainState

B, S = 8, 2048
VOCAB = 32000
V5E_BF16_PEAK = 197e12


def _model(depth=12):
    return Llama(vocab_size=VOCAB, max_len=S, embed_dim=768, depth=depth,
                 num_heads=12, num_kv_heads=4, attention="flash",
                 dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)


def _time_step(model, *, optimizer=True, loss="fused_ce", iters=10):
    tokens = jax.random.randint(jax.random.key(0), (B, S), 0, VOCAB)
    targets = jax.random.randint(jax.random.key(1), (B, S), 0, VOCAB)
    tx = optax.adamw(1e-4)

    def init(rng):
        params = model.init(rng, tokens[:1], train=False)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          batch_stats={}, opt_state=tx.init(params))

    state = jax.jit(init)(jax.random.key(0))

    def loss_of(params):
        if loss == "fused_ce":
            return fused_lm_loss(model, {"params": params}, tokens,
                                 targets, train=True)
        # feature-mean: traces embed+blocks+nothing else — the headless
        # variant (final norm, LM head, CE all gone fwd AND bwd).
        feats = model.apply({"params": params}, tokens, train=True,
                            features_only=True)
        return jnp.mean(feats.astype(jnp.float32))

    if optimizer:
        def step(state, _):
            l, grads = jax.value_and_grad(loss_of)(state.params)
            return state.apply_gradients(tx, grads), l
    else:
        def step(state, _):
            l, grads = jax.value_and_grad(loss_of)(state.params)
            # Consume the gradients: returning them unused would let XLA
            # dead-code-eliminate the whole backward and this variant
            # would silently time forward-only.
            gsum = sum(jnp.sum(g.astype(jnp.float32))
                       for g in jax.tree.leaves(grads))
            # 1e-20, not 0.0: a literal zero multiplier is foldable.
            return state, l + 1e-20 * gsum

    jstep = jax.jit(step, donate_argnums=(0,))
    state, l = jstep(state, None)
    float(l)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, l = jstep(state, None)
    float(l)
    return (time.perf_counter() - t0) / iters * 1e3  # ms/step


class _AttnStub:
    """Replace the flash attention op with identity in models.llama (it
    binds the name at import): q/k/v/o projections and RoPE remain, the
    S² kernel (fwd and bwd) disappears."""

    def __enter__(self):
        import pddl_tpu.models.llama as ml

        self._saved = ml.flash_attention

        def stub(q, k, v, **kw):
            # Consume k and v: with dead k/v, XLA dead-code-eliminates
            # the K/V projections and K's RoPE (fwd AND bwd) and the
            # variant under-counts — attributing projection cost to the
            # kernel. Same keep-alive trick as the no-optimizer variant
            # (1e-20, not 0.0: a literal zero multiplier is foldable).
            keep = (jnp.sum(k.astype(jnp.float32))
                    + jnp.sum(v.astype(jnp.float32)))
            return q + (1e-20 * keep).astype(q.dtype)

        ml.flash_attention = stub
        return self

    def __exit__(self, *exc):
        import pddl_tpu.models.llama as ml

        ml.flash_attention = self._saved
        return False


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="")
    args = p.parse_args()

    m12 = _model(12)
    n_params = sum(
        x.size for x in jax.tree.leaves(
            jax.eval_shape(lambda: m12.init(
                jax.random.key(0),
                jnp.zeros((1, S), jnp.int32), train=False))["params"]))

    full = _time_step(m12)
    no_opt = _time_step(m12, optimizer=False)
    headless = _time_step(m12, loss="features")
    with _AttnStub():
        no_attn = _time_step(m12)
    d6 = _time_step(_model(6))
    per_layer = (full - d6) / 6

    toks = B * S / (full / 1e3)
    mfu = 6 * n_params * toks / V5E_BF16_PEAK

    record = {
        "metric": "llama_small_train_step_ablation_ms",
        "config": {"batch": B, "seq": S, "depth": 12, "width": 768,
                   "heads": 12, "kv_heads": 4, "vocab": VOCAB,
                   "params_m": round(n_params / 1e6, 1),
                   "dtype": "bfloat16", "attention": "flash",
                   "fused_ce": True},
        "decomposition": {
            "full_step_ms": round(full, 2),
            "tokens_per_sec": round(toks, 0),
            "mfu_6nd": round(mfu, 4),
            "optimizer_in_situ_ms": round(full - no_opt, 2),
            "head_plus_ce_in_situ_ms": round(full - headless, 2),
            "attention_in_situ_ms": round(full - no_attn, 2),
            "per_layer_ms_depth_slope": round(per_layer, 3),
            "twelve_layers_ms": round(12 * per_layer, 2),
            "depth6_full_ms": round(d6, 2),
        },
        "device": jax.devices()[0].device_kind,
    }
    js = json.dumps(record, indent=1)
    print(js)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(record) + "\n")


if __name__ == "__main__":
    sys.exit(main() or 0)
