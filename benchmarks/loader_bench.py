"""Native C++ loader throughput vs a single-threaded Python reader.

Measures the input-pipeline side of SURVEY §2b C15: samples/sec from the
packed (PDL1) format through the threaded native runtime, against a
single-threaded Python reader doing the same shuffled access — the gap
is what the worker threads + prefetch ring buy *at the iterator alone*
(~1.5x page-cached on this image's CPU). The larger win in training is
that native assembly overlaps the device step and holds no GIL, while
the Python reader would serialize with the host loop.

    PYTHONPATH=. python benchmarks/loader_bench.py [--samples 20000]
"""

from __future__ import annotations

import argparse
import os
import struct
import tempfile
import time

import numpy as np

from pddl_tpu.data.native_loader import NativeLoader, write_packed


def python_reader(path: str, batch: int, seed: int = 0):
    """Single-threaded reference reader with the SAME access pattern as
    the native loader (seeded shuffled per-sample seeks), so the measured
    gap is the worker threads + prefetch ring, not sequential readahead.
    """
    with open(path, "rb") as f:
        magic, n, h, w, c, _ = struct.unpack("<IIHHHH", f.read(16))
        per = 4 + h * w * c
        order = np.random.default_rng(seed).permutation(n)
        images = np.empty((batch, h, w, c), np.uint8)
        labels = np.empty((batch,), np.int32)
        i = 0
        for idx in order:
            f.seek(16 + int(idx) * per)
            rec = f.read(per)
            labels[i] = struct.unpack_from("<i", rec)[0]
            images[i] = np.frombuffer(rec, np.uint8, h * w * c, 4).reshape(h, w, c)
            i += 1
            if i == batch:
                yield {"image": images, "label": labels}
                i = 0


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--samples", type=int, default=20000)
    p.add_argument("--size", type=int, default=64)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--workers", type=int, default=4)
    args = p.parse_args()

    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bench.pdl1")
        write_packed(
            path,
            rng.integers(0, 255, (args.samples, args.size, args.size, 3),
                         np.uint8),
            np.arange(args.samples),
        )
        mb = os.path.getsize(path) / 1e6

        t0 = time.perf_counter()
        n = sum(len(b["label"]) for b in python_reader(path, args.batch))
        t_py = time.perf_counter() - t0

        loader = NativeLoader([path], batch_size=args.batch, shuffle=True,
                              num_workers=args.workers)
        # Warm epoch (page cache), then the measured one.
        for _ in loader:
            pass
        t0 = time.perf_counter()
        n2 = sum(len(b["label"]) for b in loader)
        t_nat = time.perf_counter() - t0
        loader.close()

        print(f"file: {mb:.0f} MB, {args.samples} samples of "
              f"{args.size}x{args.size}x3")
        print(f"python 1-thread : {n / t_py:10.0f} samples/s")
        print(f"native {args.workers}-worker: {n2 / t_nat:10.0f} samples/s "
              f"({t_py / t_nat:.1f}x, shuffled)")


if __name__ == "__main__":
    main()
