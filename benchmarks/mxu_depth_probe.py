"""What does head_dim=64 cost on the MXU — and can head-packing recover it?

VERDICT r3 task 3a proposed "multi-head packing": contract over
``G·head_dim = 128`` by packing G=2 heads per MXU pass, on the theory
that head_dim=64 half-fills the 128-wide/deep systolic array.

This probe measures the real question with the real kernel: the flash
forward+backward at (H=12, D=64) vs (H=6, D=128) vs (H=24, D=32) — the
SAME total FLOPs, bytes, and score geometry, only the per-head depth
(score matmul contraction) and width (pv/backward output lanes) differ.
Representative v5e result (best-of-3, 50 chained-dispatch iterations,
scalar-fetch sync):

    H12 D64:  fwd 3.74 ms   fwd+bwd 7.08 ms
    H6  D128: fwd 2.69 ms   fwd+bwd 4.40 ms   (~1.6x faster)

So D=64 genuinely leaves ~40% of the attention step on the table
relative to a D=128 geometry. **Packing cannot recover it**, by
construction:

- The score matmul contracts over D. Packing two heads' q/k depth-wise
  computes ``q1·k1ᵀ + q2·k2ᵀ`` — the heads' scores SUM, which is wrong.
  Keeping them separate requires a block-diagonal (zero-padded) k-side
  operand, whose zero half performs the same number of MACs the idle
  depth wasted: neutral.
- The pv and backward matmuls have D on the 128-lane OUTPUT side.
  Packing two heads' v side by side needs a block-diagonal p
  ``[bq, 2·bk]`` — doubling the contraction depth exactly cancels the
  recovered width: neutral again, plus pack/select overhead.

Every rearrangement either mixes heads (invalid) or converts
idle-dimension waste into zero-MAC waste (neutral). head_dim is an
architecture parameter, not a kernel-schedule choice: the honest lever
is choosing D=128 model shapes (e.g. Llama-2/3 heads) where quality
allows. This measurement closes the r2/r3 "55% MFU" question: with the
fused single-sweep backward landed (see r04_kernel_head_to_head.json),
the remaining attention gap at D=64 is architectural.

    PYTHONPATH=. python benchmarks/mxu_depth_probe.py [--out out.json]
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp

# One timing methodology for all kernel benches (best-of-reps chained
# dispatch, scalar-fetch sync) — shared with the head-to-head bench so
# the two can never measure differently. Script-dir import: both live in
# benchmarks/ and run as scripts.
sys.path.insert(0, __file__.rsplit("/", 1)[0])
from flash_vs_stock_kernels import _bench as _bench_op  # noqa: E402

from pddl_tpu.ops.attention import flash_attention  # noqa: E402


def _bench(B, H, S, D, grad=False, iters=50, reps=3) -> float:
    q, k, v = (jax.random.normal(jax.random.key(i), (B, H, S, D),
                                 jnp.bfloat16) for i in range(3))
    return _bench_op(lambda q, k, v: flash_attention(q, k, v, causal=True),
                     q, k, v, iters=iters, grad=grad, reps=reps)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--out", default="")
    args = p.parse_args()

    B, S = args.batch, args.seq
    results = {}
    for H, D in ((12, 64), (6, 128), (24, 32)):
        fwd = _bench(B, H, S, D)
        fb = _bench(B, H, S, D, grad=True)
        results[f"h{H}_d{D}"] = {"fwd_ms": round(fwd, 2),
                                 "fwd_bwd_ms": round(fb, 2)}
        print(f"H{H} D{D}: fwd {fwd:.2f} ms   fwd+bwd {fb:.2f} ms",
              file=sys.stderr, flush=True)

    d64, d128 = results["h12_d64"], results["h6_d128"]
    record = {
        "metric": "flash_head_dim_equal_flops_probe",
        "unit": "ms",
        "config": {"batch": B, "seq": S, "dtype": "bfloat16",
                   "causal": True, "equal_total_flops": True},
        "results": results,
        "d64_over_d128_fwd_bwd": round(
            d64["fwd_bwd_ms"] / d128["fwd_bwd_ms"], 3),
        "verdict": ("D=64 pays ~this factor vs a D=128 geometry at equal "
                    "FLOPs; head-packing cannot recover it (block-diag "
                    "zero MACs == idle-dimension MACs — see module "
                    "docstring). Architectural, not a kernel-schedule "
                    "fix."),
        "device": jax.devices()[0].device_kind,
    }
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
