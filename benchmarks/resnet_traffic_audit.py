"""ResNet-50 train-step HBM-traffic audit: is ~330 MB/image real?

Round-3 answer to "audit the 330 MB/image op-by-op" — two parts:

1. **Empirical boundedness probe** (`--probe`): times three program
   variants on the real chip (bf16 fwd+bwd+adam, bf16 fwd-only, f32
   fwd+bwd+adam) against two predictors — XLA cost-analysis bytes at the
   819 GB/s HBM spec vs model FLOPs at peak. Measured (v5e, B256 I224):

       variant        measured   bytes-predicted   flops-predicted
       bf16 full      109.2 ms       103.4 ms          29.2 ms
       bf16 fwd-only   30.2 ms        25.5 ms           9.9 ms
       f32  full      187.8 ms       169.6 ms     29.8-120 ms

   Wall-clock tracks the BYTES model within 5-16% across all three
   programs (and not FLOPs, off by 1.6-3.7x) — the step is genuinely
   HBM-bandwidth-bound and the cost model's byte count is predictive of
   the hardware, validating bench.py's fixed 328.7 MB/image roofline
   denominator (bench.py:86-95).

2. **Instruction-level attribution** (`--attribute`): parses the
   optimized HLO and sums operand/result bytes per top-level
   instruction, grouped by op kind and by model layer. This accounts
   for ~80 MB/image; the remaining ~250 of the cost model's 330 lives
   INSIDE convolution/fusion internals — overlapping-window re-reads
   and multi-pass tile accesses that the instruction-boundary view
   cannot see but (per the probe) the hardware really pays.
   Instruction-level traffic concentrates in the high-resolution early
   stages (stage1 blocks ~8/4.8/4.8 MB/img, stem ~3.3) and the maxpool
   fwd/bwd pair (reduce_window + select-and-scatter + pad, ~9.6).

Conclusion recorded in docs/ARCHITECTURE.md §7c: at ~95% of the HBM
roofline with XLA already fusing BN/ReLU/residual chains into the convs,
the remaining byte levers (activation dtype below bf16, different
normalization, resolution/architecture changes) all change the trained
model — exactly the boundary bench.py:54-59 asserts. The audit turns
that assertion into a measured result.

    PYTHONPATH=. python benchmarks/resnet_traffic_audit.py --probe
    PYTHONPATH=. python benchmarks/resnet_traffic_audit.py --attribute
"""

from __future__ import annotations

import argparse
import collections
import re
import time

import jax
import jax.numpy as jnp
import optax

from pddl_tpu.models.resnet import ResNet50
from pddl_tpu.train.state import TrainState

B, I = 256, 224
HBM = 819e9
BF16_PEAK = 197e12


def _setup(dtype):
    model = ResNet50(num_classes=1000, dtype=dtype, stem="space_to_depth")
    images = jnp.zeros((B, I, I, 3), jnp.float32)
    labels = jnp.zeros((B,), jnp.int32)
    tx = optax.adam(1e-3)

    def init(rng):
        v = model.init(rng, images[:1], train=False)
        return TrainState(step=jnp.zeros((), jnp.int32), params=v["params"],
                          batch_stats=v.get("batch_stats", {}),
                          opt_state=tx.init(v["params"]))

    state = jax.jit(init)(jax.random.key(0))
    return model, state, images, labels, tx


def _step_fn(model, tx, fwd_only=False):
    def step(state, images, labels):
        def loss_of(params):
            (logits, upd) = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, upd

        if fwd_only:
            loss, _ = loss_of(state.params)
            return state, loss
        (loss, upd), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state.params)
        return state.apply_gradients(tx, grads, upd.get("batch_stats")), loss

    return step


def probe() -> None:
    for name, dtype, fwd_only, iters in (
        ("bf16 fwd+bwd+adam", jnp.bfloat16, False, 30),
        ("bf16 fwd only", jnp.bfloat16, True, 30),
        ("f32 fwd+bwd+adam", jnp.float32, False, 10),
    ):
        model, state, images, labels, tx = _setup(dtype)
        j = jax.jit(_step_fn(model, tx, fwd_only), donate_argnums=(0,))
        compiled = j.lower(state, images, labels).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # per-program list on some versions
            ca = ca[0]
        by, fl = ca.get("bytes accessed", 0.0), ca.get("flops", 0.0)
        state, loss = j(state, images, labels)
        float(loss)  # scalar fetch = genuine sync under the tunnel
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = j(state, images, labels)
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        print(f"{name:18s} {dt*1e3:7.1f} ms | bytes {by/1e9:6.1f} GB -> "
              f"{by/HBM*1e3:6.1f} ms at HBM spec | flops {fl/1e12:5.2f} TF "
              f"-> {fl/BF16_PEAK*1e3:5.1f} ms at bf16 peak")


_DT = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s8": 1, "u8": 1,
       "pred": 1, "s64": 8, "u64": 8, "s16": 2, "u16": 2}
_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")
_INST = re.compile(r"^\s*(?:ROOT )?%([\w\.\-]+) = (\([^)]*\)|\S+) ([\w\-]+)\(")
# Data movement pairs / structural ops: counting them would double-count
# the producer+consumer bytes already attributed to the compute ops.
_SKIP = {"parameter", "constant", "tuple", "get-tuple-element", "bitcast",
         "copy-start", "copy-done", "slice-start", "slice-done",
         "async-start", "async-done", "async-update"}


def _nbytes(shape: str) -> int:
    total = 0
    for dt, dims in _SHAPE.findall(shape):
        if dt not in _DT:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DT[dt]
    return total


def attribute() -> None:
    model, state, images, labels, tx = _setup(jnp.bfloat16)
    compiled = jax.jit(_step_fn(model, tx), donate_argnums=(0,)).lower(
        state, images, labels).compile()
    lines = compiled.as_text().split("\n")
    start = next(i for i, l in enumerate(lines) if l.startswith("ENTRY"))

    defs, rows = {}, []
    for ln in lines[start + 1:]:
        mm = _INST.match(ln)
        if not mm:
            continue
        name, shape, kind = mm.groups()
        defs[name] = _nbytes(shape)
        rows.append((name, defs[name], kind, ln))

    by_kind = collections.Counter()
    by_layer = collections.Counter()
    for name, obytes, kind, ln in rows:
        if kind in _SKIP:
            continue
        args = re.search(r" [\w\-]+\(([^)]*)\)", ln)
        rbytes = sum(defs.get(a, 0)
                     for a in re.findall(r"%([\w\.\-]+)", args.group(1))) \
            if args else 0
        t = obytes + rbytes
        meta = re.search(r'op_name="([^"]+)"', ln)
        if meta:
            opn = re.sub(r"jit\(\w+\)/", "", meta.group(1))
            seg = opn.split("/")
            by_kind[f"{kind}:{seg[-1][:30]}"] += t
            by_layer[next((s for s in seg
                           if re.match(r"stage\d|stem|head", s)),
                          "other")] += t
        else:
            by_kind[kind] += t
            by_layer["other"] += t

    total = sum(by_kind.values())
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    ca_total = ca.get("bytes accessed", 0.0)
    print(f"instruction-level traffic: {total/1e9:.1f} GB "
          f"({total/B/1e6:.1f} MB/img); cost-model total "
          f"{ca_total/1e9:.1f} GB ({ca_total/B/1e6:.1f} MB/img) — the "
          "difference lives inside conv/fusion internals (window "
          "re-reads), which the boundedness probe shows are real")
    print("-- by op kind:")
    for label, b in by_kind.most_common(12):
        print(f"{b/1e9:7.2f} GB {b/B/1e6:6.1f} MB/img  {label}")
    print("-- by layer group:")
    for lay, b in by_layer.most_common(12):
        print(f"{b/1e9:7.2f} GB {b/B/1e6:6.1f} MB/img  {lay}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--probe", action="store_true")
    p.add_argument("--attribute", action="store_true")
    a = p.parse_args()
    if not (a.probe or a.attribute):
        a.probe = a.attribute = True
    if a.probe:
        probe()
    if a.attribute:
        attribute()
