"""Online serving throughput: continuous batching vs run-to-completion.

`docs/SERVING.md` measures the single-request path; this bench measures
the ONLINE layer (`pddl_tpu/serve/`) the way a serving owner would:

1. **Head-to-head at 8 concurrent requests** — the same 8 synthetic
   requests served (a) sequentially by `generate()` (the strongest
   honest baseline: each request runs as ONE compiled decode-scan
   dispatch) and (b) through the engine's slot pool, where all 8 share
   every fused tick. The ratio is the continuous-batching lever.
2. **Poisson arrivals at 3 offered loads** (relative to the measured
   engine capacity) — open-loop traffic, the metric set an online
   system is judged by: aggregate tokens/s, p50/p99 TTFT (queue wait
   included), queue depth, slot occupancy, and shed load at the
   oversaturated point.

Weights are random (throughput does not depend on training); programs
are compiled at warmup and the bench records the engine's
compile-counts so the zero-recompile claim is visible in the artifact
(the test suite pins it; `tests/test_serve_engine.py`).

    PYTHONPATH=. python benchmarks/serve_bench.py \
        [--slots 8] [--out artifacts/gpt_bench/r06_serve.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.models.gpt import GPT, generate
from pddl_tpu.serve import QueueFull, SamplingParams, ServeEngine


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _make_requests(n: int, prompt_len: int, new_tokens: int, vocab: int,
                   seed: int = 0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
            for _ in range(n)]


def _sequential_baseline(model, variables, prompts, new_tokens: int):
    """Run-to-completion: each request is one generate() call (compiled
    once — same shapes reuse the cached decode scan)."""
    # Warm the compiled programs outside the timed window, like the
    # decode benches do.
    warm = generate(model, variables, jnp.asarray(prompts[0])[None],
                    new_tokens)
    jax.block_until_ready(warm)
    t0 = time.perf_counter()
    for p in prompts:
        out = generate(model, variables, jnp.asarray(p)[None], new_tokens)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return len(prompts) * new_tokens / dt


def _engine_concurrent(model, variables, prompts, new_tokens: int,
                       slots: int, prefill_len: int):
    """All requests submitted up front (closed-loop, max concurrency)."""
    eng = ServeEngine(model, variables, max_slots=slots,
                      prefill_len=prefill_len,
                      max_queue_depth=len(prompts) + 1)
    eng.warmup()
    t0 = time.perf_counter()
    handles = [eng.submit(p, new_tokens) for p in prompts]
    eng.run(max_steps=100000)
    dt = time.perf_counter() - t0
    assert all(h.done for h in handles)
    total = sum(len(h.tokens) for h in handles)
    assert total == len(prompts) * new_tokens
    return total / dt, eng


def _poisson_load(model, variables, offered_rps: float, n_requests: int,
                  prompt_len: int, new_tokens: int, vocab: int,
                  slots: int, prefill_len: int, max_queue_depth: int,
                  seed: int):
    """Open-loop Poisson arrivals at ``offered_rps`` requests/s; the
    engine runs in real time, so TTFT includes genuine queue wait."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_requests))
    prompts = _make_requests(n_requests, prompt_len, new_tokens, vocab,
                             seed=seed + 1)
    eng = ServeEngine(model, variables, max_slots=slots,
                      prefill_len=prefill_len,
                      max_queue_depth=max_queue_depth)
    eng.warmup()
    rejected = 0
    i = 0
    t0 = time.perf_counter()
    while i < n_requests or eng.has_work:
        now = time.perf_counter() - t0
        while i < n_requests and arrivals[i] <= now:
            try:
                eng.submit(prompts[i], new_tokens,
                           sampling=SamplingParams())
            except QueueFull:
                rejected += 1
            i += 1
        if eng.has_work:
            eng.step()
        elif i < n_requests:
            time.sleep(min(arrivals[i] - now, 0.01))
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    return {
        "offered_rps": round(offered_rps, 3),
        "offered_tokens_per_s": round(offered_rps * new_tokens, 1),
        "tokens_per_s": round(snap["tokens_emitted"] / wall, 1),
        "ttft_p50_s": round(snap["ttft_p50_s"], 4)
        if snap["ttft_p50_s"] is not None else None,
        "ttft_p99_s": round(snap["ttft_p99_s"], 4)
        if snap["ttft_p99_s"] is not None else None,
        "mean_queue_depth": round(snap["mean_queue_depth"], 2),
        "mean_slot_occupancy": round(snap["mean_slot_occupancy"], 3),
        "requests_finished": snap["requests_finished"],
        "requests_rejected_queue_full": rejected,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--embed-dim", type=int, default=256)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--prefill-len", type=int, default=64)
    p.add_argument("--concurrent", type=int, default=8,
                   help="requests in the head-to-head vs sequential "
                        "generate() (the acceptance ratio)")
    p.add_argument("--poisson-requests", type=int, default=24,
                   help="requests per Poisson load point")
    p.add_argument("--max-queue-depth", type=int, default=16)
    p.add_argument("--out", default="")
    args = p.parse_args()

    model = GPT(vocab_size=args.vocab, max_len=args.max_len,
                embed_dim=args.embed_dim, depth=args.depth,
                num_heads=args.heads, attention="reference")
    dummy = jnp.ones((1, args.prompt_len), jnp.int32)
    params = model.init(jax.random.key(0), dummy, train=False)["params"]
    variables = {"params": params}
    model_desc = (f"gpt {args.depth}x{args.embed_dim} "
                  f"(vocab {args.vocab}, max_len {args.max_len})")

    prompts = _make_requests(args.concurrent, args.prompt_len,
                             args.new_tokens, args.vocab)
    _log(f"head-to-head: {args.concurrent} requests x "
         f"{args.new_tokens} tokens, {model_desc}")
    seq_tps = _sequential_baseline(model, variables, prompts,
                                   args.new_tokens)
    eng_tps, eng = _engine_concurrent(model, variables, prompts,
                                      args.new_tokens, args.slots,
                                      args.prefill_len)
    counts = eng.compile_counts()
    speedup = eng_tps / seq_tps
    _log(f"sequential generate(): {seq_tps:,.0f} tok/s; engine "
         f"({args.slots} slots): {eng_tps:,.0f} tok/s ({speedup:.2f}x); "
         f"compile counts {counts}")

    # Offered loads relative to the measured closed-loop capacity:
    # comfortable, busy, oversaturated (the admission-control point).
    cap_rps = eng_tps / args.new_tokens
    record = {
        "metric": "online_serving_tokens_per_sec",
        "unit": "tokens/sec/chip",
        "config": {
            "model": model_desc,
            "slots": args.slots,
            "prefill_len": args.prefill_len,
            "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "concurrent": args.concurrent,
            "poisson_requests_per_load": args.poisson_requests,
            "max_queue_depth": args.max_queue_depth,
            "scheduler": "FCFS, prefill-token budget, typed QueueFull "
                         "shedding",
        },
        "results": {
            "concurrent_sequential_tokens_per_s": round(seq_tps, 1),
            "concurrent_engine_tokens_per_s": round(eng_tps, 1),
            "concurrent_speedup": round(speedup, 3),
            "engine_compile_counts_after_run": counts,
            "poisson": [],
        },
        "device": jax.devices()[0].device_kind,
    }
    for frac in (0.3, 0.6, 1.2):
        res = _poisson_load(
            model, variables, offered_rps=frac * cap_rps,
            n_requests=args.poisson_requests,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            vocab=args.vocab, slots=args.slots,
            prefill_len=args.prefill_len,
            max_queue_depth=args.max_queue_depth, seed=int(frac * 100))
        res["offered_fraction_of_capacity"] = frac
        record["results"]["poisson"].append(res)
        _log(f"poisson x{frac}: offered {res['offered_tokens_per_s']} "
             f"tok/s -> served {res['tokens_per_s']} tok/s, TTFT p50 "
             f"{res['ttft_p50_s']}s p99 {res['ttft_p99_s']}s, queue "
             f"{res['mean_queue_depth']}, occupancy "
             f"{res['mean_slot_occupancy']}, rejected "
             f"{res['requests_rejected_queue_full']}")

    line = json.dumps(record)
    print(line)
    if args.out:
        out_dir = os.path.dirname(args.out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
