"""Online serving throughput: continuous batching vs run-to-completion.

`docs/SERVING.md` measures the single-request path; this bench measures
the ONLINE layer (`pddl_tpu/serve/`) the way a serving owner would:

1. **Head-to-head at 8 concurrent requests** — the same 8 synthetic
   requests served (a) sequentially by `generate()` (the strongest
   honest baseline: each request runs as ONE compiled decode-scan
   dispatch) and (b) through the engine's slot pool, where all 8 share
   every fused tick. The ratio is the continuous-batching lever.
2. **Poisson arrivals at 3 offered loads** (relative to the measured
   engine capacity) — open-loop traffic, the metric set an online
   system is judged by: aggregate tokens/s, p50/p99 TTFT (queue wait
   included), queue depth, slot occupancy, and shed load at the
   oversaturated point.
3. **Shared-prefix workload** (`--prefix-shared-frac`, default 80%) —
   the prefix-cache lever (`pddl_tpu/serve/kvcache/`): the same
   requests through the engine with the radix prefix cache ON vs OFF;
   the TTFT ratio is what block-granular KV reuse buys when traffic
   shares a system prompt. Hit rate, prefill tokens saved, and the
   compile counts (zero recompiles with the cache on too) land in the
   artifact.
4. **Fault leg** (`--fault-rate`, default 1%; `--faults-only` for a
   standalone artifact) — the resilience tax (`pddl_tpu/serve/faults.py`
   + the engine retry/replay/degraded paths): the same closed-loop
   workload clean vs under a seeded 1%-per-dispatch injected fault mix
   (transient device errors + RESOURCE_EXHAUSTED at a tenth the rate).
   The headline is the PAIRED tok/s and mean-TTFT ratios — a
   fault-tolerant engine degrades gracefully (ratio near 1, every
   request terminal), a fail-stop one cliffs to zero. Retries, replays,
   degraded entries, and failed-request counts land in the artifact.
5. **Paged-attention leg** (`--paged-only`, standalone
   r13 artifact) — paged vs resident-row engines, PAIRED: live-stream
   KV bytes at matched total allocation (the duplicate-KV-elimination
   ratio) and prefix-hit admission TTFT head-to-head with the
   per-admission gather+insert copy wall (`admission_copy_us`) shown
   going to zero.
6. **Observability leg** (`--obs-only` for a standalone artifact) —
   the tracing tax (`pddl_tpu/obs/`): the same closed-loop workload
   with per-request tracing OFF (the default no-op tracer) vs ON
   (spans + JSONL sink). The paired ratio is the cost of turning the
   Dapper-style timeline on; the tracing-OFF number is directly
   comparable to the r08 fault-leg clean throughput (same config), so
   the artifact shows the instrumented engine did not regress the
   uninstrumented one. `--trace out.jsonl` additionally writes a full
   span/tick/metrics event log as a bench artifact.

7. **Fleet leg** (`--fleet-only`, `--fleet-replicas 2,4,8`) — the
   multi-replica tier (`pddl_tpu/serve/fleet/`): N real worker
   processes behind the health-checked router, open-loop Poisson at
   `--fleet-load` × N × the r08 single-engine clean baseline.
   Aggregate tok/s + p99 TTFT per N (the scaling curve), plus the
   failover leg at N ∈ {2, 4}: one replica SIGKILL'd mid-run (paired
   clean/killed waves) — throughput retained vs the 0.9·(N−1)/N
   floor, every request terminal, migrated survivor streams pinned
   token-exact against an oracle engine, zero recompiles on
   survivors.

8. **SLO/overload leg** (`--slo-only`) — overload robustness
   (ISSUE 7: priority/EDF/aging scheduler, chunked-prefill slicing,
   `serve/fleet/admission.py` brownout ladder): a trace-driven load —
   bursty multi-turn sessions over shared system prompts with
   heavy-tail output lengths, 35/15/50 interactive/batch/best_effort —
   at 2× measured fleet capacity through the admission-controlled
   router, PAIRED per repeat with an uncontended wave. Headlines:
   zero requests lost or hung (every one terminal: finished, DEADLINE,
   or shed-with-hint), interactive p99 TTFT ≤ 1.5× its uncontended
   value, best_effort absorbing ≥ 80% of the shedding, zero
   recompiles.

9. **Speculative leg** (`--spec-only`, standalone r17 artifact) —
   per-slot draft/verify inside the fused tick (ISSUE 12,
   `serve/engine.py spec_k`): the same closed-loop workload through a
   speculative engine vs the classic one-token tick, PAIRED per
   repeat, every stream in every wave asserted token-exact against
   the one-shot greedy `generate()` oracle. Headlines: the aggregate
   tok/s speedup at the default k, the acceptance-rate-vs-k curve,
   and a chaos leg (seeded faults + a 2-replica fleet kill
   mid-speculation) proving replayed/migrated speculative streams
   stay token-exact.

10. **Control-plane leg** (`--ctrlplane-only`, standalone r19
   artifact, ISSUE 14) — the durability tier
   (`serve/fleet/journal.py`, `transport.py`, gray machinery): (a)
   PAIRED clean vs 1%-injected wire-fault waves through real worker
   processes — throughput retained with every CRC reject counted and
   every stream token-exact (zero corrupt frames accepted); (b)
   router "SIGKILL" + `FleetRouter.recover` — WAL-rebuilt streams
   resume token-exact, with the recovery wall time (`recovery_s`)
   measured from recover() to every stream past its mirrored length;
   (c) gray-replica hedging ON vs OFF under an injected slow replica
   — interactive p99 TTFT, hedge wins counted, zero recompiles.

11. **Disaggregation leg** (`--disagg-only`, standalone r20 artifact,
   ISSUE 17) — prefill/decode role split (`serve/fleet/disagg.py`):
   the bursty LONG-PROMPT trace through a same-N pair of in-process
   fleets, unified vs split (prefill pool + decode pool,
   block-granular KV hand-off through the host tier), PAIRED per
   repeat. Decode-side latency is tick-attributed: each token is
   charged the wall duration of the engine step that produced it, so
   prefill admissions sharing a replica show up as latency on its
   co-resident decode streams. Headlines: decode-side p99 per-token
   latency ratio (`decode_p99_interference` ≤ 0.8× — the
   interference disaggregation exists to remove), aggregate tok/s
   retained ≥ 0.95×, `handoff_ms` per shipped chain, every stream
   token-exact across the two fleet shapes, zero recompiles on the
   decode replicas.

Every record embeds the engine's final `ServeMetrics.snapshot()`, so
artifacts carry tail latencies (TTFT/token-latency p50/p99), not just
throughput.

Timing follows the artifact discipline of
`pddl_tpu/utils/bench_artifact.py`: every headline number is a median
over `--repeats >= 3` runs with the spread recorded, and the record
carries the emitting tree's git commit.

Weights are random (throughput does not depend on training); programs
are compiled at warmup and the bench records the engine's
compile-counts so the zero-recompile claim is visible in the artifact
(the test suite pins it; `tests/test_serve_engine.py`).

    PYTHONPATH=. python benchmarks/serve_bench.py \
        [--slots 8] [--out artifacts/gpt_bench/r06_serve.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.models.gpt import GPT, generate
from pddl_tpu.obs import JsonlEventLog, RequestTracer
from pddl_tpu.serve import (
    FaultKind,
    FaultPlan,
    Priority,
    QueueFull,
    RequestState,
    SamplingParams,
    ServeEngine,
)
from pddl_tpu.utils.bench_artifact import median_spread, provenance


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _write_record(record: dict, out: str) -> None:
    """One artifact-write path for every leg combination: JSON line to
    stdout, plus the ``--out`` file when given."""
    line = json.dumps(record)
    print(line)
    if out:
        out_dir = os.path.dirname(out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(out, "w") as f:
            f.write(line + "\n")


def _log_fault_leg(faults: dict) -> None:
    _log(f"faults x{faults['fault_rate_per_dispatch']:.1%}: throughput "
         f"retained {faults['throughput_retained_x']}x (pairs "
         f"{faults['throughput_retained_per_pair']}), TTFT "
         f"{faults['clean_mean_ttft_s']}s -> "
         f"{faults['faulted_mean_ttft_s']}s, injected "
         f"{faults['faults_injected_total']}, recovery "
         f"{faults['recovery_counters_total']}")


def _make_requests(n: int, prompt_len: int, new_tokens: int, vocab: int,
                   seed: int = 0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
            for _ in range(n)]


def _sequential_baseline(model, variables, prompts, new_tokens: int,
                         repeats: int = 3):
    """Run-to-completion: each request is one generate() call (compiled
    once — same shapes reuse the cached decode scan). Median tok/s over
    ``repeats`` passes, spread recorded."""
    # Warm the compiled programs outside the timed window, like the
    # decode benches do.
    warm = generate(model, variables, jnp.asarray(prompts[0])[None],
                    new_tokens)
    jax.block_until_ready(warm)
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for p in prompts:
            out = generate(model, variables, jnp.asarray(p)[None],
                           new_tokens)
        jax.block_until_ready(out)
        samples.append(len(prompts) * new_tokens
                       / (time.perf_counter() - t0))
    return median_spread(samples)


def _engine_concurrent(model, variables, prompts, new_tokens: int,
                       slots: int, prefill_len: int, repeats: int = 3):
    """All requests submitted up front (closed-loop, max concurrency).
    The legacy head-to-head leg runs with the prefix cache OFF so the
    continuous-batching ratio stays comparable across rounds (prompts
    here are random — nothing to share anyway)."""
    eng = ServeEngine(model, variables, max_slots=slots,
                      prefill_len=prefill_len,
                      max_queue_depth=len(prompts) + 1,
                      prefix_cache_blocks=0)
    eng.warmup()
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=100000)
        dt = time.perf_counter() - t0
        assert all(h.done for h in handles)
        assert sum(len(h.tokens) for h in handles) \
            == len(prompts) * new_tokens
        samples.append(len(prompts) * new_tokens / dt)
    med, spread = median_spread(samples)
    return med, spread, eng


def _prefix_ttft_leg(model, variables, *, n_requests: int,
                     prompt_len: int, shared_frac: float, new_tokens: int,
                     slots: int, prefill_len: int, block_size: int,
                     chunk: int, vocab: int, repeats: int, seed: int = 3):
    """The prefix-cache lever: identical shared-prefix workload through
    the engine with the radix cache ON vs OFF; returns the artifact
    fragment (median mean-TTFT ratio over ``repeats``, hit telemetry,
    compile counts).

    The leg keeps ``n_requests <= slots`` and short decodes so the
    whole burst admits in one pass and TTFT measures ADMISSION — the
    prefill path the prefix cache actually shortens. (With requests
    queuing behind long decodes, TTFT is decode-capacity wait that no
    prefill lever can touch, and the ratio would understate the cache
    by construction.)"""
    rng = np.random.default_rng(seed)
    shared_len = int(prompt_len * shared_frac)
    shared = rng.integers(0, vocab, size=shared_len).astype(np.int32)
    prompts = [np.concatenate([
        shared,
        rng.integers(0, vocab, size=prompt_len - shared_len)
        .astype(np.int32)]) for _ in range(n_requests)]
    # Pool sized for the workload (one shared chain + each request's
    # unique suffix blocks, with slack) instead of the engine's generic
    # auto-sizing — the leg measures reuse, not eviction.
    pool_blocks = (2 + prompt_len // block_size
                   + n_requests * ((prompt_len - shared_len) // block_size
                                   + 2))

    def run_once(prefix_blocks):
        eng = ServeEngine(
            model, variables, max_slots=slots, prefill_len=prefill_len,
            max_queue_depth=n_requests + 1,
            prefix_cache_blocks=prefix_blocks,
            prefix_block_size=block_size,
            prefix_chunk=chunk if prefix_blocks else None)
        eng.warmup()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=100000)
        assert all(h.done for h in handles)
        ttfts = [h.ttft_s for h in handles]
        return float(np.mean(ttfts)), eng

    on_ttfts, off_ttfts, ratios = [], [], []
    eng_on = eng_off = None
    for _ in range(repeats):
        # PAIRED design: each repeat runs on/off back to back, and the
        # headline is the median of per-pair ratios — host load drift
        # hits both runs of a pair and cancels in the quotient, where
        # it would inflate the spread of the raw TTFT medians.
        t_on, eng_on = run_once(pool_blocks)
        t_off, eng_off = run_once(0)
        on_ttfts.append(t_on)
        off_ttfts.append(t_off)
        ratios.append(t_off / t_on)
    on_med, _ = median_spread(on_ttfts)
    off_med, _ = median_spread(off_ttfts)
    ratio_med, ratio_spread = median_spread(ratios)
    snap = eng_on.metrics.snapshot()
    return {
        "shared_frac": shared_frac,
        "prompt_len": prompt_len,
        "n_requests": n_requests,
        "prefix_block_size": block_size,
        "prefix_chunk": chunk,
        "mean_ttft_prefix_off_s": round(off_med, 5),
        "mean_ttft_prefix_on_s": round(on_med, 5),
        "ttft_reduction_x": round(ratio_med, 3),
        "ttft_reduction_per_pair": [round(r, 3) for r in ratios],
        "spread_pct": round(ratio_spread, 2),
        "prefix_hit_rate": round(snap["prefix_hit_rate"], 3),
        "prefill_tokens_saved": snap["prefill_tokens_saved"],
        "prefix_blocks_live": snap["prefix_blocks_live"],
        "prefix_evictions": snap["prefix_evictions"],
        "engine_compile_counts_prefix_on": eng_on.compile_counts(),
        "engine_compile_counts_prefix_off": eng_off.compile_counts(),
    }


def _paged_leg(model, variables, *, prompt_len: int, shared_frac: float,
               new_tokens: int, slots: int, prefill_len: int,
               block_size: int, chunk: int, vocab: int, repeats: int,
               seed: int = 17):
    """True paged attention vs the resident-row prefix cache, PAIRED.

    Two questions, both from the same warm shared-prefix workload with
    every slot live at once:

    1. **Capacity** — ``duplicate_kv_eliminated_x``: HBM holding the
       live streams' KV, row / paged, from
       ``ServeEngine.resident_kv_report()``. The row engine holds each
       slot's 80%-shared prefix privately plus one pool copy; the
       paged engine holds every DISTINCT block once, so the ratio is
       the duplicate KV paging deletes (the effective-capacity
       multiplier at this sharing level).
    2. **Admission** — prefix-HIT mean TTFT, paged vs row, per-pair
       ratio: the paged admission must not be slower than the gather
       path even though it runs the same suffix chunks (it drops the
       pool→row gather and the row→slot insert copy entirely);
       ``admission_copy_us`` (per-admission gather+insert dispatch
       wall from the telemetry ring) shows the copy cost that
       disappeared.

    The paged pool is sized to at most the row engine's TOTAL KV
    allocation (slot cache + pool), so the capacity ratio is measured
    at no-worse-than-identical pool bytes.
    """
    rng = np.random.default_rng(seed)
    shared_len = int(prompt_len * shared_frac)
    shared = rng.integers(0, vocab, size=shared_len).astype(np.int32)
    prompts = [np.concatenate([
        shared,
        rng.integers(0, vocab, size=prompt_len - shared_len)
        .astype(np.int32)]) for _ in range(slots)]
    row_pool_blocks = (2 + prompt_len // block_size
                      + slots * ((prompt_len - shared_len) // block_size
                                 + 2))
    max_len = model.max_len
    table_width = -(-max_len // block_size)
    paged_floor = slots * table_width + 1
    # Identical-or-smaller footprint: the row engine's slot cache holds
    # slots*max_len tokens and its pool row_pool_blocks*bs more; the
    # paged pool gets at most that token budget (floor-checked).
    paged_pool_blocks = max(
        paged_floor,
        (slots * max_len + row_pool_blocks * block_size) // block_size)

    def run_once(paged: bool):
        eng = ServeEngine(
            model, variables, max_slots=slots, prefill_len=prefill_len,
            max_queue_depth=2 * slots + 2,
            prefix_cache_blocks=(paged_pool_blocks if paged
                                 else row_pool_blocks),
            prefix_block_size=block_size, prefix_chunk=chunk,
            paged=paged)
        eng.warmup()
        # Wave 1 (cold): warms the cache; run to completion.
        w1 = [eng.submit(p, 4) for p in prompts]
        eng.run(max_steps=100000)
        assert all(h.done for h in w1)
        # Wave 2 (hit): every slot live on the warm prefix; snapshot
        # residency mid-decode, then finish.
        w2 = [eng.submit(p, new_tokens) for p in prompts]
        while eng.live_slots < slots:
            eng.step()
        for _ in range(2):
            eng.step()
        report = eng.resident_kv_report()
        report["blocks_shared"] = eng.blocks_shared
        eng.run(max_steps=100000)
        assert all(h.done for h in w2)
        ttft = float(np.mean([h.ttft_s for h in w2]))
        # Per-admission copy dispatch wall (gather + insert), from the
        # ring: the cost line paging deletes (0 by construction there).
        copy_s = sum(r["site_wall_s"].get("gather", 0.0)
                     + r["site_wall_s"].get("insert", 0.0)
                     for r in eng.telemetry.snapshot())
        admissions = max(eng.metrics.prefix_lookups, 1)
        return ttft, report, 1e6 * copy_s / admissions, eng

    paged_ttfts, row_ttfts, ratios, cap_ratios = [], [], [], []
    cap_paged = cap_row = None
    eng_paged = eng_row = None
    for _ in range(repeats):
        t_row, cap_row, copy_row_us, eng_row = run_once(False)
        t_paged, cap_paged, copy_paged_us, eng_paged = run_once(True)
        row_ttfts.append(t_row)
        paged_ttfts.append(t_paged)
        ratios.append(t_row / t_paged)
        cap_ratios.append(cap_row["kv_bytes_used"]
                          / max(cap_paged["kv_bytes_used"], 1))
    ttft_row_med, _ = median_spread(row_ttfts)
    ttft_paged_med, _ = median_spread(paged_ttfts)
    ratio_med, ratio_spread = median_spread(ratios)
    cap_med, cap_spread = median_spread(cap_ratios)
    snap = eng_paged.metrics.snapshot()
    return {
        "shared_frac": shared_frac,
        "prompt_len": prompt_len,
        "concurrent_streams": slots,
        "prefix_block_size": block_size,
        "paged_pool_blocks": paged_pool_blocks,
        "row_pool_blocks": row_pool_blocks,
        "kv_bytes_used_row": cap_row["kv_bytes_used"],
        "kv_bytes_used_paged": cap_paged["kv_bytes_used"],
        "kv_bytes_allocated_row": cap_row["kv_bytes_allocated"],
        "kv_bytes_allocated_paged": cap_paged["kv_bytes_allocated"],
        "tokens_resident": cap_paged["tokens_resident"],
        "duplicate_kv_eliminated_x": round(cap_med, 3),
        "duplicate_kv_eliminated_per_pair": [round(r, 3)
                                             for r in cap_ratios],
        "duplicate_kv_spread_pct": round(cap_spread, 2),
        "effective_cached_tokens_per_byte_row": round(
            cap_row["tokens_resident"]
            / max(cap_row["kv_bytes_used"], 1), 9),
        "effective_cached_tokens_per_byte_paged": round(
            cap_paged["tokens_resident"]
            / max(cap_paged["kv_bytes_used"], 1), 9),
        "hit_admission_ttft_row_s": round(ttft_row_med, 5),
        "hit_admission_ttft_paged_s": round(ttft_paged_med, 5),
        "hit_admission_speedup_x": round(ratio_med, 3),
        "hit_admission_speedup_per_pair": [round(r, 3) for r in ratios],
        "spread_pct": round(ratio_spread, 2),
        "admission_copy_us_row": round(copy_row_us, 1),
        "admission_copy_us_paged": round(copy_paged_us, 1),
        "blocks_shared_live": cap_paged["blocks_shared"],
        "copy_bytes_avoided": snap["copy_bytes_avoided"],
        "prefix_hit_rate": round(snap["prefix_hit_rate"], 3),
        "engine_compile_counts_paged": eng_paged.compile_counts(),
        "engine_compile_counts_row": eng_row.compile_counts(),
    }


def _tenant_leg(model, variables, *, n_requests: int, prompt_len: int,
                new_tokens: int, slots: int, prefill_len: int,
                n_adapters: int, vocab: int, repeats: int,
                seed: int = 23):
    """Multi-tenant serving (ISSUE 9, `serve/tenant/`), three headlines:

    1. **Memory elimination** — ``merged_copy_eliminated_x``: serving N
       tenants the naive way means N merged model copies in HBM
       (``N x base params``); the paged adapter pool serves them from
       ONE base copy plus fixed-shape factor pools. The ratio is
       arithmetic over real allocated sizes (deterministic — no
       repeats needed), the platform-economics headline.
    2. **Mixed-tenant throughput** — ``tenant_throughput_retained_x``:
       the same closed-loop workload through (a) a tenant engine with
       requests spread over ``n_adapters`` adapters plus constrained +
       unconstrained + no-adapter slots sharing every fused tick, and
       (b) a PLAIN engine (the r13-baseline program set) — PAIRED per
       repeat. Near-1 means per-request tenancy rides the batch almost
       free; also reported as the absolute ``mixed_tenant_tok_s``.
    3. **Constrained-decode overhead** — ``mask_overhead_x``: the same
       tenant engine serving an ALL-constrained wave vs an
       all-unconstrained one (identical token counts: the grammar is a
       fixed-length digit chain, so every stream emits exactly
       ``new_tokens``), paired per repeat. The mask path costs one
       ``[S, V]`` where + the FSM advance per token.
    """
    from pddl_tpu.serve import AdapterRegistry, TenantConfig

    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
               for _ in range(n_requests)]
    # Grammar vocabulary: token id i -> a digit character for the first
    # ten ids (the constrained wave's language), one unmatched filler
    # character beyond — constrained streams then emit digit tokens
    # only, unconstrained ones roam the whole vocab.
    token_strings = [str(i) if i < 10 else chr(0x100 + i)
                     for i in range(vocab)]
    digit_chain = {"kind": "regex", "pattern": "[0-9]" * new_tokens}

    # Warm the constraint automaton OUTSIDE the timed windows: spec
    # compilation is one-time per (spec, vocabulary) PROCESS-wide
    # (`grammar._FSM_CACHE`), amortized over every request/engine like
    # program compilation — the same exclusion discipline as warmup().
    from pddl_tpu.serve.tenant import compile_constraint
    compile_constraint(digit_chain, token_strings)

    def registry():
        reg = AdapterRegistry(model.embed_dim, model.vocab_size, rank=8)
        for i in range(n_adapters):
            reg.register_random(f"tenant{i}", seed=300 + i, scale=0.05)
        return reg

    def tenant_engine():
        return ServeEngine(
            model, variables, max_slots=slots, prefill_len=prefill_len,
            max_queue_depth=n_requests + 1,
            tenant=TenantConfig(registry=registry(),
                                adapter_pool_slots=slots + n_adapters + 1,
                                token_strings=token_strings))

    def run_wave(eng, submits):
        t0 = time.perf_counter()
        handles = [eng.submit(p, new_tokens, **kw) for p, kw in submits]
        eng.run(max_steps=200000)
        dt = time.perf_counter() - t0
        assert all(h.done for h in handles), "engine failed to drain"
        delivered = sum(len(h.tokens) for h in handles)
        return delivered / dt

    def mixed_submits():
        out = []
        for i, p in enumerate(prompts):
            kw = {}
            if i % 4 != 3:  # 3 of 4 requests are adapted
                kw["adapter"] = f"tenant{i % n_adapters}"
            if i % 4 == 1:  # every 4th is ALSO grammar-constrained
                kw["constraint"] = digit_chain
            out.append((p, kw))
        return out

    # --- headline 1: arithmetic over real allocated sizes (the pool
    # is `pool_rows` rows of `AdapterRegistry.adapter_nbytes` each —
    # no throwaway engine needed, and nothing extra stays resident
    # across the timed waves below).
    base_bytes = sum(int(leaf.size) * leaf.dtype.itemsize
                     for leaf in jax.tree.leaves(variables["params"]))
    pool_bytes = (slots + n_adapters + 1) * registry().adapter_nbytes
    merged_eliminated = (n_adapters * base_bytes) \
        / (base_bytes + pool_bytes)

    # FOUR resident engines, each reused for every repeat of its arm
    # (the engines are built for sustained traffic — waves re-admit
    # into free slots): the arms of a pair then run SECONDS apart
    # instead of across two ~30 s engine builds, so host-load drift
    # cancels in the quotients. One UNTIMED wave per engine first puts
    # all four in the same steady state (programs compiled, prefix
    # caches warm on these exact prompts, adapters resident).
    eng_t = tenant_engine()
    eng_p = ServeEngine(model, variables, max_slots=slots,
                        prefill_len=prefill_len,
                        max_queue_depth=n_requests + 1)
    eng_u = tenant_engine()
    eng_c = tenant_engine()
    plain_wave = [(p, {}) for p in prompts]
    con_wave = [(p, {"constraint": digit_chain}) for p in prompts]
    for eng, wave in ((eng_t, mixed_submits()), (eng_p, plain_wave),
                      (eng_u, plain_wave), (eng_c, con_wave)):
        eng.warmup()
        run_wave(eng, wave)

    tenant_tps, plain_tps, retained = [], [], []
    con_tps, unc_tps, mask_over = [], [], []
    for _ in range(repeats):
        # PAIRED per repeat (host drift cancels in each quotient).
        tps_t = run_wave(eng_t, mixed_submits())
        tps_p = run_wave(eng_p, plain_wave)
        tenant_tps.append(tps_t)
        plain_tps.append(tps_p)
        retained.append(tps_t / tps_p)
        tps_u = run_wave(eng_u, plain_wave)
        tps_c = run_wave(eng_c, con_wave)
        unc_tps.append(tps_u)
        con_tps.append(tps_c)
        mask_over.append(tps_u / tps_c)
    tps_med, tps_spread = median_spread(tenant_tps)
    ret_med, ret_spread = median_spread(retained)
    mask_med, mask_spread = median_spread(mask_over)
    snap = eng_t.metrics.snapshot()
    return {
        "n_adapters": n_adapters,
        "n_requests": n_requests,
        "adapter_rank": 8,
        "base_params_bytes": base_bytes,
        "adapter_pool_bytes": pool_bytes,
        "merged_copy_eliminated_x": round(merged_eliminated, 3),
        "mixed_tenant_tok_s": round(tps_med, 1),
        "mixed_tenant_tok_s_spread_pct": round(tps_spread, 2),
        "plain_engine_tok_s": round(median_spread(plain_tps)[0], 1),
        "tenant_throughput_retained_x": round(ret_med, 3),
        "tenant_retained_per_pair": [round(r, 3) for r in retained],
        "tenant_retained_spread_pct": round(ret_spread, 2),
        "constrained_tok_s": round(median_spread(con_tps)[0], 1),
        "unconstrained_tok_s": round(median_spread(unc_tps)[0], 1),
        "mask_overhead_x": round(mask_med, 3),
        "mask_overhead_per_pair": [round(r, 3) for r in mask_over],
        "mask_overhead_spread_pct": round(mask_spread, 2),
        "adapter_hit_rate": round(snap["adapter_hit_rate"], 3)
        if snap["adapter_hit_rate"] is not None else None,
        "adapter_loads": snap["adapter_loads"],
        "adapter_evictions": snap["adapter_evictions"],
        "constrained_requests": snap["constrained_requests"],
        "requests_grammar_complete": snap["requests_grammar_complete"],
        "engine_compile_counts_tenant": eng_t.compile_counts(),
    }


def _spec_leg(model, variables, *, n_requests: int, prompt_len: int,
              new_tokens: int, slots: int, prefill_len: int,
              spec_k: int, k_values, vocab: int, repeats: int,
              chaos_seeds=(0, 1, 2), seed: int = 23):
    """Speculative serving vs the classic one-token tick (ISSUE 12):
    the SAME closed-loop workload through a ``spec_k`` engine and a
    plain engine, PAIRED per repeat (host drift cancels in the
    quotient). Every stream in every wave is asserted token-exact
    against the one-shot greedy ``generate()`` oracle — speculation
    changes the tick count, never a token. Also records the
    acceptance-rate-vs-k curve (one wave per k) and a chaos leg:
    seeded mixed faults on the speculative engine plus a 2-replica
    fleet kill mid-speculation, all streams token-exact vs the
    non-speculative oracle."""
    prompts = _make_requests(n_requests, prompt_len, new_tokens, vocab,
                             seed=seed)
    refs = []
    for p in prompts:
        out = generate(model, variables, jnp.asarray(p)[None],
                       new_tokens)
        refs.append(np.asarray(out)[0, len(p):].tolist())

    def build(k, fault_plan=None):
        return ServeEngine(model, variables, max_slots=slots,
                           prefill_len=prefill_len,
                           max_queue_depth=n_requests + 1,
                           prefix_cache_blocks=0, spec_k=k,
                           fault_plan=fault_plan,
                           backoff_sleep=lambda s: None)

    def run_wave(eng):
        t0 = time.perf_counter()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=200000)
        dt = time.perf_counter() - t0
        assert all(h.done for h in handles)
        for h, ref in zip(handles, refs):
            assert h.tokens == ref, "speculative stream diverged"
        return n_requests * new_tokens / dt

    # Paired headline waves at the default k.
    spec_samples, base_samples, ratios = [], [], []
    spec_eng = base_eng = None
    for _ in range(repeats):
        spec_eng = build(spec_k)
        spec_eng.warmup()
        s_tps = run_wave(spec_eng)
        base_eng = build(0)
        base_eng.warmup()
        b_tps = run_wave(base_eng)
        spec_samples.append(s_tps)
        base_samples.append(b_tps)
        ratios.append(s_tps / b_tps)
    spec_med, spec_spread = median_spread(spec_samples)
    base_med, _ = median_spread(base_samples)
    ratio_med, ratio_spread = median_spread(ratios)
    snap = spec_eng.metrics.snapshot()

    # Acceptance-rate-vs-k curve: one wave per k (token-exactness
    # asserted inside run_wave for every point).
    curve = []
    for k in k_values:
        eng = build(k)
        eng.warmup()
        tps = run_wave(eng)
        ks = eng.metrics.snapshot()
        total = n_requests * new_tokens
        curve.append({
            "k": k,
            "acceptance_rate": round(ks["spec_acceptance_rate"] or 0.0,
                                     4),
            "spec_tok_s": round(tps, 1),
            "tokens_per_tick": round(total / max(ks["spec_ticks"], 1),
                                     3),
        })

    # Chaos leg: (a) seeded mixed faults through the speculative
    # engine — replayed speculative streams token-exact vs the oracle;
    # (b) a 2-replica speculative fleet with a kill mid-speculation —
    # live-migrated streams token-exact on the survivor.
    from pddl_tpu.serve.fleet import FleetRouter, LocalReplica

    chaos_requests = 0
    chaos_replays = 0
    chaos_migrated = 0
    for cs in chaos_seeds:
        plan = FaultPlan(seed=cs, sleep_fn=lambda s: None,
                         transient_rate=0.04, oom_rate=0.01,
                         max_random_injections=16)
        eng = build(spec_k, fault_plan=plan)
        eng.warmup()
        handles = [eng.submit(p, new_tokens) for p in prompts[:slots]]
        eng.run(max_steps=200000)
        for h, ref in zip(handles, refs[:slots]):
            assert h.done and h.tokens == ref, \
                "chaos: replayed speculative stream diverged"
        chaos_requests += len(handles)
        chaos_replays += eng.metrics.replays

        plans = [FaultPlan(sleep_fn=lambda s: None) for _ in range(2)]
        reps = [LocalReplica(i, (lambda pl: lambda: build(spec_k, pl))(
            plans[i])) for i in range(2)]
        fleet = FleetRouter(reps, affinity_block_size=8,
                            affinity_blocks=1, respawn=False)
        fh = [fleet.submit(p, new_tokens) for p in prompts[:4]]
        for _ in range(2):
            fleet.step()
        victim = max(fleet.replicas, key=lambda s: s.load)
        plans[victim.replica_id]._sched[
            (victim.driver.engine._step_idx, "verify")] = [FaultKind.KILL]
        fleet.run(max_steps=200000)
        for h, ref in zip(fh, refs[:4]):
            assert h.done and h.tokens == ref, \
                "chaos: migrated speculative stream diverged"
        chaos_requests += len(fh)
        chaos_migrated += fleet.metrics.requests_migrated

    return {
        "n_requests": n_requests,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "spec_k": spec_k,
        "baseline_tok_s": round(base_med, 1),
        "spec_tok_s": round(spec_med, 1),
        "spec_tok_s_spread_pct": round(spec_spread, 2),
        "spec_speedup_x": round(ratio_med, 3),
        "spec_speedup_per_pair": [round(r, 3) for r in ratios],
        "spread_pct": round(ratio_spread, 2),
        "acceptance_rate": round(snap["spec_acceptance_rate"] or 0.0, 4),
        "tokens_per_tick": round(
            n_requests * new_tokens / max(snap["spec_ticks"], 1), 3),
        "acceptance_curve": curve,
        "all_streams_token_exact": True,  # asserted in every wave above
        "chaos": {
            "seeds": list(chaos_seeds),
            "requests_token_exact": chaos_requests,
            "replays": chaos_replays,
            "requests_migrated": chaos_migrated,
        },
        "engine_compile_counts_spec": spec_eng.compile_counts(),
        "engine_compile_counts_baseline": base_eng.compile_counts(),
        "serve_metrics_snapshot": snap,
    }


def _tier_leg(model, variables, *, repeats: int, mults=(4, 8, 16, 32),
              seed: int = 31):
    """Tiered KV cache vs the r13 evict-and-recompute baseline
    (ISSUE 13), PAIRED at working sets 4-32x the device pool.

    A Zipf-skewed closed-loop trace over ``mult * pool_prompts``
    distinct prefixes, 4 requests per prefix on average AT EVERY
    sweep point (the revisit fraction is the tier's whole lever — at
    2 the compulsory first visits drown it and the 4x point loses to
    its own transfer overhead; a flat cap would thin it back out as
    the sweep widens): the device pool holds ~2 prompts' chains, so at 4x the
    tail already spills and at 32x almost every revisit would
    recompute without the tier. The host byte budget is sized to the
    WORKING SET (the runbook's sizing rule) so the comparison isolates
    the tier, not its own eviction. Both sides of a pair replay the
    IDENTICAL request order, so the Zipf draw cancels in the ratio;
    TTFT is measured closed-loop (one request live at a time), i.e.
    pure admission — the path promotion shortens.

    Sizing note (the r16/r17 sized-worker discipline, inverted): the
    tier's lever is prefill COMPUTE avoided, so the leg needs a model
    where recomputing a prompt costs meaningfully more than one H2D
    block scatter — the default 4x256 with 384-token prompts (~30 ms
    a prefill on the reference container), and a COARSE 48-token
    block so a demotion is 8 slice reads, not 48. On a toy model the
    transfer overhead dominates and the tier rightly loses — that
    regime is what ``min_chain_blocks`` and a zero budget are for."""
    bs, prompt_len, prefill_len, chunk = 48, 384, 384, 96
    blocks_per_prompt = prompt_len // bs
    pool_prompts = 2
    pool_blocks = pool_prompts * blocks_per_prompt + 1
    # K+V bytes per block: 2 leaves x embed x f32 x depth x block_size.
    kv_block_bytes = 2 * model.embed_dim * 4 * model.depth * bs

    def run_once(tier_bytes, prefixes, order):
        eng = ServeEngine(
            model, variables, max_slots=2, prefill_len=prefill_len,
            max_queue_depth=4, prefix_cache_blocks=pool_blocks,
            prefix_block_size=bs, prefix_chunk=chunk,
            host_tier=tier_bytes)
        eng.warmup()
        ttfts = []
        for idx in order:
            h = eng.submit(prefixes[idx], 2)
            eng.run(max_steps=10000)
            assert h.done
            ttfts.append(h.ttft_s)
        return float(np.mean(ttfts)), eng

    # One UNTIMED warm pair first: the tiered side runs first inside
    # every timed pair, so process-wide one-time costs (eager-op
    # caches, the persistent compile cache, numpy import paths) would
    # otherwise all land on the first pair's tiered TTFT and flip it
    # against a bound the steady state clears comfortably.
    wrng = np.random.default_rng(seed - 1)
    wprefixes = [wrng.integers(0, model.vocab_size,
                               size=prompt_len).astype(np.int32)
                 for _ in range(4)]
    worder = wrng.choice(4, size=8)
    run_once(4 * blocks_per_prompt * kv_block_bytes, wprefixes, worder)
    run_once(None, wprefixes, worder)

    curve = []
    counts_tiered = counts_evict = None
    for mult in mults:
        n_prefixes = pool_prompts * mult
        # UNCAPPED 4x revisit rate: a flat request cap would quietly
        # thin the revisit fraction as the sweep widens (2 per prefix
        # at 16x, 1 at 32x) and the tail of the curve would measure
        # the cap, not the working-set scaling it claims to.
        n_requests = 4 * n_prefixes
        ws_bytes = n_prefixes * blocks_per_prompt * kv_block_bytes
        tier_ts, evict_ts, ratios = [], [], []
        hits_t, hits_e, tier_stats = [], [], []
        for rep in range(repeats):
            rng = np.random.default_rng(seed + 101 * rep + mult)
            prefixes = [rng.integers(0, model.vocab_size,
                                     size=prompt_len).astype(np.int32)
                        for _ in range(n_prefixes)]
            p = 1.0 / np.power(np.arange(1, n_prefixes + 1), 1.1)
            order = rng.choice(n_prefixes, size=n_requests, p=p / p.sum())
            t_tier, eng_t = run_once(ws_bytes, prefixes, order)
            t_evict, eng_e = run_once(None, prefixes, order)
            tier_ts.append(t_tier)
            evict_ts.append(t_evict)
            ratios.append(t_tier / t_evict)
            snap = eng_t.metrics.snapshot()
            hits_t.append(snap["prefix_hit_rate"])
            hits_e.append(eng_e.metrics.snapshot()["prefix_hit_rate"])
            tier_stats.append(snap)
            counts_tiered = eng_t.compile_counts()
            counts_evict = eng_e.compile_counts()
        ratio_med, ratio_spread = median_spread(ratios)

        # Tier traffic and hit rates are MEDIANS across the paired
        # repeats like the TTFT fields beside them — each repeat draws
        # its own Zipf trace, and pinning the gate to whichever repeat
        # ran last would let one noisy draw flip it.
        def _stat_med(key):
            return float(np.median([s[key] for s in tier_stats]))

        curve.append({
            "working_set_x": mult,
            "n_prefixes": n_prefixes,
            "n_requests": n_requests,
            "host_tier_byte_budget": ws_bytes,
            "mean_ttft_tiered_s": round(median_spread(tier_ts)[0], 5),
            "mean_ttft_evict_s": round(median_spread(evict_ts)[0], 5),
            "ttft_tiered_over_evict_x": round(ratio_med, 3),
            "ttft_ratio_per_pair": [round(r, 3) for r in ratios],
            "spread_pct": round(ratio_spread, 2),
            "hit_rate_tiered": round(float(np.median(hits_t)), 3),
            "hit_rate_evict": round(float(np.median(hits_e)), 3),
            "host_tier_spills": int(_stat_med("host_tier_spills")),
            "host_tier_promotions":
                int(_stat_med("host_tier_promotions")),
            "host_tier_promote_tokens_charged":
                int(_stat_med("host_tier_promote_tokens_charged")),
            "host_tier_bytes_resident":
                int(_stat_med("host_tier_bytes_resident")),
        })
    # The ISSUE 13 headline point; None (leaf omitted by the gate's
    # numeric-leaf walk) when a custom --tier-mults sweep skips 8 —
    # the curve itself still carries every measured point.
    at8 = next((c for c in curve if c["working_set_x"] == 8), None)
    return {
        "prompt_len": prompt_len,
        "prefix_block_size": bs,
        "device_pool_blocks": pool_blocks,
        "device_pool_prompts": pool_prompts,
        "zipf_a": 1.1,
        "curve": curve,
        "mean_ttft_ratio_at_8x": (at8["ttft_tiered_over_evict_x"]
                                  if at8 is not None else None),
        "all_pairs_directional": all(
            r < 1.0 for c in curve for r in c["ttft_ratio_per_pair"]),
        "engine_compile_counts_tiered": counts_tiered,
        "engine_compile_counts_evict": counts_evict,
    }


def _tier_fleet_leg(model, variables, *, repeats: int, seed: int = 37):
    """The 2-replica half of ISSUE 13: duplicate-prefill tokens
    eliminated by the chain pull vs shadow-blind routing, PAIRED.

    Replica A holds the warm shared prefix (and two long batch streams
    keep it loaded); interactive probes sharing the prefix escape to
    cold replica B. Shadow-blind, B re-prefills the prefix it has
    never seen — tokens the FLEET already computed. With
    ``chain_pull_blocks`` armed, the router pulls A's chain into B's
    host tier and the admission promotes instead. duplicate tokens =
    matchable prefix tokens probes presented on B minus the tokens B's
    cache (pull included) saved — computed from the cold replica's own
    prefill_tokens_saved counter, no estimate."""
    from pddl_tpu.serve.fleet import FleetRouter, LocalReplica

    bs, prompt_len, prefill_len = 8, 48, 64
    shared_blocks = 5          # probes share 5*bs = 40 leading tokens
    l_match = shared_blocks * bs
    n_probes = 6

    def factory():
        return ServeEngine(
            model, variables, max_slots=4, prefill_len=prefill_len,
            max_queue_depth=16, prefix_cache_blocks=64,
            prefix_block_size=bs, prefix_chunk=16,
            host_tier=1 << 24)

    def run_pair(rep, pull):
        rng = np.random.default_rng(seed + rep)
        shared = rng.integers(0, model.vocab_size,
                              size=prompt_len).astype(np.int32)
        fleet = FleetRouter(
            [LocalReplica(0, factory), LocalReplica(1, factory)],
            affinity_block_size=bs, interactive_reroute_load=1,
            shadow_host_capacity_blocks=4096,
            chain_pull_blocks=(2 if pull else None))
        fleet.warmup()
        warmer = fleet.submit(list(shared), 2, priority=Priority.BATCH)
        while not warmer.done:
            fleet.step()
        warm_id = warmer.replica_id
        busy = [fleet.submit(list(shared), 48, priority=Priority.BATCH)
                for _ in range(2)]
        probe_tokens = []
        for _ in range(n_probes):
            p = np.concatenate([
                shared[:l_match],
                rng.integers(0, model.vocab_size, prompt_len - l_match)
                .astype(np.int32)])
            h = fleet.submit(list(p), 2, priority=Priority.INTERACTIVE)
            while not h.done:
                fleet.step()
            assert h.replica_id != warm_id, "probe did not escape"
            probe_tokens.append(list(h.tokens))
        while not all(b.done for b in busy):
            fleet.step()
        cold = next(s for s in fleet.replicas
                    if s.replica_id != warm_id)
        saved = cold.driver.engine.metrics.prefill_tokens_saved
        duplicate = n_probes * l_match - saved
        pulls = fleet.metrics.chain_pulls
        pull_tokens = fleet.metrics.chain_pull_tokens
        promoted = cold.driver.engine.metrics.host_tier_promotions
        fleet.close()
        return duplicate, pulls, pull_tokens, promoted, probe_tokens

    dup_blind, dup_pulled, pulls_total, promoted_total = [], [], 0, 0
    for rep in range(repeats):
        d_b, _, _, _, toks_b = run_pair(rep, pull=False)
        d_p, pulls, pull_tokens, promoted, toks_p = run_pair(rep,
                                                             pull=True)
        assert toks_b == toks_p, "pull changed a stream"
        dup_blind.append(d_b)
        dup_pulled.append(d_p)
        pulls_total += pulls
        promoted_total += promoted
    import statistics

    # Plain medians: the pulled side is exactly 0 when elimination is
    # total, and a spread over zero is undefined — the per-pair lists
    # carry the drift picture instead.
    blind_med = float(statistics.median(dup_blind))
    pulled_med = float(statistics.median(dup_pulled))
    return {
        "replicas": 2,
        "n_probe_requests": n_probes,
        "shared_prefix_tokens_matchable": l_match,
        "duplicate_prefill_tokens_blind": blind_med,
        "duplicate_prefill_tokens_pulled": pulled_med,
        "duplicate_per_pair_blind": dup_blind,
        "duplicate_per_pair_pulled": dup_pulled,
        "chain_pulls": pulls_total,
        "host_tier_promotions_cold_replica": promoted_total,
        "all_pairs_directional": all(
            p < b for p, b in zip(dup_pulled, dup_blind)),
        "streams_identical_blind_vs_pulled": True,
    }


def _fault_leg(model, variables, *, n_requests: int, prompt_len: int,
               new_tokens: int, slots: int, prefill_len: int,
               fault_rate: float, vocab: int, repeats: int, seed: int = 11):
    """Graceful-degradation measurement: the same closed-loop workload
    clean vs under seeded injection at ``fault_rate`` per device
    dispatch (transient errors, plus RESOURCE_EXHAUSTED at a tenth the
    rate so the degraded path fires too). PAIRED runs per repeat —
    host-load drift cancels in the per-pair ratio. Throughput counts
    DELIVERED tokens (a failed request's partial stream included), so
    a crash-looping engine cannot hide behind survivors."""
    prompts = _make_requests(n_requests, prompt_len, new_tokens, vocab,
                             seed=seed)

    def run_once(rate, run_seed):
        plan = (FaultPlan(seed=run_seed, transient_rate=rate,
                          oom_rate=rate / 10.0) if rate > 0 else None)
        eng = ServeEngine(model, variables, max_slots=slots,
                          prefill_len=prefill_len,
                          max_queue_depth=n_requests + 1,
                          fault_plan=plan, retry_backoff_s=0.005)
        eng.warmup()
        t0 = time.perf_counter()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=200000)
        dt = time.perf_counter() - t0
        assert all(h.done for h in handles), "engine failed to drain"
        delivered = sum(len(h.tokens) for h in handles)
        ttft = float(np.mean([h.ttft_s for h in handles
                              if h.ttft_s is not None]))
        finished = sum(h.state == RequestState.FINISHED for h in handles)
        return delivered / dt, ttft, finished, eng, plan

    tps_ratios, ttft_ratios = [], []
    clean_tps_all, fault_tps_all = [], []
    clean_ttft_all, fault_ttft_all = [], []
    finished_min = n_requests
    eng_fault = None
    # Injections and recovery work summed over ALL faulted repeats —
    # last-run-only counters can honestly read 0 at a 1% rate, which
    # would make the artifact look like nothing was survived.
    injected_total = {k.value: 0 for k in FaultKind}
    counters_total = {"retries": 0, "replays": 0, "degraded_entries": 0,
                      "requests_failed": 0}
    for i in range(repeats):
        c_tps, c_ttft, _, _, _ = run_once(0.0, seed + i)
        f_tps, f_ttft, f_fin, eng_fault, plan = run_once(fault_rate,
                                                         seed + i)
        clean_tps_all.append(c_tps)
        fault_tps_all.append(f_tps)
        clean_ttft_all.append(c_ttft)
        fault_ttft_all.append(f_ttft)
        tps_ratios.append(f_tps / c_tps)
        ttft_ratios.append(f_ttft / c_ttft)
        finished_min = min(finished_min, f_fin)
        for kind, count in plan.injected.items():
            injected_total[kind.value] += count
        snap_i = eng_fault.metrics.snapshot()
        for key in counters_total:
            counters_total[key] += snap_i[key]
    tps_med, tps_spread = median_spread(tps_ratios)
    return {
        "fault_rate_per_dispatch": fault_rate,
        "oom_rate_per_dispatch": fault_rate / 10.0,
        "n_requests": n_requests,
        "new_tokens": new_tokens,
        "clean_tokens_per_s": round(median_spread(clean_tps_all)[0], 1),
        "faulted_tokens_per_s": round(median_spread(fault_tps_all)[0], 1),
        "throughput_retained_x": round(tps_med, 3),
        "throughput_retained_per_pair": [round(r, 3) for r in tps_ratios],
        "throughput_retained_spread_pct": round(tps_spread, 2),
        "clean_mean_ttft_s": round(median_spread(clean_ttft_all)[0], 5),
        "faulted_mean_ttft_s": round(median_spread(fault_ttft_all)[0], 5),
        "ttft_inflation_per_pair": [round(r, 3) for r in ttft_ratios],
        "min_requests_finished_faulted": finished_min,
        "faults_injected_total": injected_total,
        "recovery_counters_total": counters_total,
        "engine_compile_counts_faulted": eng_fault.compile_counts(),
        # Tail latencies, not just throughput: the faulted engine's
        # full final snapshot rides in the artifact.
        "serve_metrics_snapshot": eng_fault.metrics.snapshot(),
    }


def _obs_leg(model, variables, *, n_requests: int, prompt_len: int,
             new_tokens: int, slots: int, prefill_len: int, vocab: int,
             repeats: int, seed: int = 5):
    """The tracing tax: the same closed-loop workload with per-request
    tracing OFF (the engine default — the no-op tracer) vs ON (a
    `RequestTracer` streaming every span to a JSONL sink). PAIRED runs
    per repeat so host-load drift cancels in the ratio. The OFF number
    is the instrumented engine at its production default; the
    acceptance gate compares it against the pre-obs engine's committed
    clean throughput (r08 fault leg, identical config)."""
    prompts = _make_requests(n_requests, prompt_len, new_tokens, vocab,
                             seed=seed)
    tmpdir = tempfile.mkdtemp(prefix="serve_obs_")

    def run_once(tracer):
        eng = ServeEngine(model, variables, max_slots=slots,
                          prefill_len=prefill_len,
                          max_queue_depth=n_requests + 1,
                          tracer=tracer)
        eng.warmup()
        t0 = time.perf_counter()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=200000)
        dt = time.perf_counter() - t0
        assert all(h.done for h in handles)
        assert sum(len(h.tokens) for h in handles) \
            == n_requests * new_tokens
        return n_requests * new_tokens / dt, eng

    off_tps, on_tps, ratios = [], [], []
    spans_total = records_total = 0
    eng_on = None
    try:
        for i in range(repeats):
            t_off, _ = run_once(None)
            with JsonlEventLog(os.path.join(tmpdir,
                                            f"trace_{i}.jsonl")) as log:
                tracer = RequestTracer(sink=log)
                t_on, eng_on = run_once(tracer)
            off_tps.append(t_off)
            on_tps.append(t_on)
            ratios.append(t_on / t_off)
            spans_total += tracer.spans_finished
            records_total += log.records_written
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    off_med, off_spread = median_spread(off_tps)
    on_med, _ = median_spread(on_tps)
    ratio_med, ratio_spread = median_spread(ratios)
    # The committed pre-obs baseline at this exact config, when present
    # (r08's fault-leg clean run: same requests x tokens x slots) —
    # resolved against the repo, not the caller's cwd.
    baseline = None
    r08 = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        "artifacts", "gpt_bench", "r08_serve_faults.json")
    try:
        with open(r08) as f:
            baseline = json.load(f)["results"]["faults"][
                "clean_tokens_per_s"]
    except Exception:  # noqa: BLE001 - artifact absent: ratio omitted
        pass
    ring_last = eng_on.telemetry.summary()
    return {
        "n_requests": n_requests,
        "new_tokens": new_tokens,
        "tokens_per_s_tracing_off": round(off_med, 1),
        "tokens_per_s_tracing_off_spread_pct": round(off_spread, 2),
        "tokens_per_s_tracing_on": round(on_med, 1),
        "tracing_on_over_off_x": round(ratio_med, 3),
        "tracing_on_over_off_per_pair": [round(r, 3) for r in ratios],
        "spread_pct": round(ratio_spread, 2),
        "baseline_r08_clean_tokens_per_s": baseline,
        "tracing_off_vs_r08_clean_x": (
            round(off_med / baseline, 3) if baseline else None),
        "trace_spans_finished_total": spans_total,
        "trace_records_written_total": records_total,
        "ring_ticks_recorded_last_repeat": ring_last["ticks"],
        "ring_tick_wall_p99_s_last_repeat": round(
            ring_last["tick_wall_p99_s"], 6),
        "engine_compile_counts_traced": eng_on.compile_counts(),
        "serve_metrics_snapshot": eng_on.metrics.snapshot(),
    }


def _write_trace_artifact(model, variables, prompts, new_tokens: int,
                          slots: int, prefill_len: int, path: str) -> int:
    """One fully traced closed-loop pass whose span log IS the bench
    artifact: every request's span, every engine tick (the tracer's
    ``emit_ticks`` stream — complete, unlike the capacity-bounded
    ring), the ring's per-site-wall records for the final window, and
    the final metrics snapshot — a self-contained timeline
    (`docs/OPERATIONS.md` § Observability)."""
    with JsonlEventLog(path) as log:
        eng = ServeEngine(model, variables, max_slots=slots,
                          prefill_len=prefill_len,
                          max_queue_depth=len(prompts) + 1,
                          tracer=RequestTracer(sink=log, emit_ticks=True))
        eng.warmup()
        handles = [eng.submit(p, new_tokens) for p in prompts]
        eng.run(max_steps=200000)
        assert all(h.done for h in handles)
        ring = eng.telemetry
        # The ring window is capacity-bounded; say so in the artifact
        # instead of letting a truncated dump read as the whole run.
        log.write({"kind": "ring_window", "recorded": len(ring),
                   "total_ticks": ring.total_appended,
                   "truncated": ring.total_appended > len(ring)})
        for rec in ring.snapshot():
            # A DISTINCT kind from the tracer's own "tick" records:
            # ring records carry tick_wall_s/tokens/retries, tracer
            # ticks carry wall_s/new_tokens — one kind per shape.
            rec["kind"] = "ring_tick"
            log.write(rec)
        log.write({"kind": "metrics",
                   "snapshot": eng.metrics.snapshot()})
        return log.records_written


def _maybe_write_trace(args, model, variables) -> None:
    """The shared ``--trace`` leg: ONE workload shape (2x concurrent
    closed-loop, the fault/obs-leg shape) regardless of which flag
    combination invoked the bench."""
    if not args.trace:
        return
    n = _write_trace_artifact(
        model, variables,
        _make_requests(2 * args.concurrent, args.prompt_len,
                       args.new_tokens, args.vocab),
        args.new_tokens, args.slots, args.prefill_len, args.trace)
    _log(f"trace artifact: {n} records -> {args.trace}")


def _log_obs_leg(obs: dict) -> None:
    vs_r08 = obs["tracing_off_vs_r08_clean_x"]
    _log(f"observability: {obs['tokens_per_s_tracing_off']} tok/s "
         f"tracing off -> {obs['tokens_per_s_tracing_on']} tok/s on "
         f"({obs['tracing_on_over_off_x']}x, pairs "
         f"{obs['tracing_on_over_off_per_pair']}); vs r08 clean "
         f"{f'{vs_r08}x' if vs_r08 is not None else 'n/a'}; "
         f"{obs['trace_spans_finished_total']} spans, "
         f"{obs['trace_records_written_total']} records")


def _poisson_load(model, variables, offered_rps: float, n_requests: int,
                  prompt_len: int, new_tokens: int, vocab: int,
                  slots: int, prefill_len: int, max_queue_depth: int,
                  seed: int):
    """Open-loop Poisson arrivals at ``offered_rps`` requests/s; the
    engine runs in real time, so TTFT includes genuine queue wait."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, n_requests))
    prompts = _make_requests(n_requests, prompt_len, new_tokens, vocab,
                             seed=seed + 1)
    # Prefix cache off: the Poisson prompts are random (nothing to
    # share), and the load curve stays comparable with r06.
    eng = ServeEngine(model, variables, max_slots=slots,
                      prefill_len=prefill_len,
                      max_queue_depth=max_queue_depth,
                      prefix_cache_blocks=0)
    eng.warmup()
    rejected = 0
    i = 0
    t0 = time.perf_counter()
    while i < n_requests or eng.has_work:
        now = time.perf_counter() - t0
        while i < n_requests and arrivals[i] <= now:
            try:
                eng.submit(prompts[i], new_tokens,
                           sampling=SamplingParams())
            except QueueFull:
                rejected += 1
            i += 1
        if eng.has_work:
            eng.step()
        elif i < n_requests:
            time.sleep(min(arrivals[i] - now, 0.01))
    wall = time.perf_counter() - t0
    snap = eng.metrics.snapshot()
    return {
        "offered_rps": round(offered_rps, 3),
        "offered_tokens_per_s": round(offered_rps * new_tokens, 1),
        "tokens_per_s": round(snap["tokens_emitted"] / wall, 1),
        "ttft_p50_s": round(snap["ttft_p50_s"], 4)
        if snap["ttft_p50_s"] is not None else None,
        "ttft_p99_s": round(snap["ttft_p99_s"], 4)
        if snap["ttft_p99_s"] is not None else None,
        "mean_queue_depth": round(snap["mean_queue_depth"], 2),
        "mean_slot_occupancy": round(snap["mean_slot_occupancy"], 3),
        "requests_finished": snap["requests_finished"],
        "requests_rejected_queue_full": rejected,
    }


def _fleet_worker_config(args) -> dict:
    return dict(vocab=args.vocab, max_len=args.max_len,
                embed_dim=args.embed_dim, depth=args.depth,
                heads=args.heads, slots=args.slots,
                prefill_len=args.prefill_len,
                max_queue_depth=4 * args.slots, param_seed=0,
                # Prefix reuse OFF: this leg's prompts share nothing
                # (the pool would only add overhead) and the committed
                # r11 artifact was measured on the 4-program engine —
                # keep reruns comparable to it.
                prefix_cache_blocks=0)


def _fleet_spawn(n: int, cfg: dict):
    import subprocess

    from pddl_tpu.serve.fleet import FleetRouter, ProcessReplica

    # Launch every worker first, then wait: the N warmup compiles run
    # concurrently instead of paying N serial engine builds.
    replicas = [ProcessReplica(i, {**cfg, "replica_id": i},
                               stderr=subprocess.DEVNULL, wait_ready=False)
                for i in range(n)]
    for r in replicas:
        r.wait_ready()
    return FleetRouter(replicas, affinity_block_size=8,
                       affinity_blocks=1, respawn=False)


def _fleet_wave(fleet, prompts, new_tokens: int, offered_rps: float,
                seed: int, kill_at_request: int = -1):
    """One open-loop Poisson wave through the fleet (real time, so TTFT
    includes genuine queue wait). ``kill_at_request >= 0`` SIGKILLs the
    busiest replica once that many requests have been submitted — the
    un-drainable mid-run death the failover leg measures."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / offered_rps, len(prompts)))
    handles, rejected, killed_id = [], 0, None
    # Hang protection: without a deadline the all_terminal field below
    # would be a tautology — the loop could only ever exit with every
    # handle done, and a regression stranding one request would spin
    # the bench forever instead of failing its assert.
    deadline = time.perf_counter() + max(
        120.0, float(arrivals[-1]) + 2.0 * len(prompts))
    t0 = time.perf_counter()
    i = 0
    while i < len(prompts) or any(not h.done for h in handles):
        if time.perf_counter() > deadline:
            break  # stranded request: report it, don't hang
        now = time.perf_counter() - t0
        while i < len(prompts) and arrivals[i] <= now:
            try:
                handles.append(fleet.submit(prompts[i], new_tokens))
            except Exception:  # noqa: BLE001 - QueueFull / NoHealthy
                rejected += 1
            i += 1
            if i == kill_at_request and killed_id is None:
                victim = max((s for s in fleet.replicas
                              if s.state.value == "up"),
                             key=lambda s: s.load)
                killed_id = victim.replica_id
                victim.driver.kill()
        if fleet.step() == 0:
            time.sleep(0.001)
    wall = time.perf_counter() - t0
    delivered = sum(len(h.tokens) for h in handles)
    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    return {
        "tokens_per_s": delivered / wall,
        "ttft_p50_s": float(np.percentile(ttfts, 50)) if ttfts else None,
        "ttft_p99_s": float(np.percentile(ttfts, 99)) if ttfts else None,
        "rejected": rejected,
        "all_terminal": all(h.done for h in handles),
        "finished": sum(h.state.value == "finished" for h in handles),
        "n_requests": len(handles),
        "killed_replica": killed_id,
        "handles": handles,
    }


def _fleet_leg(args, replica_counts, *, load_frac: float = 0.8,
               kill_counts=(2, 4)):
    """The r11 leg: aggregate tok/s + p99 TTFT at N replicas under
    Poisson load (clean), and the failover leg — one replica
    SIGKILL'd mid-run — at N in ``kill_counts``. Process replicas run
    genuinely in parallel, so the scaling curve is real concurrency,
    not slot arithmetic. Clean repeats reuse one fleet (spawn cost is
    startup, not serving); every killed repeat gets a fresh fleet and
    is PAIRED with a clean wave for the retained-throughput ratio.
    Token-exactness after migration is pinned against an in-process
    oracle engine built from the same param seed."""
    from pddl_tpu.serve.fleet.worker import build_engine

    cfg = _fleet_worker_config(args)
    # The committed r08 single-engine clean baseline at this config —
    # the acceptance comparison (N=4 must beat 2x this number).
    r08_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        "artifacts", "gpt_bench", "r08_serve_faults.json")
    try:
        with open(r08_path) as f:
            baseline = json.load(f)["results"]["faults"][
                "clean_tokens_per_s"]
    except Exception:  # noqa: BLE001 - artifact absent: ratio omitted
        baseline = None
    cap_single = baseline or 1000.0
    oracle = build_engine(cfg)
    oracle_refs = {}

    def ref_for(prompt):
        key = tuple(prompt)
        if key not in oracle_refs:
            out = generate(oracle.model, {"params": oracle._params},
                           jnp.asarray(prompt, jnp.int32)[None],
                           args.new_tokens)
            oracle_refs[key] = np.asarray(out)[0, len(prompt):].tolist()
        return oracle_refs[key]

    scaling = []
    for n in replica_counts:
        offered = load_frac * n * cap_single / args.new_tokens
        n_requests = 48 * n  # long waves: the drain tail amortizes
        fleet = _fleet_spawn(n, cfg)
        try:
            tps_all, p99_all, p50_all = [], [], []
            last = None
            for rep in range(args.repeats):
                prompts = _make_requests(n_requests, args.prompt_len,
                                         args.new_tokens, args.vocab,
                                         seed=100 * n + rep)
                last = _fleet_wave(fleet, prompts, args.new_tokens,
                                   offered, seed=100 * n + rep)
                assert last["all_terminal"]
                tps_all.append(last["tokens_per_s"])
                p99_all.append(last["ttft_p99_s"])
                p50_all.append(last["ttft_p50_s"])
            tps_med, tps_spread = median_spread(tps_all)
            counts = fleet.compile_counts()
            snap = fleet.metrics.snapshot()
        finally:
            fleet.close()
        scaling.append({
            "replicas": n,
            "offered_fraction_of_nx_baseline": load_frac,
            "offered_tokens_per_s": round(offered * args.new_tokens, 1),
            "n_requests_per_wave": n_requests,
            "tokens_per_s": round(tps_med, 1),
            "tokens_per_s_spread_pct": round(tps_spread, 2),
            "tokens_per_s_per_repeat": [round(t, 1) for t in tps_all],
            "ttft_p50_s": round(median_spread(p50_all)[0], 4),
            "ttft_p99_s": round(median_spread(p99_all)[0], 4),
            "rejected_last_wave": last["rejected"],
            "vs_r08_clean_x": (round(tps_med / baseline, 3)
                               if baseline else None),
            "zero_recompiles_all_replicas": bool(counts) and all(
                v == 1 for v in counts.values()),
            "fleet_metrics": snap,
        })
        _log(f"fleet N={n}: {tps_med:,.0f} tok/s (spread "
             f"{tps_spread:.1f}%), p99 TTFT "
             f"{scaling[-1]['ttft_p99_s']}s, vs r08 "
             f"{scaling[-1]['vs_r08_clean_x']}x")

    killed = []
    for n in (k for k in kill_counts if k in replica_counts):
        offered = load_frac * n * cap_single / args.new_tokens
        n_requests = 48 * n
        ratios, clean_all, killed_all = [], [], []
        exact_all, migrated_total = True, 0
        for rep in range(args.repeats):
            prompts = _make_requests(n_requests, args.prompt_len,
                                     args.new_tokens, args.vocab,
                                     seed=500 * n + rep)
            fleet = _fleet_spawn(n, cfg)
            try:  # PAIRED: clean wave then killed wave, fresh fleets
                clean = _fleet_wave(fleet, prompts, args.new_tokens,
                                    offered, seed=500 * n + rep)
                # A stranded clean-wave request would deflate the clean
                # denominator and inflate the retained ratio meets_floor
                # is judged on — fail the pair loudly instead.
                assert clean["all_terminal"], \
                    "a clean-wave request never settled"
            finally:
                fleet.close()
            fleet = _fleet_spawn(n, cfg)
            try:
                kill = _fleet_wave(fleet, prompts, args.new_tokens,
                                   offered, seed=500 * n + rep,
                                   kill_at_request=n_requests // 2)
                assert kill["all_terminal"], "a request never settled"
                for h in kill["handles"]:
                    if h.state.value == "finished" \
                            and h.tokens != ref_for(h.request.prompt):
                        exact_all = False
                migrated_total += fleet.metrics.requests_migrated
                counts = fleet.compile_counts()
                surv_ok = bool(counts) and all(
                    v == 1 for v in counts.values())
            finally:
                fleet.close()
            clean_all.append(clean["tokens_per_s"])
            killed_all.append(kill["tokens_per_s"])
            ratios.append(kill["tokens_per_s"] / clean["tokens_per_s"])
        ratio_med, ratio_spread = median_spread(ratios)
        floor = 0.9 * (n - 1) / n
        killed.append({
            "replicas": n,
            "kill": "SIGKILL busiest replica at half the request "
                    "schedule (un-drainable: replay-mirror migration)",
            "clean_tokens_per_s": round(median_spread(clean_all)[0], 1),
            "killed_tokens_per_s": round(median_spread(killed_all)[0], 1),
            "throughput_retained_x": round(ratio_med, 3),
            "throughput_retained_per_pair": [round(r, 3) for r in ratios],
            "throughput_retained_spread_pct": round(ratio_spread, 2),
            "retained_floor_0p9_nm1_over_n": round(floor, 3),
            "meets_floor": ratio_med >= floor,
            "requests_migrated_total": migrated_total,
            "survivor_streams_token_exact": exact_all,
            "zero_recompiles_survivors_last_repeat": surv_ok,
        })
        _log(f"fleet kill N={n}: retained {ratio_med:.3f}x (floor "
             f"{floor:.3f}, pairs {killed[-1]['throughput_retained_per_pair']}), "
             f"migrated {migrated_total}, token-exact {exact_all}")
    return {
        "baseline_r08_clean_tokens_per_s": baseline,
        "scaling": scaling,
        "killed": killed,
    }


def _trace_schedule(n_requests: int, vocab: int, seed: int, *,
                    prompt_base: int = 16, prompt_cap: int = 60):
    """Trace-driven load: bursty MULTI-TURN sessions over shared system
    prompts with heavy-tail output lengths — the shape of real chat
    traffic, not Poisson. Sessions arrive in bursts (a long gap then a
    clump), each session keeps one of 4 system prompts as its prefix
    (prefix-cache + sticky-session territory), turns grow the
    conversation, and output lengths draw from a bounded Pareto (most
    replies short, a heavy tail of long ones). Priorities:
    ~35% interactive sessions (deadlined), ~15% batch, ~50%
    best_effort — the sheddable bulk a brownout should eat first.

    Returns (events, mean_new_tokens); event times are UNIT-paced —
    :func:`_scale_schedule` rescales them to an offered rate."""
    rng = np.random.default_rng(seed)
    sys_prompts = [rng.integers(0, vocab, size=prompt_base)
                   for _ in range(4)]
    events, t, s = [], 0.0, 0
    while len(events) < n_requests:
        s += 1
        # Bursty arrivals: occasional long inter-burst gaps, tight
        # spacing inside a burst (a burst clumps ~2 s of the average
        # rate into ~0.6 s — pronounced, but proportionate to a
        # 16-slot toy fleet rather than a thundering herd).
        t += float(rng.exponential(3.0) if rng.random() < 0.15
                   else rng.exponential(0.6))
        r = rng.random()
        pr = (Priority.INTERACTIVE if r < 0.35
              else Priority.BATCH if r < 0.50 else Priority.BEST_EFFORT)
        sysp = sys_prompts[int(rng.integers(0, len(sys_prompts)))]
        convo: list = []
        tt = t
        for _turn in range(int(rng.integers(1, 4))):
            convo = convo + rng.integers(
                0, vocab, size=int(rng.integers(6, 13))).tolist()
            prompt = np.concatenate(
                [sysp, np.asarray(convo)]).astype(np.int32)[:prompt_cap]
            new = int(min(4 + rng.pareto(1.3) * 4, 48))
            events.append(dict(
                t=tt, session=f"s{s}", prompt=prompt.tolist(),
                new_tokens=new, priority=pr,
                deadline_s=8.0 if pr is Priority.INTERACTIVE else None))
            tt += float(rng.exponential(0.8))  # think time between turns
    events = sorted(events, key=lambda e: e["t"])[:n_requests]
    mean_new = float(np.mean([e["new_tokens"] for e in events]))
    return events, mean_new


def _scale_schedule(events, offered_rps: float):
    """Rescale event times so the WHOLE trace offers ``offered_rps``
    requests/s on average (burst structure preserved)."""
    t0 = events[0]["t"]
    span = max(events[-1]["t"] - t0, 1e-9)
    scale = (len(events) / offered_rps) / span
    return [dict(e, t=(e["t"] - t0) * scale) for e in events]


def _slo_fleet(args, *, with_admission: bool, rates=None):
    import subprocess

    from pddl_tpu.serve.fleet import (
        AdmissionControl,
        FleetRouter,
        ProcessReplica,
    )

    # Real worker processes (the r11 deployment shape): each replica
    # self-drives its engine loop, so burst admissions on one replica
    # never stall another's decode cadence — the parallelism the SLO
    # numbers are about. SLO engine knobs ride the worker config:
    # per-step prefill bounded at two prompt widths (a burst admits
    # over a couple of steps, a prompt that dwarfs the budget — the
    # 32k case slicing exists for — time-slices against the tick) and
    # aging long enough that batch waits out a burst instead of
    # immediately contending with interactive.
    cfg = dict(vocab=args.vocab, max_len=args.max_len,
               embed_dim=args.embed_dim, depth=args.depth,
               heads=args.heads, slots=args.slots,
               prefill_len=args.prefill_len,
               max_queue_depth=2 * args.slots, param_seed=0,
               aging_s=3.0,
               prefill_slice_tokens=2 * args.prefill_len)
    replicas = [ProcessReplica(i, {**cfg, "replica_id": i},
                               stderr=subprocess.DEVNULL,
                               wait_ready=False)
                for i in range(args.slo_replicas)]
    for r in replicas:
        r.wait_ready()
    admission = None
    if with_admission:
        # Fast-acting ladder: the brownout must engage within a few
        # rejected submits (min_samples 4, no escalate hold) so early
        # overload sheds best_effort instead of class-blind QueueFulls.
        # Token buckets (the runbook's sizing rule): the NON-protected
        # classes alone must fit beside interactive inside capacity.
        admission = AdmissionControl(
            rates=rates, burst=6.0,
            detector_kw=dict(window_s=1.0, min_samples=4),
            brownout_kw=dict(high=0.2, low=0.05, escalate_hold_s=0.0,
                             recover_hold_s=0.5, output_cap=12))
    return FleetRouter(replicas, affinity_block_size=8,
                       affinity_blocks=2, respawn=False,
                       admission=admission)


def _slo_capacity(args) -> float:
    """Sustained fleet capacity (tokens/s): closed-loop mean-shape
    requests straight through the SLO fleet (no admission control, big
    queue pressure absorbed by retry-on-full)."""
    fleet = _slo_fleet(args, with_admission=False)
    try:
        events, _ = _trace_schedule(6 * args.slots * args.slo_replicas,
                                    args.vocab, seed=999)
        t0 = time.perf_counter()
        handles = []
        backlog = list(events)
        deadline = t0 + 300.0
        while backlog or fleet.has_work:
            while backlog:
                ev = backlog[0]
                try:
                    handles.append(fleet.submit(
                        ev["prompt"], ev["new_tokens"],
                        session=ev["session"]))
                    backlog.pop(0)
                except QueueFull:
                    break
            fleet.step()
            assert time.perf_counter() < deadline, "capacity leg hung"
        wall = time.perf_counter() - t0
        assert all(h.done for h in handles)
        return sum(len(h.tokens) for h in handles) / wall
    finally:
        fleet.close()


def _slo_wave(fleet, schedule, *, hang_s: float = 300.0):
    """One open-loop pass of the trace through the fleet, via the
    shared hint-honoring replay client (`serve/fleet/replay.py`): a
    rejected event re-enters at ``now + retry_after_s`` — the behavior
    a polite caller actually has — instead of being dropped (the r12
    harness's discipline, which understated brownout recovery);
    ``rejects`` counts only TERMINAL sheds, after the hint-driven
    retries ran out. Returns the handles (with their events), the
    per-class terminal sheds, and whether every request reached a
    terminal state before the hang deadline (a measurement, not a
    tautology — the loop CAN exit with stragglers and reports them)."""
    from pddl_tpu.serve.fleet import replay_trace

    rep = replay_trace(fleet, schedule, honor_hints=True,
                       max_attempts=4, hang_s=hang_s,
                       clock=time.perf_counter)
    return {"handles": rep.handles, "rejects": rep.rejects,
            "hinted_rejects": rep.hinted_rejects,
            "retried_after_hint": rep.retried_after_hint,
            "wall_s": rep.wall_s, "all_terminal": rep.all_terminal}


def _slo_leg(args, *, overload_x: float = 2.0,
             uncontended_x: float = 0.3):
    """The r12 leg: the bursty multi-turn trace at ``overload_x`` times
    measured fleet capacity, admission control + brownout armed,
    PAIRED per repeat with an uncontended wave for the interactive-p99
    ratio. Headlines: zero lost/hung requests, interactive p99 TTFT
    within 1.5x its uncontended value, best_effort absorbing the bulk
    of the shedding, zero recompiles."""
    cap_tps = _slo_capacity(args)
    _log(f"slo: measured fleet capacity {cap_tps:,.0f} tok/s "
         f"({args.slo_replicas} process replicas)")
    events, mean_new = _trace_schedule(args.slo_requests, args.vocab,
                                       seed=17)
    # Bucket sizing per the runbook: batch's bucket fits its own
    # offered rate (0.15 x 2x = 0.3x of capacity — batch should WAIT,
    # not shed), while best_effort (0.5 x 2x = 1.0x offered) is capped
    # well below that, so the front door sheds the sheddable class and
    # the brownout's output cap absorbs the rest of the overshoot.
    cap_rps = cap_tps / mean_new
    rates = {Priority.BATCH: 0.35 * cap_rps,
             Priority.BEST_EFFORT: 0.3 * cap_rps}
    ratios, be_fracs, over_tps, over_p99s, unc_p99s = [], [], [], [], []
    goodputs = []
    lost_total = rejects_total = 0
    max_rung = 0
    counts_ok = True
    fleet_metrics_last = None
    for rep in range(args.repeats):
        # Uncontended half of the pair: interactive's baseline p99.
        fleet = _slo_fleet(args, with_admission=True, rates=rates)
        try:
            unc = _slo_wave(fleet, _scale_schedule(
                events, uncontended_x * cap_tps / mean_new))
            assert unc["all_terminal"], "uncontended wave stranded work"
            unc_tt = [h.ttft_s for ev, h in unc["handles"]
                      if ev["priority"] is Priority.INTERACTIVE
                      and h.ttft_s is not None]
        finally:
            fleet.close()
        # The overload half: 2x sustained capacity, brownout armed.
        fleet = _slo_fleet(args, with_admission=True, rates=rates)
        try:
            over = _slo_wave(fleet, _scale_schedule(
                events, overload_x * cap_tps / mean_new))
            lost = sum(1 for _, h in over["handles"] if not h.done)
            lost_total += lost
            over_tt = [h.ttft_s for ev, h in over["handles"]
                       if ev["priority"] is Priority.INTERACTIVE
                       and h.ttft_s is not None]
            delivered = sum(len(h.tokens) for _, h in over["handles"])
            inter_deliv = sum(
                len(h.tokens) for ev, h in over["handles"]
                if ev["priority"] is Priority.INTERACTIVE)
            # Sheds by class: front-door/queue rejects plus requests
            # the engines deadline-shed or timed out (derived from the
            # fleet handles, so the accounting is driver-agnostic).
            sheds = dict(over["rejects"])
            for ev, h in over["handles"]:
                if h.state is RequestState.TIMED_OUT:
                    sheds[ev["priority"].value] += 1
            total_shed = sum(sheds.values())
            rejects_total += sum(over["rejects"].values())
            be_fracs.append(sheds["best_effort"] / total_shed
                            if total_shed else 1.0)
            over_tps.append(delivered / over["wall_s"])
            goodputs.append(inter_deliv / over["wall_s"])
            p99_unc = float(np.percentile(unc_tt, 99))
            p99_over = float(np.percentile(over_tt, 99))
            unc_p99s.append(p99_unc)
            over_p99s.append(p99_over)
            ratios.append(p99_over / p99_unc)
            max_rung = max(max_rung, int(fleet.admission.rung))
            counts = fleet.compile_counts()
            counts_ok = counts_ok and bool(counts) and all(
                v == 1 for v in counts.values())
            fleet_metrics_last = fleet.metrics.snapshot()
        finally:
            fleet.close()
        _log(f"slo pair {rep}: interactive p99 {p99_unc:.3f}s -> "
             f"{p99_over:.3f}s ({ratios[-1]:.2f}x), best_effort shed "
             f"frac {be_fracs[-1]:.2f}, lost {lost}")
    ratio_med, ratio_spread = median_spread(ratios)
    be_med, be_spread = median_spread(be_fracs)
    tps_med, tps_spread = median_spread(over_tps)
    return {
        "trace": "bursty multi-turn sessions, 4 shared system prompts, "
                 "bounded-Pareto output lengths, 35/15/50 "
                 "interactive/batch/best_effort",
        "process_replicas": args.slo_replicas,
        "n_requests_per_wave": args.slo_requests,
        "mean_new_tokens": round(mean_new, 2),
        "overload_x_capacity": overload_x,
        "capacity_tokens_per_s": round(cap_tps, 1),
        "overload_tokens_per_s": round(tps_med, 1),
        "overload_tokens_per_s_spread_pct": round(tps_spread, 2),
        "interactive_goodput_tokens_per_s": round(
            median_spread(goodputs)[0], 1),
        "uncontended_interactive_ttft_p99_s": round(
            median_spread(unc_p99s)[0], 4),
        "overload_interactive_ttft_p99_s": round(
            median_spread(over_p99s)[0], 4),
        "interactive_ttft_p99_overload_over_uncontended_x": round(
            ratio_med, 3),
        "interactive_ttft_ratio_per_pair": [round(r, 3) for r in ratios],
        "interactive_ttft_ratio_spread_pct": round(ratio_spread, 2),
        "interactive_ttft_ratio_bound": 1.5,
        "best_effort_shed_absorbed_frac": round(be_med, 3),
        "best_effort_shed_absorbed_per_repeat": [
            round(f, 3) for f in be_fracs],
        "best_effort_shed_absorbed_spread_pct": round(be_spread, 2),
        "best_effort_shed_absorbed_bound": 0.8,
        "requests_lost_or_hung_total": lost_total,
        "front_door_rejects_total": rejects_total,
        "brownout_rung_at_wave_end_max": max_rung,
        "zero_recompiles_all_replicas": counts_ok,
        "fleet_metrics_last_repeat": fleet_metrics_last,
    }


def _disagg_prefill_len(args) -> int:
    """Largest block-aligned prefill buffer that still fits beside
    the restore chunk in the KV budget (the engine's
    `prefill_len + prefix_chunk <= max_len` invariant)."""
    return (args.max_len - 2 * _DISAGG_CHUNK) // 8 * 8


# Restore-suffix chunk width: a handed-off chain covers every FULL
# block of the prompt, so the destination's prefill-from-cache only
# computes the partial tail block (+ the tokens decoded before the
# move) — a narrow chunk program keeps that from paying a
# quarter-buffer of padding per restore.
_DISAGG_CHUNK = 16


def _disagg_engine_factory(args, model, variables):
    """Hand-off-capable engine for BOTH fleet shapes — the only
    variable in a pair is the role assignment. Prefix cache ON (the
    chain to export) and host tier ON (the landing zone, r18 wire
    format). Admission is un-sliced: on this trace's 250+-token
    prompts the r12 slice budget would triple TTFT to buy jitter
    relief, and chunked prefill is the TRADEOFF disaggregation
    removes, not a free alternative — the r12 SLO leg keeps
    benchmarking the sliced operating point on its short-prompt
    trace."""
    def make():
        return ServeEngine(
            model, variables, max_slots=args.slots,
            prefill_len=_disagg_prefill_len(args),
            prefix_cache_blocks=256, prefix_block_size=8,
            prefix_chunk=_DISAGG_CHUNK,
            host_tier=1 << 28, max_queue_depth=2 * args.slots)
    return make


class _TimedLocalReplica:
    """In-process replica that times its own engine ticks.

    The leg runs LOCAL replicas (like the r18 tier fleet leg), for
    two reasons that are one reason: the pddl_tpu target is a
    TPU-native fleet where a KV-block DMA costs microseconds against
    milliseconds of prefill compute, and a CPU worker pipe prices the
    same transfer at base64+JSON rates — compute parity, a transport
    artifact the paper's fabric does not have. In-process transfer
    (`export_prefix_chain` buffers straight into the peer's host
    tier) models the DMA side of that ratio, and per-tick timing
    gives an arrival-clock-free read of decode cadence: every token
    is charged the duration of the engine step that produced it, so
    a prefill admission (or a restore) sharing the tick is charged to
    its co-residents' tokens — interference measured where it
    happens, not through the router's harvest loop."""

    def __init__(self, replica_id, engine_factory, *, role="unified"):
        from pddl_tpu.serve.fleet import LocalReplica

        self._inner = LocalReplica(replica_id, engine_factory,
                                   role=role)
        self.last_step_s = 0.0

    def step(self):
        t0 = time.perf_counter()
        try:
            return self._inner.step()
        finally:
            self.last_step_s = time.perf_counter() - t0

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _disagg_fleet(args, model, variables, roles, *, tracer=None):
    from pddl_tpu.serve.fleet import FleetRouter

    make = _disagg_engine_factory(args, model, variables)
    replicas = [_TimedLocalReplica(i, make, role=role)
                for i, role in enumerate(roles)]
    return FleetRouter(replicas, affinity_block_size=8,
                       affinity_blocks=2, respawn=False, tracer=tracer)


def _disagg_warm(fleet, args, *, seed: int = 4242):
    """Compile every program the wave will run — prefill, decode,
    and (on decode replicas) the promote + restore-chunk path each
    hand-off exercises — so the measured ticks are steady-state and
    the zero-recompile pin holds over the wave itself."""
    rng = np.random.default_rng(seed)
    n = 2 * sum(1 for s in fleet.replicas
                if getattr(s.driver, "role", "unified") != "prefill")
    handles = [fleet.submit(
        rng.integers(0, args.vocab,
                     size=_disagg_prefill_len(args) - 8 * k)
        .astype(np.int32).tolist(), 4) for k in range(1, n + 1)]
    fleet.run(max_steps=4000)
    assert all(h.done for h in handles), "disagg warmup stranded work"


def _disagg_wave(fleet, schedule, *, hang_s: float = 600.0):
    """One open-loop pass of the long-prompt trace. Decode-side
    per-token latency pool: each harvested token is charged the wall
    duration of the replica tick that produced it (first tokens — the
    TTFT side, where prefill and the hand-off itself live — are
    excluded; everything after, including the restored stream's first
    post-move tick with its promote charge, is decode cadence)."""
    t0 = time.perf_counter()
    backlog = sorted(schedule, key=lambda e: e["t"])
    by_id = {s.replica_id: s.driver for s in fleet.replicas}
    handles, lats, seen = [], [], {}
    while backlog or fleet.has_work or any(
            not h.done for _, h in handles):
        now = time.perf_counter() - t0
        while backlog and backlog[0]["t"] <= now:
            ev = backlog[0]
            try:
                handles.append((ev, fleet.submit(
                    ev["prompt"], ev["new_tokens"])))
                backlog.pop(0)
            except QueueFull:
                break  # re-offer on the next pump
        fleet.step()
        for i, (_ev, h) in enumerate(handles):
            n = len(h.tokens)
            prev_n = seen.get(i, 0)
            if n > prev_n:
                if prev_n > 0:
                    lats.extend(
                        [by_id[h.replica_id].last_step_s]
                        * (n - prev_n))
                seen[i] = n
        assert time.perf_counter() - t0 < hang_s, "disagg wave hung"
    wall = time.perf_counter() - t0
    assert all(h.done for _, h in handles), "a stream never settled"
    return {
        "handles": handles,
        "tokens_per_s": sum(len(h.tokens) for _, h in handles) / wall,
        "decode_lat_p50_s": float(np.percentile(lats, 50)),
        "decode_lat_p99_s": float(np.percentile(lats, 99)),
        "wall_s": wall,
    }


def _disagg_capacity(args, model, variables) -> float:
    """Sustained unified-fleet capacity on the LONG-PROMPT trace
    (tokens/s, closed loop) — the offered-rate yardstick both halves
    of every pair share."""
    fleet = _disagg_fleet(args, model, variables,
                          ["unified"] * args.disagg_replicas)
    try:
        _disagg_warm(fleet, args)
        events, _ = _disagg_trace(args, seed=999)
        t0 = time.perf_counter()
        handles, backlog = [], list(events)
        while backlog or fleet.has_work:
            while backlog:
                ev = backlog[0]
                try:
                    handles.append(fleet.submit(ev["prompt"],
                                                ev["new_tokens"]))
                    backlog.pop(0)
                except QueueFull:
                    break
            fleet.step()
            assert time.perf_counter() - t0 < 600.0, \
                "disagg capacity leg hung"
        wall = time.perf_counter() - t0
        assert all(h.done for h in handles)
        return sum(len(h.tokens) for h in handles) / wall
    finally:
        fleet.close()


def _disagg_trace(args, *, seed: int):
    """The r12 bursty multi-turn trace with the prompt knobs turned
    to LONG: system prompts of ``--disagg-prompt-base`` tokens,
    capped at the prefill buffer — prompts an order of magnitude past
    the per-turn decode budget, so an admission genuinely contends
    with decode on a unified replica. Two edits over the r12 shape:

    - Per-SESSION system prompts (the r12 trace shares 4 across the
      fleet, which the prefix cache absorbs into a handful of cold
      prefills — with the cache necessarily ON for the hand-off
      chain, a shared-prefix trace measures cache hits, not prefill
      interference). Each session's FIRST turn is cache-cold — the
      long cold prompt disaggregation exists for — while later turns
      still exercise the affinity + prefix-cache path.
    - Outputs stretched 4x (still Pareto-shaped, capped at the KV
      budget): decode cadence is the measured quantity, so each
      stream must live long enough that its p99 reflects
      steady-state ticks."""
    events, _ = _trace_schedule(
        args.disagg_requests, args.vocab, seed,
        prompt_base=args.disagg_prompt_base,
        prompt_cap=_disagg_prefill_len(args) - 8)
    rng = np.random.default_rng(seed + 1)
    bases: dict = {}
    out = []
    for e in events:
        base = bases.setdefault(e["session"], rng.integers(
            0, args.vocab, size=args.disagg_prompt_base)
            .astype(np.int32).tolist())
        prompt = (base + e["prompt"][args.disagg_prompt_base:])[
            :_disagg_prefill_len(args) - 8]
        out.append(dict(e, prompt=prompt, new_tokens=int(min(
            4 * e["new_tokens"], args.max_len - len(prompt) - 8))))
    mean_new = float(np.mean([e["new_tokens"] for e in out]))
    return out, mean_new


def _disagg_leg(args):
    """The ISSUE 17 leg: same-N unified vs role-split fleets, PAIRED
    per repeat on the identical long-prompt schedule. The split
    fleet must hold decode-side p99 token latency <= 0.8x unified
    AND aggregate tok/s >= 0.95x, every pair directional, with every
    stream token-exact across the two fleet shapes and zero
    recompiles on the decode replicas."""
    from pddl_tpu.obs import RequestTracer

    n = args.disagg_replicas
    n_prefill = args.disagg_prefill_replicas or max(1, n // 2)
    assert 1 <= n_prefill < n, "need at least one replica per role"
    model = GPT(vocab_size=args.vocab, max_len=args.max_len,
                embed_dim=args.embed_dim, depth=args.depth,
                num_heads=args.heads, attention="reference")
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32),
                        train=False)["params"]
    variables = {"params": params}
    cap_tps = _disagg_capacity(args, model, variables)
    events, mean_new = _disagg_trace(args, seed=23)
    offered_rps = args.disagg_load * cap_tps / mean_new
    schedule = _scale_schedule(events, offered_rps)
    split_roles = ["prefill"] * n_prefill + ["decode"] * (n - n_prefill)
    decode_ids = set(range(n_prefill, n))
    _log(f"disagg: unified capacity {cap_tps:,.0f} tok/s (N={n}), "
         f"offering {offered_rps:.2f} req/s "
         f"({args.disagg_load:.0%} load, mean_new {mean_new:.1f}); "
         f"split {n_prefill} prefill + {n - n_prefill} decode")
    uni_p99s, split_p99s, p99_ratios, tps_ratios = [], [], [], []
    uni_tps_all, split_tps_all, handoff_ms_all = [], [], []
    exact_all = True
    handoffs_total = handoff_failures_total = 0
    decode_counts_ok = True
    split_metrics_last = None
    for rep in range(args.repeats):
        fleet = _disagg_fleet(args, model, variables, ["unified"] * n)
        try:
            _disagg_warm(fleet, args)
            uni = _disagg_wave(fleet, schedule)
        finally:
            fleet.close()
        oracle = {tuple(ev["prompt"]): list(h.tokens)
                  for ev, h in uni["handles"]}
        tracer = RequestTracer()
        fleet = _disagg_fleet(args, model, variables, split_roles,
                              tracer=tracer)
        try:
            _disagg_warm(fleet, args)
            split = _disagg_wave(fleet, schedule)
            for ev, h in split["handles"]:
                if list(h.tokens) != oracle[tuple(ev["prompt"])]:
                    exact_all = False
            m = fleet.metrics
            handoffs_total += m.handoffs_completed
            handoff_failures_total += m.handoffs_failed
            hand_ms = [e["ms"] for e in tracer.events_named("handoff")]
            if hand_ms:
                handoff_ms_all.append(float(np.median(hand_ms)))
            counts = {k: v for k, v in fleet.compile_counts().items()
                      if int(k.split("/")[0][1:]) in decode_ids}
            decode_counts_ok = decode_counts_ok and bool(counts) \
                and all(v == 1 for v in counts.values())
            split_metrics_last = m.snapshot()
        finally:
            fleet.close()
        uni_p99s.append(uni["decode_lat_p99_s"])
        split_p99s.append(split["decode_lat_p99_s"])
        p99_ratios.append(split["decode_lat_p99_s"]
                          / uni["decode_lat_p99_s"])
        tps_ratios.append(split["tokens_per_s"] / uni["tokens_per_s"])
        uni_tps_all.append(uni["tokens_per_s"])
        split_tps_all.append(split["tokens_per_s"])
        _log(f"disagg pair {rep}: decode p99 "
             f"{uni['decode_lat_p99_s'] * 1e3:.1f}ms -> "
             f"{split['decode_lat_p99_s'] * 1e3:.1f}ms "
             f"({p99_ratios[-1]:.3f}x), tok/s retained "
             f"{tps_ratios[-1]:.3f}x, handoffs "
             f"{m.handoffs_completed}, token-exact {exact_all}")
    p99_med, p99_spread = median_spread(p99_ratios)
    tps_med, tps_spread = median_spread(tps_ratios)
    return {
        "trace": "bursty multi-turn long-prompt sessions "
                 f"(system prompts {args.disagg_prompt_base} tokens, "
                 "bounded-Pareto output lengths stretched 4x)",
        "replicas": n,
        "split_shape": f"{n_prefill} prefill + {n - n_prefill} "
                       "decode, block-granular KV hand-off",
        "n_requests_per_wave": args.disagg_requests,
        "mean_new_tokens": round(mean_new, 2),
        "offered_load_x_capacity": args.disagg_load,
        "unified_capacity_tokens_per_s": round(cap_tps, 1),
        "unified_tokens_per_s": round(median_spread(uni_tps_all)[0], 1),
        "split_tokens_per_s": round(median_spread(split_tps_all)[0], 1),
        "tokens_per_s_retained_x": round(tps_med, 3),
        "tokens_per_s_retained_per_pair": [round(r, 3)
                                           for r in tps_ratios],
        "tokens_per_s_retained_spread_pct": round(tps_spread, 2),
        "tokens_per_s_retained_floor": 0.95,
        "unified_decode_lat_p99_ms": round(
            median_spread(uni_p99s)[0] * 1e3, 2),
        "split_decode_lat_p99_ms": round(
            median_spread(split_p99s)[0] * 1e3, 2),
        "decode_p99_interference": round(p99_med, 3),
        "decode_p99_interference_per_pair": [round(r, 3)
                                             for r in p99_ratios],
        "decode_p99_interference_spread_pct": round(p99_spread, 2),
        "decode_p99_interference_bound": 0.8,
        "all_pairs_directional": all(r < 1.0 for r in p99_ratios),
        "handoff_ms": round(float(np.median(handoff_ms_all)), 3),
        "handoffs_completed_total": int(handoffs_total),
        "handoffs_failed_total": int(handoff_failures_total),
        "streams_token_exact_split_vs_unified": exact_all,
        "zero_recompiles_decode_replicas": decode_counts_ok,
        "split_fleet_metrics_last_repeat": split_metrics_last,
    }


def _autoscale_cfg(args) -> dict:
    """Worker config for the autoscale leg. Two deliberate choices:
    small enough that a scale-up's spawn+warmup completes in seconds
    (the leg measures the CONTROL LOOP against a diurnal day
    compressed to ~minutes, and a spawn costing a whole period would
    measure jax import time instead), yet slow enough per replica
    (~1.1k tok/s: depth 6, 4 slots) that genuine overload is
    expressible at request rates the single-threaded router loop
    sustains — a faster engine turns the open-loop replay into a
    de-facto closed loop and no static baseline can ever saturate.
    Prefix reuse off: the 4-program engine keeps the zero-recompile
    pin exact."""
    del args
    return dict(vocab=64, max_len=128, embed_dim=192, depth=6, heads=4,
                slots=4, prefill_len=64,
                max_queue_depth=8, param_seed=0,
                aging_s=3.0, prefix_cache_blocks=0)


def _autoscale_admission():
    from pddl_tpu.serve.fleet import AdmissionControl

    # The r12 fast-acting ladder: the brownout must engage within a few
    # rejected submits — it is the LOSING condition the autoscaler is
    # supposed to pre-empt, so it has to be armed and quick.
    return AdmissionControl(
        detector_kw=dict(window_s=1.0, min_samples=4),
        brownout_kw=dict(high=0.2, low=0.05, escalate_hold_s=0.0,
                         recover_hold_s=0.5, output_cap=12))


def _autoscale_fleet(args, cfg, *, replicas: int, autoscale: bool):
    import subprocess

    from pddl_tpu.serve.fleet import (
        FleetAutoscaler,
        FleetRouter,
        ProcessReplica,
    )

    def spawn(rid, wait_ready):
        return ProcessReplica(rid, {**cfg, "replica_id": rid},
                              stderr=subprocess.DEVNULL,
                              wait_ready=wait_ready,
                              ready_timeout_s=120.0)

    reps = [spawn(i, False) for i in range(replicas)]
    for r in reps:
        r.wait_ready()
    fleet = FleetRouter(reps, affinity_block_size=8, affinity_blocks=2,
                        respawn=False, admission=_autoscale_admission())
    if autoscale:
        # Target-utilization scaling: grow at ~60% of a slot pool's
        # assigned load per replica (the diurnal ramp is gradual, so an
        # early trigger buys the ~5 s spawn its head start), shrink at
        # ~30% with calm pressure held 2 s so the sinusoid's shoulders
        # do not flap the fleet. up_pressure 0.08 sits well below the
        # ladder's high mark (0.2): pressure is the backstop that
        # engages capacity ahead of brownout when load alone lags.
        # Grow on genuine saturation, not comfort: PRESSURE (0.08,
        # well under the ladder's 0.2 high mark) is the early trigger —
        # ramp sheds feed the detector within a window — and the load
        # trigger only fires at a full slot-pool of assigned backlog
        # per replica. Shrink at ~50% utilization held 2 s. The gap
        # between the two is what keeps mean fleet size tracking the
        # demand curve instead of hugging max_replicas; it also keeps
        # the projection guard (veto at up_load) off the knife edge.
        slots = cfg["slots"]
        FleetAutoscaler(
            fleet, lambda rid: spawn(rid, False),
            min_replicas=replicas, max_replicas=args.autoscale_max,
            up_pressure=0.08, down_pressure=0.02,
            up_load=1.0 * slots, down_load=0.5 * slots,
            up_hold_s=0.1, down_hold_s=2.0, cooldown_s=0.25,
            spawn_backoff_base_s=0.5, spawn_backoff_max_s=10.0)
    return fleet


def _autoscale_capacity(args, cfg) -> float:
    """Single-replica sustained capacity (tokens/s) on the trace's
    request shape, closed-loop — the unit the diurnal offered load is
    expressed in."""
    from pddl_tpu.serve.fleet import diurnal_trace

    fleet = _autoscale_fleet(args, cfg, replicas=1, autoscale=False)
    try:
        events, _ = diurnal_trace(6 * cfg["slots"], cfg["vocab"],
                                  seed=999,
                                  duration_s=1.0, prompt_cap=30,
                                  new_tokens_base=16,
                                  new_tokens_scale=12.0,
                                  new_tokens_cap=80)
        t0 = time.perf_counter()
        handles = []
        backlog = list(events)
        deadline = t0 + 300.0
        while backlog or fleet.has_work:
            while backlog:
                ev = backlog[0]
                try:
                    handles.append(fleet.submit(
                        ev["prompt"], ev["new_tokens"],
                        session=ev["session"]))
                    backlog.pop(0)
                except QueueFull:
                    break
            fleet.step()
            assert time.perf_counter() < deadline, "capacity leg hung"
        wall = time.perf_counter() - t0
        assert all(h.done for h in handles)
        return sum(len(h.tokens) for h in handles) / wall
    finally:
        fleet.close()


def _autoscale_wave(args, cfg, schedule, *, static_n=None,
                    autoscale=False, hang_s=420.0):
    """One diurnal replay: a static-N fleet, or an autoscaled fleet
    starting at ``autoscale_min``. Returns the report plus the fleet's
    scale/migration counters and the zero-recompile verdict."""
    from pddl_tpu.serve.fleet import replay_trace
    from pddl_tpu.serve.request import RequestState

    n0 = args.autoscale_min if autoscale else static_n
    fleet = _autoscale_fleet(args, cfg, replicas=n0, autoscale=autoscale)
    try:
        # max_attempts 8: a polite client keeps honoring hints while
        # the diurnal ramp (or a scale-up in flight) catches up —
        # terminal sheds then measure genuinely unservable demand, not
        # client impatience.
        rep = replay_trace(fleet, schedule, honor_hints=True,
                           max_attempts=8, hang_s=hang_s,
                           clock=time.perf_counter)
        lost = rep.stragglers + sum(
            1 for _, h in rep.handles
            if h.state is RequestState.FAILED)
        finished = sum(1 for _, h in rep.handles
                       if h.state is RequestState.FINISHED)
        counts = fleet.compile_counts()
        snap = fleet.metrics.snapshot()
        scaler = fleet.autoscaler
        return {
            "report": rep,
            "lost": lost,
            "attainment": finished / max(len(schedule), 1),
            "rejected": sum(rep.rejects.values()),
            "scale_up_events": snap["scale_up_events"],
            "scale_down_events": snap["scale_down_events"],
            "scale_down_migrated": snap["scale_down_migrated"],
            "zero_recompiles": bool(counts) and all(
                v == 1 for v in counts.values()),
            "fleet_metrics": snap,
            "autoscale_metrics": (scaler.metrics.snapshot()
                                  if scaler is not None else None),
        }
    finally:
        fleet.close()


def _autoscale_leg(args):
    """The r16 leg: the same seeded diurnal trace (1 period,
    peak:trough ``--autoscale-peak-trough``) through (a) static fleets
    at each N in ``--autoscale-static`` and (b) the autoscaled fleet
    (min..max replicas), admission armed everywhere. The headline is
    AlpaServe's framing made concrete: goodput per replica-hour —
    finished tokens per hour of replica (spawning included) the fleet
    burned — autoscaled over the BEST static, PAIRED per repeat.
    Secondary pins: brownout rung time strictly below the
    under-provisioned static, zero lost requests anywhere, every
    scale-down migration zero-loss, zero recompiles."""
    from pddl_tpu.serve.fleet import diurnal_trace

    cfg = _autoscale_cfg(args)
    cap1 = _autoscale_capacity(args, cfg)
    _log(f"autoscale: single-replica capacity {cap1:,.0f} tok/s")
    # Offered MEAN load in capacity units; the sinusoid swings
    # peak:trough around it (peak = mean * 2r/(r+1)).
    duration = args.autoscale_duration
    ratio = args.autoscale_peak_trough
    # Fat decodes (mean ~30 new tokens, prompts capped at 30): the
    # offered TOKEN load reaches the target at a request rate the
    # single-threaded router's synchronous submit path sustains.
    shape = dict(prompt_cap=30, new_tokens_base=16,
                 new_tokens_scale=12.0, new_tokens_cap=80)
    events, mean_new = diurnal_trace(
        max(int(args.autoscale_offered * cap1 / 30.0 * duration), 64),
        cfg["vocab"], seed=29, duration_s=duration, periods=1.0,
        peak_to_trough=ratio, **shape)
    # The generator's mean_new is a draw, not a constant — rescale the
    # request count so offered TOKENS hit the target, then regenerate.
    n_requests = max(int(args.autoscale_offered * cap1 / mean_new
                         * duration), 64)
    events, mean_new = diurnal_trace(
        n_requests, cfg["vocab"], seed=29, duration_s=duration,
        periods=1.0, peak_to_trough=ratio, **shape)
    _log(f"autoscale: {n_requests} requests over {duration}s, mean_new "
         f"{mean_new:.1f}, offered mean "
         f"{args.autoscale_offered:.2f}x capacity, peak:trough {ratio}")

    # Static sweep, ATTAINMENT-QUALIFIED (AlpaServe's framing: SLO
    # attainment per resource-hour, not raw density): a static fleet
    # only counts as a baseline when it actually SERVED the demand —
    # >= `floor` of offered requests finished, hint-honoring retries
    # allowed. Without the floor, raw goodput-per-replica-hour crowns
    # the saturated under-provisioned fleet that shed a fifth of its
    # callers (the smoke run's static-1), which is not a capacity
    # planning anyone ships.
    floor = args.autoscale_attainment_floor
    static_ns = [int(n) for n in args.autoscale_static.split(",") if n]
    statics = []
    for n in static_ns:
        w = _autoscale_wave(args, cfg, events, static_n=n)
        r = w["report"]
        statics.append({
            "replicas": n,
            "goodput_tokens": r.goodput_tokens,
            "goodput_per_replica_hour": round(
                r.goodput_per_replica_hour, 1),
            "replica_hours": round(r.replica_hours, 6),
            "attainment": round(w["attainment"], 4),
            "qualified": w["attainment"] >= floor,
            "brownout_rung_time_s": round(r.rung_seconds, 3),
            "rejected_terminal": w["rejected"],
            "retried_after_hint": r.retried_after_hint,
            "lost": w["lost"],
            "zero_recompiles": w["zero_recompiles"],
        })
        _log(f"autoscale static N={n}: gphr "
             f"{statics[-1]['goodput_per_replica_hour']:,.0f}, "
             f"attainment {w['attainment']:.3f} "
             f"({'ok' if statics[-1]['qualified'] else 'FAILS floor'}), "
             f"rung {statics[-1]['brownout_rung_time_s']}s, shed "
             f"{w['rejected']}, lost {w['lost']}")
    qualified = [s for s in statics if s["qualified"]]
    best = max(qualified or statics,
               key=lambda s: s["goodput_per_replica_hour"])
    under = min(statics, key=lambda s: s["replicas"])

    repeats = max(args.repeats, 5)
    auto_gphr, ratios, rungs, attains = [], [], [], []
    scale_ups, scale_downs, migrated_total = [], [], 0
    lost_total = 0
    counts_ok = True
    last = None
    for rep_i in range(repeats):
        # PAIRED: autoscaled and best-static back to back, ratio per
        # pair — host drift cancels in the quotient.
        wa = _autoscale_wave(args, cfg, events, autoscale=True)
        wb = _autoscale_wave(args, cfg, events,
                             static_n=best["replicas"])
        ra, rb = wa["report"], wb["report"]
        auto_gphr.append(ra.goodput_per_replica_hour)
        ratios.append(ra.goodput_per_replica_hour
                      / max(rb.goodput_per_replica_hour, 1e-9))
        rungs.append(ra.rung_seconds)
        attains.append(wa["attainment"])
        scale_ups.append(wa["scale_up_events"])
        scale_downs.append(wa["scale_down_events"])
        migrated_total += wa["scale_down_migrated"]
        lost_total += wa["lost"] + wb["lost"]
        counts_ok = counts_ok and wa["zero_recompiles"] \
            and wb["zero_recompiles"]
        last = wa
        _log(f"autoscale pair {rep_i}: gphr {ra.goodput_per_replica_hour:,.0f}"
             f" vs static-{best['replicas']} "
             f"{rb.goodput_per_replica_hour:,.0f} "
             f"({ratios[-1]:.3f}x), attainment {wa['attainment']:.3f}, "
             f"scale {wa['scale_up_events']}up/"
             f"{wa['scale_down_events']}down, migrated "
             f"{wa['scale_down_migrated']}, rung {ra.rung_seconds:.2f}s")
    gphr_med, gphr_spread = median_spread(auto_gphr)
    ratio_med, ratio_spread = median_spread(ratios)
    # Plain median: a spread is undefined at a zero median, and an
    # all-zero rung series (the autoscaler fully pre-empting brownout)
    # is the GOOD case, not an error.
    rung_med = float(np.median(rungs))
    return {
        "trace": (f"seeded diurnal (1 period over {duration}s, "
                  f"peak:trough {ratio}), heavy-tail multi-turn "
                  "sessions (r12 mix), 35/15/50 "
                  "interactive/batch/best_effort"),
        "n_requests": n_requests,
        "duration_s": duration,
        "peak_to_trough": ratio,
        "mean_new_tokens": round(mean_new, 2),
        "capacity_single_replica_tokens_per_s": round(cap1, 1),
        "offered_mean_x_capacity": args.autoscale_offered,
        "autoscale_min_replicas": args.autoscale_min,
        "autoscale_max_replicas": args.autoscale_max,
        "attainment_qualification": (
            f"a static baseline must FINISH >= {floor:.0%} of offered "
            "requests (hint-honoring retries allowed) to count as "
            "best-static; density bought by shedding callers is not a "
            "baseline (AlpaServe: SLO attainment per resource-hour)"),
        "attainment_floor": floor,
        "static_sweep": statics,
        "best_static_replicas": best["replicas"],
        "best_static_qualified": bool(qualified),
        "attainment_autoscaled": round(min(attains), 4),
        "goodput_per_replica_hour": round(gphr_med, 1),
        "goodput_per_replica_hour_spread_pct": round(gphr_spread, 2),
        "goodput_per_replica_hour_vs_best_static_x": round(ratio_med, 3),
        "goodput_vs_best_static_per_pair": [round(r, 3) for r in ratios],
        "goodput_vs_best_static_spread_pct": round(ratio_spread, 2),
        # min(ups) + min(downs), NOT min(u+d): the headline must pin
        # BOTH directions — a fleet that only ever grows (scale-down
        # broken, e.g. the projection guard vetoing every shrink) must
        # drop this number loudly even if its up-count compensates.
        "scale_events": int(min(scale_ups) + min(scale_downs)),
        "scale_up_events_per_wave": scale_ups,
        "scale_down_events_per_wave": scale_downs,
        "migrated_zero_lost": migrated_total if lost_total == 0 else 0,
        "requests_lost_total": lost_total,
        "brownout_rung_time_autoscaled_s": round(rung_med, 3),
        "brownout_rung_time_static_under_s":
            under["brownout_rung_time_s"],
        "rung_time_below_static_under": bool(
            rung_med < under["brownout_rung_time_s"]),
        "zero_recompiles_all_replicas": counts_ok,
        "fleet_metrics_last_repeat": last["fleet_metrics"],
        "autoscale_metrics_last_repeat": last["autoscale_metrics"],
    }


def _ctrlplane_cfg() -> dict:
    """The leg's sized worker config (the r16/r17 small-model
    discipline: control-plane costs are host-side, a big model only
    slows the referee)."""
    return dict(vocab=64, max_len=128, embed_dim=64, depth=2, heads=2,
                slots=4, prefill_len=32, max_queue_depth=96,
                param_seed=0, prefix_cache_blocks=0)


def _ctrl_wave(fleet, prompts, new_tokens: int, *, hang_s: float = 300.0,
               priority=None):
    """Closed-loop wave: submit everything, pump to terminal. Returns
    (handles, tokens_per_s, wall_s)."""
    t0 = time.perf_counter()
    handles = []
    for p in prompts:
        kw = {} if priority is None else {"priority": priority}
        handles.append(fleet.submit(list(p), new_tokens, **kw))
    deadline = time.perf_counter() + hang_s
    while any(not h.done for h in handles) \
            and time.perf_counter() < deadline:
        fleet.step()
    wall = time.perf_counter() - t0
    assert all(h.done for h in handles), "a wave request never settled"
    return handles, sum(len(h.tokens) for h in handles) / wall, wall


def _ctrlplane_wire_leg(args, repeats: int) -> dict:
    """Paired clean vs wire-fault-storm waves through process
    replicas: the framed transport must hold throughput and
    token-exactness at a 1% injected frame-fault rate."""
    import subprocess

    from pddl_tpu.serve.fleet import (
        FleetRouter,
        ProcessReplica,
        WireFaultPlan,
    )
    from pddl_tpu.serve.fleet.worker import build_engine

    cfg = _ctrlplane_cfg()
    new_tokens = 96
    n_requests = 64
    oracle = build_engine(cfg)
    refs = {}

    def ref_for(prompt):
        key = tuple(prompt)
        if key not in refs:
            out = generate(oracle.model, {"params": oracle._params},
                           jnp.asarray(prompt, jnp.int32)[None],
                           new_tokens)
            refs[key] = np.asarray(out)[0, len(prompt):].tolist()
        return refs[key]

    def spawn(plan_seed=None):
        reps = []
        for i in range(2):
            plan = (None if plan_seed is None else WireFaultPlan(
                plan_seed + i, corrupt_rate=0.004, duplicate_rate=0.002,
                reorder_rate=0.002, drop_rate=0.002))
            # Tight ping/resend cadences: gap-detection latency is the
            # storm's whole cost, and the clean fleet runs the same
            # cadence so the pair stays fair.
            reps.append(ProcessReplica(
                i, {**cfg, "replica_id": i}, stderr=subprocess.DEVNULL,
                wire_fault_plan=plan, ping_interval_s=0.01,
                resend_timeout_s=0.01, wait_ready=False))
        for r in reps:
            r.wait_ready()
        return FleetRouter(reps, affinity_block_size=8,
                           affinity_blocks=1, respawn=False)

    ratios, clean_all, storm_all = [], [], []
    rejects = retries = injected = 0
    exact = True
    # BOTH fleets are long-lived and warmed with an untimed wave, so
    # every pair compares equally-warm processes — a fresh-spawned
    # storm fleet against a wave-warmed clean one would measure
    # process warmth, not the transport.
    clean_fleet = spawn(None)
    storm = spawn(1000)
    try:
        warm_rng = np.random.default_rng(899)
        warm = [warm_rng.integers(0, cfg["vocab"], size=12).tolist()
                for _ in range(n_requests)]
        _ctrl_wave(clean_fleet, warm, new_tokens)
        _ctrl_wave(storm, warm, new_tokens)
        for rep in range(repeats):
            rng = np.random.default_rng(900 + rep)
            prompts = [rng.integers(0, cfg["vocab"], size=12).tolist()
                       for _ in range(n_requests)]
            _, tps_clean, _ = _ctrl_wave(clean_fleet, prompts,
                                         new_tokens)
            handles, tps_storm, _ = _ctrl_wave(storm, prompts,
                                               new_tokens)
            for p, h in zip(prompts, handles):
                if h.state.value != "finished" \
                        or h.tokens != ref_for(p):
                    exact = False
            clean_all.append(tps_clean)
            storm_all.append(tps_storm)
            ratios.append(tps_storm / tps_clean)
            _log(f"ctrlplane wire pair {rep}: {tps_clean:,.0f} -> "
                 f"{tps_storm:,.0f} tok/s ({ratios[-1]:.3f}x)")
        rejects = storm.metrics.wire_crc_rejects
        retries = storm.metrics.wire_retries
        for slot in storm.replicas:
            injected += slot.driver._plan.total_injected
    finally:
        clean_fleet.close()
        storm.close()
    ratio_med, ratio_spread = median_spread(ratios)
    return {
        "injected_fault_rate_per_frame": 0.01,
        "n_requests_per_wave": n_requests,
        "new_tokens": new_tokens,
        "tokens_per_s_clean": round(median_spread(clean_all)[0], 1),
        "tokens_per_s_storm": round(median_spread(storm_all)[0], 1),
        "throughput_retained_x": round(ratio_med, 3),
        "throughput_retained_per_pair": [round(r, 3) for r in ratios],
        "throughput_retained_spread_pct": round(ratio_spread, 2),
        "wire_faults_injected_total": injected,
        "wire_crc_rejects_total": rejects,
        "wire_retries_total": retries,
        # Zero corrupt frames accepted is a codec property; the
        # referee is every storm stream byte-identical to the oracle.
        "corrupt_frames_accepted": 0 if exact else None,
        "streams_token_exact": exact,
    }


def _dtrace_leg(args, repeats: int) -> dict:
    """Paired tracing-off vs tracing-on waves through 2 process
    replicas: fleet-wide distributed tracing (ISSUE 19) must ride
    along at >= 0.95x throughput while every request's spans ship
    back over the pipe and stitch gap-free across processes."""
    import subprocess

    from pddl_tpu.obs.assemble import stitch
    from pddl_tpu.serve.fleet import FleetRouter, ProcessReplica
    from pddl_tpu.serve.fleet.worker import build_engine

    cfg = _ctrlplane_cfg()
    # Waves sized so one (off, on) attempt fits inside a host noise
    # burst's dwell time (~1s per wave): the per-attempt RATIO then
    # sees the same noise on both sides and cancels it.
    new_tokens = 64
    n_requests = 48
    oracle = build_engine(cfg)
    refs = {}

    def ref_for(prompt):
        key = tuple(prompt)
        if key not in refs:
            out = generate(oracle.model, {"params": oracle._params},
                           jnp.asarray(prompt, jnp.int32)[None],
                           new_tokens)
            refs[key] = np.asarray(out)[0, len(prompt):].tolist()
        return refs[key]

    def spawn(traced):
        reps = []
        for i in range(2):
            wcfg = {**cfg, "replica_id": i}
            if traced:
                wcfg["dtrace"] = True
            # The same tight ping cadence on BOTH fleets: pongs carry
            # the traced fleet's span batches AND clock samples, and
            # the untraced fleet must pay the identical ping cost so
            # the pair isolates tracing, not heartbeat traffic.
            reps.append(ProcessReplica(
                i, wcfg, stderr=subprocess.DEVNULL,
                ping_interval_s=0.01, wait_ready=False))
        for r in reps:
            r.wait_ready()
        return FleetRouter(reps, respawn=False,
                           dtrace=True if traced else None)

    ratios, off_all, on_all = [], [], []
    exact = True
    # Long-lived fleets, both warmed untimed (the r19 wire-leg
    # discipline): every pair compares equally-warm processes.
    fleet_off = spawn(False)
    fleet_on = spawn(True)
    try:
        warm_rng = np.random.default_rng(1899)
        warm = [warm_rng.integers(0, cfg["vocab"], size=12).tolist()
                for _ in range(n_requests)]
        _ctrl_wave(fleet_off, warm, new_tokens)
        _ctrl_wave(fleet_on, warm, new_tokens)
        for rep in range(repeats):
            rng = np.random.default_rng(1900 + rep)
            prompts = [rng.integers(0, cfg["vocab"], size=12).tolist()
                       for _ in range(n_requests)]
            # Nine alternated (off, on) attempts; the pair's ratio is
            # the MEDIAN of the per-attempt ratios. On a shared 1-core
            # host, noise bursts dwell for seconds — longer than any
            # wave — so a burst lands on BOTH waves of an attempt and
            # cancels in that attempt's ratio, while the median sheds
            # the attempts where it straddled only one side. The order
            # flips each attempt so neither fleet always runs first.
            attempt_ratios, attempt_off, attempt_on = [], [], []
            for k in range(9):
                first, second = ((fleet_off, fleet_on) if k % 2 == 0
                                 else (fleet_on, fleet_off))
                _, t_first, _ = _ctrl_wave(first, prompts, new_tokens)
                handles, t_second, _ = _ctrl_wave(second, prompts,
                                                  new_tokens)
                t_off, t_on = ((t_first, t_second) if k % 2 == 0
                               else (t_second, t_first))
                on_handles = handles if k % 2 == 0 else None
                if on_handles is not None:
                    for p, h in zip(prompts, on_handles):
                        if h.state.value != "finished" \
                                or h.tokens != ref_for(p):
                            exact = False
                attempt_ratios.append(t_on / t_off)
                attempt_off.append(t_off)
                attempt_on.append(t_on)
            # Burst rejection: an attempt where either side ran well
            # below its own best this pair caught external load on one
            # wave — its ratio measures the neighbour, not tracing.
            # Median the attempts that ran clean on BOTH sides.
            best_off, best_on = max(attempt_off), max(attempt_on)
            kept = [i for i in range(len(attempt_ratios))
                    if attempt_off[i] >= 0.9 * best_off
                    and attempt_on[i] >= 0.9 * best_on]
            if len(kept) < 3:  # storm ate the pair: keep everything
                kept = list(range(len(attempt_ratios)))
            tps_off = float(np.median([attempt_off[i] for i in kept]))
            tps_on = float(np.median([attempt_on[i] for i in kept]))
            off_all.append(tps_off)
            on_all.append(tps_on)
            ratios.append(float(np.median(
                [attempt_ratios[i] for i in kept])))
            _log(f"dtrace pair {rep}: {tps_off:,.0f} -> "
                 f"{tps_on:,.0f} tok/s ({ratios[-1]:.3f}x)")
        # Drain the tail: the last wave's span batches ride pong reads,
        # so pump past a few ping intervals before the referee stitches.
        drain = time.perf_counter() + 1.0
        while time.perf_counter() < drain:
            fleet_on.step()
            time.sleep(0.01)
        records = fleet_on.dtrace.records()
        traces = stitch(records)
        gap_free = sum(1 for t in traces.values() if not t.gaps())
        replica_spans = sum(1 for r in records
                            if r.get("kind") == "span")
        dropped = sum(int(getattr(slot.driver, "spans_dropped", 0))
                      for slot in fleet_on.replicas)
    finally:
        fleet_off.close()
        fleet_on.close()
    ratio_med, ratio_spread = median_spread(ratios)
    floor = 0.95
    return {
        "process_replicas": 2,
        "n_requests_per_wave": n_requests,
        "new_tokens": new_tokens,
        "tokens_per_s_tracing_off":
            round(median_spread(off_all)[0], 1),
        "tokens_per_s_tracing_on":
            round(median_spread(on_all)[0], 1),
        "tracing_on_over_off_x": round(ratio_med, 3),
        "tracing_on_over_off_per_pair": [round(r, 3) for r in ratios],
        "tracing_on_over_off_spread_pct": round(ratio_spread, 2),
        "tracing_retained_floor": floor,
        "all_pairs_above_floor": all(r >= floor for r in ratios),
        "traces_stitched_total": len(traces),
        "traces_gap_free_total": gap_free,
        "traces_all_gap_free": gap_free == len(traces),
        "replica_spans_collected_total": replica_spans,
        "spans_dropped_remote_total": dropped,
        "streams_token_exact": exact,
    }


def _ctrlplane_recovery_leg(model, variables, args,
                            repeats: int) -> dict:
    """Router WAL crash + recover: wall time from ``recover()`` until
    every revived stream moved PAST its mirrored length (the streams
    are serving again), plus full-stream token-exactness."""
    from pddl_tpu.serve.fleet import (
        FleetRouter,
        LocalReplica,
        RouterJournal,
    )

    def factory():
        return ServeEngine(model, variables, max_slots=4,
                           prefill_len=32, max_queue_depth=96,
                           prefix_cache_blocks=0)

    def replicas():
        return [LocalReplica(i, factory) for i in range(2)]

    new_tokens = 32
    recovery_all, revived_all = [], []
    exact = True
    recompile_free = True
    for rep in range(repeats):
        d = tempfile.mkdtemp(prefix="pddl-ctrlplane-wal-")
        try:
            rng = np.random.default_rng(700 + rep)
            prompts = [rng.integers(0, 64, size=12).tolist()
                       for _ in range(12)]
            refs = {tuple(p): _make_ref(model, variables, p, new_tokens)
                    for p in prompts}
            fleet = FleetRouter(replicas(), affinity_block_size=8,
                                affinity_blocks=1, respawn=False,
                                journal=RouterJournal(
                                    d, fsync_batch_records=16))
            for p in prompts:
                fleet.submit(list(p), new_tokens)
            for _ in range(10):  # mid-stream: mirrors partly populated
                fleet.step()
            # SIGKILL-equivalent: the router object is abandoned with
            # its buffers unflushed; the WAL is all that survives.
            t0 = time.perf_counter()
            recovered, revived = FleetRouter.recover(
                d, replicas(), affinity_block_size=8,
                affinity_blocks=1, respawn=False)
            at_recovery = {rid: len(fh.tokens)
                           for rid, fh in revived.items()}
            for _ in range(100000):
                if not any(len(fh.tokens) <= at_recovery[rid]
                           and not fh.done
                           for rid, fh in revived.items()):
                    break
                recovered.step()
            recovery_s = time.perf_counter() - t0
            recovered.run(max_steps=100000)
            for fh in revived.values():
                if fh.state.value != "finished" or fh.tokens != refs[
                        tuple(int(t) for t in fh.request.prompt)]:
                    exact = False
            counts = recovered.compile_counts()
            if not counts or any(v != 1 for v in counts.values()):
                recompile_free = False
            recovered.close()
            recovery_all.append(recovery_s)
            revived_all.append(len(revived))
            _log(f"ctrlplane recovery pair {rep}: {len(revived)} "
                 f"streams resumed in {recovery_s:.3f}s")
        finally:
            shutil.rmtree(d, ignore_errors=True)
    med, spread = median_spread(recovery_all)
    return {
        "kill": "router abandoned mid-stream with unflushed buffers "
                "(WAL-only recovery), fresh replicas",
        "recovery_s": round(med, 4),
        "recovery_s_spread_pct": round(spread, 2),
        "recovery_s_per_repeat": [round(r, 4) for r in recovery_all],
        "streams_revived_per_repeat": revived_all,
        "streams_token_exact": exact,
        "zero_recompiles_recovered": recompile_free,
    }


def _make_ref(model, variables, prompt, n_new):
    out = generate(model, variables,
                   jnp.asarray(prompt, jnp.int32)[None], n_new)
    return np.asarray(out)[0, len(prompt):].tolist()


def _ctrlplane_hedge_leg(args, repeats: int) -> dict:
    """Gray-replica hedging ON vs OFF under an injected slow WORKER
    (real processes — the regime where a slow replica costs wall
    time the router does not spend): interactive p99 TTFT for traffic
    stuck to the suspect, paired per repeat."""
    import subprocess

    from pddl_tpu.serve.fleet import (
        FleetRouter,
        GrayDetector,
        ProcessReplica,
    )

    cfg = {**_ctrlplane_cfg(), "slots": 2}
    n_interactive = 8
    delay_s = 0.03

    def run_once(hedge: bool, seed: int):
        reps = [ProcessReplica(i, {**cfg, "replica_id": i},
                               stderr=subprocess.DEVNULL,
                               ping_interval_s=0.05, wait_ready=False)
                for i in range(2)]
        for r in reps:
            r.wait_ready()
        fleet = FleetRouter(
            reps, affinity_block_size=8, affinity_blocks=1,
            respawn=False,
            gray=GrayDetector(window=8, baseline=16, z_threshold=4.0,
                              min_excess_s=0.01, consecutive=2),
            gray_hedge=hedge, gray_drain=False)
        try:
            # Session-pin traffic to one replica; give the detector a
            # clean-speed baseline from its self-reported tick walls.
            pin = fleet.submit(list(range(1, 9)), 96, session="s",
                               priority=Priority.BATCH)
            victim = pin.replica_id
            t_end = time.perf_counter() + 1.5
            while time.perf_counter() < t_end:
                fleet.step()
            # Now make the worker GRAY (every tick +30 ms) and keep
            # its two slots saturated with long batch streams.
            victim_slot = next(s for s in fleet.replicas
                               if s.replica_id == victim)
            victim_slot.driver.set_tick_delay(delay_s)
            busy = [fleet.submit(list(range(2, 10)), 96, session="s",
                                 priority=Priority.BATCH)
                    for _ in range(2)]
            deadline = time.perf_counter() + 30
            while victim not in fleet.gray.suspected \
                    and time.perf_counter() < deadline:
                fleet.step()
            assert victim in fleet.gray.suspected, \
                "suspicion never fired"
            rng = np.random.default_rng(seed)
            ttfts = []
            for _ in range(n_interactive):
                p = rng.integers(0, cfg["vocab"], size=10).tolist()
                h = fleet.submit(p, 4, session="s")
                hang = time.perf_counter() + 120
                while not h.done and time.perf_counter() < hang:
                    fleet.step()
                assert h.done and h.ttft_s is not None
                ttfts.append(h.ttft_s)
            del busy  # batch streams need not finish: the leg
            #           measures the interactive tail, not them
            wins = fleet.metrics.hedge_wins
            counts = fleet.compile_counts()
            ok = bool(counts) and all(v == 1 for v in counts.values())
            return float(np.percentile(ttfts, 99)), wins, ok
        finally:
            fleet.close()

    ratios, on_all, off_all = [], [], []
    wins_total = 0
    recompile_free = True
    for rep in range(repeats):
        p99_off, _, ok_off = run_once(False, 800 + rep)
        p99_on, wins, ok_on = run_once(True, 800 + rep)
        wins_total += wins
        recompile_free = recompile_free and ok_off and ok_on
        on_all.append(p99_on)
        off_all.append(p99_off)
        ratios.append(p99_off / p99_on)
        _log(f"ctrlplane hedge pair {rep}: p99 TTFT {p99_off:.4f}s "
             f"-> {p99_on:.4f}s ({ratios[-1]:.2f}x, {wins} wins)")
    ratio_med, ratio_spread = median_spread(ratios)
    return {
        "slow_replica": f"worker tick delay {delay_s * 1000:.0f} ms "
                        "(set_tick_delay), detector-suspected from "
                        "self-reported tick walls before measuring",
        "interactive_requests_per_wave": n_interactive,
        "ttft_p99_hedge_off_s": round(median_spread(off_all)[0], 4),
        "ttft_p99_hedge_on_s": round(median_spread(on_all)[0], 4),
        "hedged_ttft_p99_reduction_x": round(ratio_med, 3),
        "hedged_ttft_reduction_per_pair": [round(r, 3) for r in ratios],
        "hedged_ttft_reduction_spread_pct": round(ratio_spread, 2),
        "hedge_wins_total": wins_total,
        "all_pairs_directional": all(r > 1.0 for r in ratios),
        "zero_recompiles": recompile_free,
    }


def _ctrlplane_leg(args) -> dict:
    repeats = max(args.repeats, 5)
    cfg = _ctrlplane_cfg()
    model = GPT(vocab_size=cfg["vocab"], max_len=cfg["max_len"],
                embed_dim=cfg["embed_dim"], depth=cfg["depth"],
                num_heads=cfg["heads"], attention="reference")
    dummy = jnp.ones((1, 16), jnp.int32)
    params = model.init(jax.random.key(0), dummy,
                        train=False)["params"]
    variables = {"params": params}
    wire = _ctrlplane_wire_leg(args, repeats)
    recovery = _ctrlplane_recovery_leg(model, variables, args, repeats)
    hedge = _ctrlplane_hedge_leg(args, repeats)
    return {"wire": wire, "recovery": recovery, "hedge": hedge}


def _ha_failover_leg(model, variables, args, repeats: int) -> dict:
    """Hot-standby failover vs the r19 cold recover path, PAIRED per
    repeat on the same workload (ISSUE 20).

    Hot: the standby tails the primary's WAL live over the shipper,
    the lease lapses when the primary goes silent, and promotion
    replays onto the SAME still-warm engines. Cold: the r19 path —
    ``FleetRouter.recover`` onto FRESH replicas, which pays the spawn
    + prefill/decode compile inside the outage window. Both clocks
    start at the moment of primary silence and stop when every revived
    stream has produced a token PAST its mirrored length (serving
    again, not merely rebuilt), so the pair isolates exactly what the
    standby buys. The deposed primary keeps commanding after each hot
    takeover; its refusal count is the split-brain headline."""
    from pddl_tpu.serve.fleet import (
        EpochFenced,
        FleetRouter,
        HotStandby,
        Lease,
        LeaseKeeper,
        LocalReplica,
        RouterJournal,
        WalShipper,
    )

    def factory():
        return ServeEngine(model, variables, max_slots=4,
                           prefill_len=32, max_queue_depth=96,
                           prefix_cache_blocks=0)

    def replicas():
        return [LocalReplica(i, factory) for i in range(2)]

    router_kw = dict(affinity_block_size=8, affinity_blocks=1,
                     respawn=False)
    new_tokens = 32
    lease_ttl_s = 0.25
    hot_all, cold_all, ratios, revived_all = [], [], [], []
    exact = True
    recompile_free = True
    acked_lost = 0
    probes_attempted = 0
    probes_refused = 0

    for rep in range(repeats):
        rng = np.random.default_rng(900 + rep)
        prompts = [rng.integers(0, 64, size=12).tolist()
                   for _ in range(12)]
        refs = {tuple(p): _make_ref(model, variables, p, new_tokens)
                for p in prompts}

        # ---- hot: WAL-shipped standby, lease-lapse promotion --------
        d = tempfile.mkdtemp(prefix="pddl-ha-hot-")
        try:
            journal = RouterJournal(d, fsync_batch_records=16)
            fleet = FleetRouter(replicas(), journal=journal,
                                **router_kw)
            lease = Lease(os.path.join(d, "ha_lease.json"),
                          ttl_s=lease_ttl_s)
            keeper = LeaseKeeper(lease, "primary", seed=rep)
            fleet.set_epoch(keeper.acquire())
            fleet.ha = keeper
            standby = HotStandby(
                d, [s.driver for s in fleet.replicas], lease=lease,
                holder="standby", router_kw=router_kw, seed=rep + 1)
            shipper = WalShipper(journal, standby.feed)
            standby.attach(shipper)
            handles = [fleet.submit(list(p), new_tokens)
                       for p in prompts]
            for _ in range(10):           # mid-stream, mirrors partial
                fleet.step()
                keeper.step()
            acked = {tuple(int(t) for t in h.request.prompt):
                     list(h.tokens) for h in handles}
            # Primary goes silent: no more steps, no more renewals.
            t0 = time.perf_counter()
            out = None
            while out is None and time.perf_counter() < t0 + 60.0:
                out = standby.step()
                time.sleep(0.002)
            assert out is not None, "standby never promoted"
            promoted, revived = out
            at_promo = {rid: len(fh.tokens)
                        for rid, fh in revived.items()}
            for _ in range(100000):
                if not any(len(fh.tokens) <= at_promo[rid]
                           and not fh.done
                           for rid, fh in revived.items()):
                    break
                promoted.step()
            failover_s = time.perf_counter() - t0
            # The deposed primary keeps commanding: every worker must
            # refuse it on the fencing epoch, not on trust.
            probes_attempted += 1
            try:
                fleet.submit([1, 2, 3], 4)
            except EpochFenced:
                probes_refused += 1
            promoted.run(max_steps=100000)
            revived_keys = set()
            for fh in revived.values():
                key = tuple(int(t) for t in fh.request.prompt)
                revived_keys.add(key)
                if fh.state.value != "finished" \
                        or fh.tokens != refs[key]:
                    exact = False
            open_keys = {k for k, t in acked.items()
                         if len(t) < len(refs[k])}
            acked_lost += len(open_keys - revived_keys)
            counts = promoted.compile_counts()
            if not counts or any(v != 1 for v in counts.values()):
                recompile_free = False
            promoted.close()
            revived_all.append(len(revived))
            hot_all.append(failover_s)
        finally:
            shutil.rmtree(d, ignore_errors=True)

        # ---- cold: the r19 recover path, same workload --------------
        d2 = tempfile.mkdtemp(prefix="pddl-ha-cold-")
        try:
            fleet = FleetRouter(replicas(),
                                journal=RouterJournal(
                                    d2, fsync_batch_records=16),
                                **router_kw)
            for p in prompts:
                fleet.submit(list(p), new_tokens)
            for _ in range(10):
                fleet.step()
            t0 = time.perf_counter()
            recovered, revived = FleetRouter.recover(
                d2, replicas(), **router_kw)
            at_rec = {rid: len(fh.tokens)
                      for rid, fh in revived.items()}
            for _ in range(100000):
                if not any(len(fh.tokens) <= at_rec[rid]
                           and not fh.done
                           for rid, fh in revived.items()):
                    break
                recovered.step()
            cold_s = time.perf_counter() - t0
            recovered.run(max_steps=100000)
            recovered.close()
            cold_all.append(cold_s)
        finally:
            shutil.rmtree(d2, ignore_errors=True)

        ratios.append(cold_all[-1] / hot_all[-1])
        _log(f"ha pair {rep}: failover {hot_all[-1]:.3f}s vs cold "
             f"recover {cold_all[-1]:.3f}s ({ratios[-1]:.1f}x)")

    med, spread = median_spread(hot_all)
    cold_med, _ = median_spread(cold_all)
    ratio_med, ratio_spread = median_spread(ratios)
    return {
        "outage": "primary partitioned mid-stream (stops stepping and "
                  "renewing; OBJECT stays alive and keeps commanding), "
                  "standby promotes on lease lapse over the same live "
                  "replicas",
        "detection_lease_ttl_s": lease_ttl_s,
        "failover_s": round(med, 4),
        "failover_s_spread_pct": round(spread, 2),
        "failover_s_per_repeat": [round(s, 4) for s in hot_all],
        "cold_recover_s": round(cold_med, 4),
        "cold_recover_s_per_repeat": [round(s, 4) for s in cold_all],
        "failover_speedup_vs_cold_x": round(ratio_med, 2),
        "failover_speedup_spread_pct": round(ratio_spread, 2),
        "all_pairs_directional": all(r > 1.0 for r in ratios),
        "streams_revived_per_repeat": revived_all,
        "acked_streams_lost_total": acked_lost,
        "streams_token_exact": exact,
        "zero_recompiles_promoted": recompile_free,
        "deposed_probes_attempted": probes_attempted,
        "deposed_probes_refused": probes_refused,
    }


def _chaosd_availability_leg(model, variables, args,
                             repeats: int) -> dict:
    """Paired clean vs persistent-EIO-storm waves over a WAL-armed
    local fleet (ISSUE 18): while EVERY disk op fails, the journal
    degrades NON_DURABLE and serving must hold ~all of its clean
    throughput — then, when the disk returns, the next due probe
    re-arms durability and the retained backlog lands on disk."""
    from pddl_tpu.serve.fleet import (
        FleetRouter,
        LocalReplica,
        RouterJournal,
    )
    from pddl_tpu.utils.faults import StorageFaultPlan

    new_tokens = 64
    n_requests = 48
    probe_s = 0.05

    def factory():
        return ServeEngine(model, variables, max_slots=4,
                           prefill_len=32, max_queue_depth=96,
                           prefix_cache_blocks=0)

    d = tempfile.mkdtemp(prefix="pddl-chaosd-wal-")
    sp = StorageFaultPlan(seed=0)
    # checkpoint_every_records is pushed out of reach: a checkpoint+
    # rotate cycle (~3 fsyncs + a full-state write) fires every ~1.3
    # waves at the default and lands on whichever wave is running —
    # storm waves skip it (degraded checkpoints fail fast), so the
    # lump lands only on CLEAN waves and whipsaws the ratio between
    # runs. Checkpoint cost has its own r19 recovery leg; this leg
    # isolates the steady-state durability tax (write+fsync batching).
    journal = RouterJournal(d, storage_plan=sp, fsync_batch_records=8,
                            retry_limit=1, retry_backoff_s=0.0,
                            rearm_interval_s=probe_s,
                            checkpoint_every_records=1 << 20)
    # ONE long-lived fleet serves both halves of every pair: the clean
    # and storm waves ride identically-warm engines, so the ratio
    # isolates the degraded journal, not compile state.
    fleet = FleetRouter([LocalReplica(i, factory) for i in range(2)],
                        journal=journal, affinity_block_size=8,
                        affinity_blocks=1, respawn=False)
    refs = {}

    def ref_for(prompt):
        key = tuple(prompt)
        if key not in refs:
            refs[key] = _make_ref(model, variables, prompt, new_tokens)
        return refs[key]

    ratios, clean_all, storm_all, rearm_all = [], [], [], []
    exact = True
    try:
        warm_rng = np.random.default_rng(949)
        warm = [warm_rng.integers(0, 64, size=12).tolist()
                for _ in range(n_requests)]
        _ctrl_wave(fleet, warm, new_tokens)
        for rep in range(repeats):
            rng = np.random.default_rng(950 + rep)
            prompts = [rng.integers(0, 64, size=12).tolist()
                       for _ in range(2 * n_requests)]

            def clean_wave():
                _, tps, _ = _ctrl_wave(fleet, prompts[:n_requests],
                                       new_tokens)
                return tps

            def storm_wave():
                nonlocal exact
                sp._rates = (1.0, 0.0, 0.0, 0.0)  # the disk dies
                handles, tps, _ = _ctrl_wave(
                    fleet, prompts[n_requests:], new_tokens)
                assert journal.non_durable, \
                    "storm never degraded the WAL"
                for p, h in zip(prompts[n_requests:], handles):
                    if h.state.value != "finished" \
                            or h.tokens != ref_for(p):
                        exact = False
                sp.quiesce()                  # the disk comes back
                t0 = time.perf_counter()
                hang = t0 + 5.0
                while journal.non_durable \
                        and time.perf_counter() < hang:
                    fleet.step()
                rearm = time.perf_counter() - t0
                assert not journal.non_durable, \
                    "journal never re-armed"
                return tps, rearm

            # Alternate the pair order per repeat: a slow drift in
            # host throughput across the run (thermal, ambient load)
            # would otherwise bias every ratio the same way.
            if rep % 2 == 0:
                tps_clean = clean_wave()
                tps_storm, rearm_s = storm_wave()
            else:
                tps_storm, rearm_s = storm_wave()
                tps_clean = clean_wave()
            clean_all.append(tps_clean)
            storm_all.append(tps_storm)
            ratios.append(tps_storm / tps_clean)
            rearm_all.append(rearm_s)
            _log(f"chaosd availability pair {rep}: {tps_clean:,.0f} -> "
                 f"{tps_storm:,.0f} tok/s ({ratios[-1]:.3f}x), "
                 f"re-armed in {rearm_s * 1000:.1f} ms")
        m = fleet.metrics
        degraded_events = m.journal_degraded_events
        rearms = m.journal_rearms
        storage_errors = m.journal_storage_errors
    finally:
        fleet.close()
        shutil.rmtree(d, ignore_errors=True)
    ratio_med, ratio_spread = median_spread(ratios)
    rearm_med, _ = median_spread(rearm_all)
    # Worst-case honest bound: the probe may have JUST failed when the
    # disk recovers, so re-arm can take up to one full interval plus
    # one idle router step of wall.
    rearm_bound_s = probe_s + 0.05
    return {
        "fault_profile": "every vfs op EIO (rate 1.0) for the whole "
                         "wave; quiesced before the re-arm measurement",
        "n_requests_per_wave": n_requests,
        "new_tokens": new_tokens,
        "tokens_per_s_clean": round(median_spread(clean_all)[0], 1),
        "tokens_per_s_storm": round(median_spread(storm_all)[0], 1),
        "non_durable_availability_x": round(ratio_med, 3),
        "non_durable_availability_per_pair": [round(r, 3)
                                              for r in ratios],
        "non_durable_availability_spread_pct": round(ratio_spread, 2),
        "rearm_probe_interval_s": probe_s,
        "rearm_latency_s": round(rearm_med, 4),
        "rearm_latency_s_per_repeat": [round(r, 4) for r in rearm_all],
        "rearm_within_one_probe_interval": bool(
            max(rearm_all) <= rearm_bound_s),
        "journal_degraded_events_total": degraded_events,
        "journal_rearms_total": rearms,
        "journal_storage_errors_total": storage_errors,
        "storage_faults_injected_total": int(sp.total_injected),
        "streams_token_exact": exact,
    }


def _chaosd_campaign_leg(args) -> dict:
    """3-seed composed-plane campaigns over PROCESS fleets (ISSUE 18):
    seeded wire storms underneath, a storage storm on the router WAL,
    a gray slow-wall span, a worker SIGKILL, then the router
    crash+recover — :class:`ChaosConductor`'s invariant referee judges
    each campaign (acked_terminal, token_exact, zero_recompiles,
    recover_idempotent, recovery_bounded, exposition)."""
    import subprocess

    from pddl_tpu.chaos import ChaosConductor, ReplicaChaos
    from pddl_tpu.serve.fleet import ProcessReplica, WireFaultPlan
    from pddl_tpu.serve.fleet.worker import build_engine
    from pddl_tpu.utils.faults import StorageFaultPlan

    cfg = _ctrlplane_cfg()
    # Enough queued work that every plane lands on a LIVE fleet: with
    # the baseline tick wall set in make_replicas, the workers chew
    # ~3k tokens over ~2 s of wall while the paced schedule (pace_s
    # below) spreads the storm/kill/crash across the same window —
    # chaos composed over traffic, not over a drained fleet.
    new_tokens = 64
    n_streams = 48
    seeds = (0, 1, 2)
    oracle = build_engine(cfg)
    refs = {}

    def ref_for(prompt, n):
        key = (tuple(prompt), int(n))
        if key not in refs:
            out = generate(oracle.model, {"params": oracle._params},
                           jnp.asarray(prompt, jnp.int32)[None], int(n))
            refs[key] = np.asarray(out)[0, len(prompt):].tolist()
        return refs[key]

    reports = []
    wire_injected = storage_injected = 0
    for seed in seeds:
        d = tempfile.mkdtemp(prefix=f"pddl-chaosd-campaign-{seed}-")

        def make_replicas():
            reps = []
            for i in range(2):
                plan = WireFaultPlan(3000 + 100 * seed + i,
                                     corrupt_rate=0.004,
                                     duplicate_rate=0.002,
                                     reorder_rate=0.002,
                                     drop_rate=0.002)
                reps.append(ProcessReplica(
                    i, {**cfg, "replica_id": i},
                    stderr=subprocess.DEVNULL, wire_fault_plan=plan,
                    ping_interval_s=0.01, resend_timeout_s=0.01,
                    wait_ready=False))
            for r in reps:
                r.wait_ready()
                # A 2x64 worker decodes ~6k tok/s: the whole campaign
                # workload would drain inside the first 3 paced steps,
                # before any span plane fires. A small baseline tick
                # wall prices each tick like a real model so the
                # storm/kill/crash land on LIVE traffic.
                r.set_tick_delay(0.004)
            return reps

        def make_chaos(fleet):
            # No GrayDetector armed: the gray PLANE here is the slow
            # wall itself composing with the other planes; detection/
            # hedging has its own paired leg in r19.
            return [ReplicaChaos(replica_id=int(s.replica_id),
                                 wire_plan=getattr(s.driver, "_plan",
                                                   None),
                                 slow_fn=s.driver.set_tick_delay,
                                 kill_fn=s.driver.kill)
                    for s in fleet.replicas]

        sp = StorageFaultPlan(seed=seed)
        cond = ChaosConductor(
            make_replicas, make_chaos, ref_for,
            journal_dir=d, storage_plan=sp,
            router_kw=dict(affinity_block_size=8, affinity_blocks=1,
                           respawn=False),
            journal_kw=dict(fsync_batch_records=4, retry_limit=1,
                            retry_backoff_s=0.0,
                            rearm_interval_s=0.05),
            recovery_bound_s=90.0, seed=seed)
        rng = np.random.default_rng(990 + seed)
        workload, seen = [], set()
        while len(workload) < n_streams:
            p = rng.integers(0, cfg["vocab"], size=12).tolist()
            if tuple(p) in seen:
                continue
            seen.add(tuple(p))
            workload.append((p, new_tokens))
        try:
            report = cond.run(
                workload,
                planes=("wire", "storage", "gray", "kill", "router"),
                horizon=40, kills=1, slow_delay_s=0.02,
                pace_s=0.04, max_wall_s=300.0)
        finally:
            shutil.rmtree(d, ignore_errors=True)
        assert report.ok, (f"campaign seed {seed} violated: "
                           f"{report.violations}")
        wire_injected += report.injected.get("wire", 0)
        storage_injected += report.injected.get("storage", 0)
        reports.append(report)
        _log(f"chaosd campaign seed {seed}: {len(report.actions)} "
             f"actions over {report.steps} steps, recovery "
             f"{report.recovery_s:.2f}s, injected {report.injected}, "
             f"ok={report.ok}")
    recovery_med, recovery_spread = median_spread(
        [r.recovery_s for r in reports])
    return {
        "planes_composed": ["wire", "storage", "gray", "kill",
                            "router"],
        "seeds": list(seeds),
        "streams_per_campaign": n_streams,
        "new_tokens": new_tokens,
        "campaigns_all_ok": all(r.ok for r in reports),
        "invariants_checked": sorted(reports[0].invariants),
        "invariants_failed": sorted(
            {name for r in reports
             for name, ok in r.invariants.items() if not ok}),
        "recovery_s": round(recovery_med, 3),
        "recovery_s_per_seed": [round(r.recovery_s, 3)
                                for r in reports],
        "recovery_s_spread_pct": round(recovery_spread, 2),
        "actions_fired_per_seed": [len(r.actions) for r in reports],
        "kills_fired_total": sum(
            1 for r in reports for a in r.actions if a.kind == "kill"),
        "router_crashes_total": sum(
            1 for r in reports for a in r.actions
            if a.kind == "router_crash"),
        "wire_faults_injected_total": wire_injected,
        "storage_faults_injected_total": storage_injected,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--embed-dim", type=int, default=256)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--prefill-len", type=int, default=64)
    p.add_argument("--concurrent", type=int, default=8,
                   help="requests in the head-to-head vs sequential "
                        "generate() (the acceptance ratio)")
    p.add_argument("--poisson-requests", type=int, default=24,
                   help="requests per Poisson load point")
    p.add_argument("--max-queue-depth", type=int, default=16)
    p.add_argument("--skip-poisson", action="store_true",
                   help="head-to-head + prefix legs only (the Poisson "
                        "curve runs in real time and dominates wall "
                        "clock)")
    p.add_argument("--prefix-requests", type=int, default=24,
                   help="requests in the shared-prefix TTFT leg (the "
                        "leg runs them at n_requests slots with short "
                        "decodes, so TTFT measures the admission "
                        "prefill the cache shortens)")
    p.add_argument("--prefix-prompt-len", type=int, default=384,
                   help="shared-prefix leg prompt length (long prompts "
                        "are the cache's home turf — suffix compute "
                        "stays 1-shared_frac of the prompt while the "
                        "per-admission fixed costs amortize)")
    p.add_argument("--prefix-new-tokens", type=int, default=8)
    p.add_argument("--prefix-shared-frac", type=float, default=0.8)
    p.add_argument("--prefix-block-size", type=int, default=8)
    p.add_argument("--prefix-chunk", type=int, default=80,
                   help="narrow suffix-chunk width (~ the uncached "
                        "suffix at the default shared fraction)")
    p.add_argument("--spec-only", action="store_true",
                   help="speculative-serving leg only (ISSUE 12): "
                        "paired spec/plain waves + acceptance-vs-k "
                        "curve + chaos leg, standalone r17 artifact")
    p.add_argument("--spec-k", type=int, default=6,
                   help="drafted tokens per slot per step for the "
                        "headline wave (the verify window is k+1 wide)")
    p.add_argument("--spec-k-curve", default="2,4,6,8",
                   help="comma-separated k values for the "
                        "acceptance-rate curve")
    p.add_argument("--tier-only", action="store_true",
                   help="run only the tiered-KV-cache leg (host-RAM "
                        "spill tier vs the r13 evict-and-recompute "
                        "baseline at 4-32x working sets, plus the "
                        "2-replica duplicate-prefill chain-pull leg) "
                        "-> the r18 artifact")
    p.add_argument("--tier-mults", default="4,8,16,32",
                   help="working-set multiples of the device pool the "
                        "tier curve sweeps")
    p.add_argument("--tenant-only", action="store_true",
                   help="run only the multi-tenant leg (paged LoRA "
                        "adapters + constrained decoding; r14 artifact)")
    p.add_argument("--tenant-adapters", type=int, default=8,
                   help="distinct LoRA adapters in the tenant leg")
    p.add_argument("--paged-only", action="store_true",
                   help="run ONLY the paged-attention leg (paged vs "
                        "resident-row engines, paired: duplicate-KV "
                        "elimination at matched pool bytes + prefix-hit "
                        "admission head-to-head) and write a standalone "
                        "artifact (r13_serve_paged.json)")
    p.add_argument("--fault-rate", type=float, default=0.01,
                   help="injected fault probability per device dispatch "
                        "in the fault leg (transient; OOM rides at a "
                        "tenth of it); 0 skips the leg")
    p.add_argument("--faults-only", action="store_true",
                   help="run ONLY the fault leg and write a standalone "
                        "artifact (r08_serve_faults.json)")
    p.add_argument("--obs-only", action="store_true",
                   help="run ONLY the observability leg (tracing "
                        "on/off paired overhead) and write a "
                        "standalone artifact (r09_serve_obs.json)")
    p.add_argument("--trace", default="",
                   help="also write a fully traced pass's span/tick/"
                        "metrics event log to this JSONL path as a "
                        "bench artifact")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed repetitions per headline number (median "
                        "+ spread recorded)")
    p.add_argument("--fleet-only", action="store_true",
                   help="run ONLY the multi-replica fleet leg (process "
                        "replicas behind the router) and write a "
                        "standalone artifact (r11_serve_fleet.json)")
    p.add_argument("--fleet-replicas", default="2,4,8",
                   help="comma-separated replica counts for the fleet "
                        "scaling curve")
    p.add_argument("--fleet-load", type=float, default=0.8,
                   help="offered Poisson load as a fraction of "
                        "N x the r08 single-engine clean baseline")
    p.add_argument("--slo-only", action="store_true",
                   help="run ONLY the SLO/overload leg (bursty "
                        "multi-turn trace at 2x capacity through the "
                        "admission-controlled fleet) and write a "
                        "standalone artifact (r12_serve_slo.json)")
    p.add_argument("--slo-requests", type=int, default=240,
                   help="requests per SLO trace wave")
    p.add_argument("--slo-replicas", type=int, default=2,
                   help="in-process replicas behind the "
                        "admission-controlled router in the SLO leg")
    p.add_argument("--slo-overload", type=float, default=2.0,
                   help="offered load as a multiple of measured fleet "
                        "capacity in the SLO overload wave")
    p.add_argument("--autoscale-only", action="store_true",
                   help="run ONLY the elastic-autoscaling leg (diurnal "
                        "trace through static-N fleets vs the "
                        "autoscaled fleet; goodput per replica-hour) "
                        "and write a standalone artifact "
                        "(r16_serve_autoscale.json)")
    p.add_argument("--autoscale-min", type=int, default=1,
                   help="autoscaled fleet's floor (and starting size)")
    p.add_argument("--autoscale-max", type=int, default=4,
                   help="autoscaled fleet's ceiling")
    p.add_argument("--autoscale-static", default="1,2,4",
                   help="comma-separated static replica counts swept "
                        "for the best-static baseline")
    p.add_argument("--autoscale-duration", type=float, default=120.0,
                   help="seconds one diurnal period is compressed to")
    p.add_argument("--autoscale-offered", type=float, default=2.5,
                   help="offered MEAN load as a multiple of "
                        "single-replica capacity — sized to sit "
                        "BETWEEN static fleet sizes (the regime where "
                        "no static N is both sufficient and "
                        "efficient); the sinusoid swings peak:trough "
                        "around it")
    p.add_argument("--autoscale-peak-trough", type=float, default=8.0,
                   help="diurnal peak:trough intensity ratio")
    p.add_argument("--autoscale-attainment-floor", type=float,
                   default=0.95,
                   help="fraction of offered requests a static fleet "
                        "must FINISH to qualify as the best-static "
                        "baseline (and the autoscaled fleet is held "
                        "to the same bar)")
    p.add_argument("--ctrlplane-only", action="store_true",
                   help="run ONLY the control-plane durability leg "
                        "(framed-transport wire storm, router WAL "
                        "crash recovery, gray-replica hedging; "
                        "ISSUE 14) and write a standalone artifact "
                        "(r19_serve_ctrlplane.json)")
    p.add_argument("--ha-only", action="store_true",
                   help="run ONLY the router high-availability leg "
                        "(hot-standby WAL tail + lease-lapse fenced "
                        "promotion vs the cold recover path, paired "
                        "per repeat; ISSUE 20) and write a standalone "
                        "artifact (r23_serve_ha.json)")
    p.add_argument("--chaosd-only", action="store_true",
                   help="run ONLY the storage-chaos leg (paired "
                        "clean vs persistent-EIO-storm NON_DURABLE "
                        "availability + re-arm latency, 3-seed "
                        "composed-plane ChaosConductor campaigns "
                        "over process fleets; ISSUE 18) and write a "
                        "standalone artifact "
                        "(r21_serve_chaosd.json)")
    p.add_argument("--dtrace-only", action="store_true",
                   help="run ONLY the distributed-tracing overhead "
                        "leg (paired tracing-on/off waves at N=2 "
                        "process replicas, gap-free stitch referee; "
                        "ISSUE 19) and write a standalone artifact "
                        "(r22_serve_dtrace.json)")
    p.add_argument("--disagg-only", action="store_true",
                   help="run ONLY the disaggregated prefill/decode leg "
                        "(role-split fleet, block-granular KV "
                        "hand-off; ISSUE 17) and write a standalone "
                        "artifact (r20_serve_disagg.json)")
    p.add_argument("--disagg-replicas", type=int, default=4,
                   help="fleet size N for BOTH halves of each pair: "
                        "N unified vs a same-N role split")
    p.add_argument("--disagg-prefill-replicas", type=int, default=0,
                   help="prefill-pool size inside the split fleet "
                        "(0 = auto N//2; compute share, not token "
                        "share — decode steps cost ~10x a batched "
                        "prefill token on this model)")
    p.add_argument("--disagg-requests", type=int, default=48,
                   help="trace requests per wave")
    p.add_argument("--disagg-prompt-base", type=int, default=256,
                   help="per-session system-prompt length of the "
                        "long-prompt trace (tokens)")
    p.add_argument("--disagg-load", type=float, default=0.75,
                   help="offered rate as a fraction of measured "
                        "unified capacity")
    p.add_argument("--out", default="")
    args = p.parse_args()

    if args.ha_only:
        repeats = max(args.repeats, 5)
        _log(f"ha leg only: hot-standby failover vs cold recover, "
             f"{repeats} paired runs, gpt 2x64")
        cfg = _ctrlplane_cfg()
        model = GPT(vocab_size=cfg["vocab"], max_len=cfg["max_len"],
                    embed_dim=cfg["embed_dim"], depth=cfg["depth"],
                    num_heads=cfg["heads"], attention="reference")
        dummy = jnp.ones((1, 16), jnp.int32)
        params = model.init(jax.random.key(0), dummy,
                            train=False)["params"]
        variables = {"params": params}
        ha = _ha_failover_leg(model, variables, args, repeats)
        record = {
            "metric": "fleet_serving_router_ha",
            "unit": "seconds (primary silence -> every revived stream "
                    "serving again); ratio (cold recover / hot "
                    "failover wall)",
            "config": {
                "model": "gpt 2x64 (vocab 64, max_len 128)",
                "replicas": 2,
                "standby": "WAL-shipped hot standby: live record "
                           "stream over the framed transport, disk "
                           "catch-up on join and wire gaps "
                           "(serve/fleet/standby.py)",
                "lease": f"file-backed, ttl {ha['detection_lease_ttl_s']}s, "
                         "seeded subtractive renewal jitter; holder "
                         "change bumps the fencing epoch",
                "fencing": "every worker-bound command carries the "
                           "issuing router's epoch; workers persist "
                           "the highest seen and refuse lower with a "
                           "typed reject (EpochFenced)",
                "promotion": "lease-lapse takeover replays the WAL "
                             "suffix onto the SAME live engines "
                             "(mirror-replay contract: token-exact, "
                             "zero recompiles)",
                "cold_baseline": "r19 FleetRouter.recover onto fresh "
                                 "replicas (spawn + compile inside "
                                 "the outage window), same workload",
            },
            "provenance": provenance(repeats),
            "results": {"ha": ha},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"ha: failover {ha['failover_s']}s median vs cold "
             f"{ha['cold_recover_s']}s "
             f"({ha['failover_speedup_vs_cold_x']}x, all pairs "
             f"directional {ha['all_pairs_directional']}); acked "
             f"streams lost {ha['acked_streams_lost_total']}, "
             f"token-exact {ha['streams_token_exact']}, zero "
             f"recompiles {ha['zero_recompiles_promoted']}; deposed "
             f"primary refused "
             f"{ha['deposed_probes_refused']}/"
             f"{ha['deposed_probes_attempted']}")
        _write_record(record, args.out)
        return

    if args.chaosd_only:
        repeats = max(args.repeats, 5)
        _log(f"chaosd leg only: persistent-EIO-storm availability "
             f"({repeats} paired waves) + 3-seed composed-plane "
             f"campaigns, gpt 2x64")
        cfg = _ctrlplane_cfg()
        model = GPT(vocab_size=cfg["vocab"], max_len=cfg["max_len"],
                    embed_dim=cfg["embed_dim"], depth=cfg["depth"],
                    num_heads=cfg["heads"], attention="reference")
        dummy = jnp.ones((1, 16), jnp.int32)
        params = model.init(jax.random.key(0), dummy,
                            train=False)["params"]
        variables = {"params": params}
        avail = _chaosd_availability_leg(model, variables, args,
                                         repeats)
        campaign = _chaosd_campaign_leg(args)
        record = {
            "metric": "fleet_serving_storage_chaos",
            "unit": "ratio (storm/clean tok_s while the WAL is "
                    "degraded NON_DURABLE); seconds (durability "
                    "re-arm, campaign crash recovery)",
            "config": {
                "model": "gpt 2x64 (vocab 64, max_len 128)",
                "storage_faults": "seeded StorageFaultPlan "
                                  "(EIO/ENOSPC/torn/slow) through "
                                  "the journal VFS shim "
                                  "(utils/faults.py, "
                                  "serve/fleet/journal.py)",
                "degradation": "bounded retries -> NON_DURABLE with "
                               "acks flowing, rate-limited re-arm "
                               "probes, emergency checkpoint on "
                               "ENOSPC",
                "conductor": "seeded multi-plane campaign engine + "
                             "invariant referee "
                             "(pddl_tpu/chaos/conductor.py)",
                "campaign_fleet": "2 process replicas, WireFaultPlan "
                                  "armed, worker SIGKILL + router "
                                  "crash planes",
            },
            "provenance": provenance(repeats),
            # Group key "storm", NOT "availability": metric_direction
            # substring-matches the whole leaf path, and an
            # "availability" segment would stamp higher-is-better onto
            # every leaf under it — including rearm_latency_s.
            "results": {"storm": avail, "campaign": campaign},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"chaosd: NON_DURABLE availability "
             f"{avail['non_durable_availability_x']}x "
             f"({avail['storage_faults_injected_total']} storage "
             f"faults injected, token-exact "
             f"{avail['streams_token_exact']}); re-arm "
             f"{avail['rearm_latency_s']}s median (within one probe "
             f"interval: {avail['rearm_within_one_probe_interval']}); "
             f"campaigns ok={campaign['campaigns_all_ok']} over "
             f"planes {campaign['planes_composed']}, recovery "
             f"{campaign['recovery_s']}s median, injected "
             f"wire={campaign['wire_faults_injected_total']} "
             f"storage={campaign['storage_faults_injected_total']}")
        _write_record(record, args.out)
        return

    if args.dtrace_only:
        repeats = max(args.repeats, 5)
        _log(f"dtrace leg only: paired tracing-on/off waves, 2 "
             f"process replicas, {repeats} pairs, gpt 2x64")
        dtrace = _dtrace_leg(args, repeats)
        record = {
            "metric": "fleet_serving_distributed_tracing",
            "unit": "ratio (tracing-on/off tok_s); counts (spans, "
                    "gap-free stitched traces)",
            "config": {
                "model": "gpt 2x64 (vocab 64, max_len 128)",
                "process_replicas": 2,
                "propagation": "router-stamped (trace_id, "
                               "parent_span_id) on every pipe "
                               "command; worker child spans ship "
                               "back batched on pong/event reads "
                               "(pddl_tpu/obs/propagate.py)",
                "assembly": "trace_id stitch + min-RTT clock "
                            "alignment + gap referee "
                            "(pddl_tpu/obs/assemble.py)",
                "flight_recorder": "crash-durable per-worker span "
                                   "segments through the journal "
                                   "VFS shim "
                                   "(pddl_tpu/obs/flightrec.py)",
            },
            "provenance": provenance(repeats),
            "results": {"dtrace": dtrace},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"dtrace: {dtrace['tokens_per_s_tracing_off']} -> "
             f"{dtrace['tokens_per_s_tracing_on']} tok/s "
             f"({dtrace['tracing_on_over_off_x']}x, floor "
             f"{dtrace['tracing_retained_floor']}x, all pairs above "
             f"{dtrace['all_pairs_above_floor']}); "
             f"{dtrace['traces_gap_free_total']}/"
             f"{dtrace['traces_stitched_total']} traces gap-free, "
             f"{dtrace['replica_spans_collected_total']} spans "
             f"shipped ({dtrace['spans_dropped_remote_total']} "
             f"dropped); token-exact {dtrace['streams_token_exact']}")
        _write_record(record, args.out)
        return

    if args.disagg_only:
        repeats = max(args.repeats, 5)
        args.repeats = repeats
        _log(f"disagg leg only: {args.disagg_requests} long-prompt "
             f"trace requests, N={args.disagg_replicas} unified vs "
             f"same-N role split, {repeats} paired runs")
        disagg = _disagg_leg(args)
        record = {
            "metric": "fleet_serving_disaggregated_prefill_decode",
            "unit": "ratio (split/unified decode p99 inter-token "
                    "latency; split/unified aggregate tok/s); "
                    "milliseconds (KV hand-off)",
            "config": {
                "model": (f"gpt {args.depth}x{args.embed_dim} "
                          f"(vocab {args.vocab}, max_len "
                          f"{args.max_len})"),
                "slots_per_replica": args.slots,
                "replicas": args.disagg_replicas,
                "prefill_len": _disagg_prefill_len(args),
                "prompt_base": args.disagg_prompt_base,
                "offered_load_x_capacity": args.disagg_load,
                "roles": "router-side role-aware routing + "
                         "first-token KV hand-off, WAL-journaled "
                         "rebind (pddl_tpu/serve/fleet/disagg.py)",
                "transfer": "export_prefix_chain -> host-tier "
                            "import on in-process replicas "
                            "(models TPU-DMA transfer cost << "
                            "compute; a CPU pipe would price the "
                            "copy at compute parity), fresh-rid "
                            "hedge-alias rebind",
                "latency_attribution": "per-token latency = wall "
                                       "duration of the engine tick "
                                       "that produced the token; "
                                       "first tokens excluded",
            },
            "provenance": provenance(repeats),
            "results": {"disagg": disagg},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"disagg: decode p99 "
             f"{disagg['unified_decode_lat_p99_ms']}ms -> "
             f"{disagg['split_decode_lat_p99_ms']}ms "
             f"({disagg['decode_p99_interference']}x, bound "
             f"{disagg['decode_p99_interference_bound']}x); tok/s "
             f"retained {disagg['tokens_per_s_retained_x']}x (floor "
             f"{disagg['tokens_per_s_retained_floor']}x); hand-off "
             f"{disagg['handoff_ms']}ms median, "
             f"{disagg['handoffs_completed_total']} shipped; "
             f"token-exact "
             f"{disagg['streams_token_exact_split_vs_unified']}")
        _write_record(record, args.out)
        return

    if args.ctrlplane_only:
        repeats = max(args.repeats, 5)
        _log(f"ctrlplane leg only: wire storm + WAL recovery + gray "
             f"hedging, {repeats} paired runs each, gpt 2x64")
        ctrl = _ctrlplane_leg(args)
        record = {
            "metric": "fleet_serving_ctrlplane_durability",
            "unit": "ratio (storm/clean tok_s retained; hedge-off/on "
                    "interactive p99 TTFT); seconds (WAL recovery)",
            "config": {
                "model": "gpt 2x64 (vocab 64, max_len 128)",
                "process_replicas": 2,
                "wire_fault_rate_per_frame": 0.01,
                "transport": "PF1 length+CRC32+seq framing, dup "
                             "suppression, gap detection, bounded "
                             "resend (serve/fleet/transport.py)",
                "journal": "CRC-framed fsync-batched router WAL, "
                           "checkpoint+rotate cycle, mirror-replay "
                           "recovery (serve/fleet/journal.py)",
                "gray": "self-baseline latency-quantile detector, "
                        "first-result-wins interactive hedging "
                        "(serve/fleet/health.py GrayDetector)",
            },
            "provenance": provenance(repeats),
            "results": {"ctrlplane": ctrl},
            "device": jax.devices()[0].device_kind,
        }
        wire, rec, hedge = (ctrl["wire"], ctrl["recovery"],
                            ctrl["hedge"])
        _log(f"ctrlplane: wire retained "
             f"{wire['throughput_retained_x']}x at 1% frame faults "
             f"({wire['wire_crc_rejects_total']} CRC rejects, "
             f"{wire['wire_retries_total']} retries, token-exact "
             f"{wire['streams_token_exact']}); recovery "
             f"{rec['recovery_s']}s median "
             f"({rec['streams_revived_per_repeat']} streams, "
             f"token-exact {rec['streams_token_exact']}); hedging cut "
             f"interactive p99 TTFT {hedge['ttft_p99_hedge_off_s']}s "
             f"-> {hedge['ttft_p99_hedge_on_s']}s "
             f"({hedge['hedged_ttft_p99_reduction_x']}x, "
             f"{hedge['hedge_wins_total']} hedge wins)")
        _write_record(record, args.out)
        return

    if args.autoscale_only:
        _log(f"autoscale leg only: diurnal "
             f"{args.autoscale_duration:.0f}s trace, autoscale "
             f"{args.autoscale_min}..{args.autoscale_max} vs static "
             f"{{{args.autoscale_static}}}, 4 slots/replica")
        auto = _autoscale_leg(args)
        record = {
            "metric": "fleet_serving_elastic_autoscale",
            "unit": "goodput tokens per replica-hour (finished tokens "
                    "over integrated replica-hours, spawning included)",
            "config": {
                "model": "gpt 6x192 (vocab 64, max_len 128)",
                "slots_per_replica": 4,
                "autoscale_min": args.autoscale_min,
                "autoscale_max": args.autoscale_max,
                "static_sweep": args.autoscale_static,
                "offered_mean_x_capacity": args.autoscale_offered,
                "peak_to_trough": args.autoscale_peak_trough,
                "duration_s": args.autoscale_duration,
                "attainment_floor": args.autoscale_attainment_floor,
                "controller": "hysteretic pressure+load bands, "
                              "concurrent wait_ready warm-start "
                              "scale-up, drain-snapshot live-migration "
                              "scale-down "
                              "(pddl_tpu/serve/fleet/autoscaler.py)",
                "admission": "overload detector + brownout ladder "
                             "armed on every fleet "
                             "(pddl_tpu/serve/fleet/admission.py)",
                "replay": "hint-honoring open-loop client "
                          "(pddl_tpu/serve/fleet/replay.py)",
            },
            "provenance": provenance(max(args.repeats, 5)),
            "results": {"autoscale": auto},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"autoscale: {auto['goodput_per_replica_hour']:,.0f} "
             f"goodput tok/replica-hour = "
             f"{auto['goodput_per_replica_hour_vs_best_static_x']}x "
             f"best static (N={auto['best_static_replicas']}); "
             f"scale_events >= {auto['scale_events']}/wave, migrated "
             f"{auto['migrated_zero_lost']} with "
             f"{auto['requests_lost_total']} lost; rung time "
             f"{auto['brownout_rung_time_autoscaled_s']}s vs "
             f"{auto['brownout_rung_time_static_under_s']}s "
             f"under-provisioned static")
        _write_record(record, args.out)
        return

    if args.slo_only:
        model_desc = (f"gpt {args.depth}x{args.embed_dim} "
                      f"(vocab {args.vocab}, max_len {args.max_len})")
        _log(f"slo leg only: {args.slo_requests} trace requests at "
             f"{args.slo_overload}x capacity, {args.slo_replicas} "
             f"process replicas x {args.slots} slots, {model_desc}")
        slo = _slo_leg(args, overload_x=args.slo_overload)
        record = {
            "metric": "online_serving_slo_overload",
            "unit": "ratio (interactive p99 TTFT overload/uncontended; "
                    "best_effort shed fraction)",
            "config": {
                "model": model_desc,
                "slots_per_replica": args.slots,
                "process_replicas": args.slo_replicas,
                "prefill_len": args.prefill_len,
                "overload_x_capacity": args.slo_overload,
                "scheduler": "priority classes + EDF + aging_s=3.0 + "
                             "best_effort preemption, "
                             "prefill_slice_tokens=2*prefill_len "
                             "(pddl_tpu/serve/scheduler.py)",
                "admission": "per-priority token buckets + overload "
                             "detector + hysteretic brownout ladder "
                             "(pddl_tpu/serve/fleet/admission.py)",
            },
            "provenance": provenance(args.repeats),
            "results": {"slo": slo},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"slo: interactive p99 "
             f"{slo['uncontended_interactive_ttft_p99_s']}s -> "
             f"{slo['overload_interactive_ttft_p99_s']}s at "
             f"{args.slo_overload}x "
             f"({slo['interactive_ttft_p99_overload_over_uncontended_x']}"
             f"x, bound {slo['interactive_ttft_ratio_bound']}x); "
             f"best_effort absorbed "
             f"{slo['best_effort_shed_absorbed_frac']:.0%} of sheds "
             f"(bound 80%); lost/hung "
             f"{slo['requests_lost_or_hung_total']}")
        _write_record(record, args.out)
        return

    if args.fleet_only:
        replica_counts = [int(n) for n in
                          args.fleet_replicas.split(",") if n]
        kill_ns = [k for k in (2, 4) if k in replica_counts]
        _log(f"fleet leg only: N in {replica_counts}, "
             f"{args.slots} slots/replica, Poisson at "
             f"{args.fleet_load:.0%} of N x r08 baseline, kill leg at "
             f"N in {kill_ns or '(none: no N in {2, 4} requested)'}")
        fleet_results = _fleet_leg(args, replica_counts,
                                   load_frac=args.fleet_load)
        record = {
            "metric": "fleet_serving_scaling_and_failover",
            "unit": "tokens/sec aggregate (fleet, process replicas)",
            "config": {
                "model": (f"gpt {args.depth}x{args.embed_dim} "
                          f"(vocab {args.vocab}, max_len "
                          f"{args.max_len})"),
                "slots_per_replica": args.slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prompt_len,
                "new_tokens": args.new_tokens,
                "fleet_load_fraction": args.fleet_load,
                "router": "prefix-affinity + rendezvous hash + sticky "
                          "sessions; per-replica circuit breaker; "
                          "drain-format live migration with "
                          "replay-mirror fallback "
                          "(pddl_tpu/serve/fleet/)",
            },
            "provenance": provenance(args.repeats),
            "results": {"fleet": fleet_results},
            "device": jax.devices()[0].device_kind,
        }
        _write_record(record, args.out)
        return

    model = GPT(vocab_size=args.vocab, max_len=args.max_len,
                embed_dim=args.embed_dim, depth=args.depth,
                num_heads=args.heads, attention="reference")
    dummy = jnp.ones((1, args.prompt_len), jnp.int32)
    params = model.init(jax.random.key(0), dummy, train=False)["params"]
    variables = {"params": params}
    model_desc = (f"gpt {args.depth}x{args.embed_dim} "
                  f"(vocab {args.vocab}, max_len {args.max_len})")

    if args.spec_only:
        k_values = [int(k) for k in args.spec_k_curve.split(",") if k]
        # A dedicated small serving model (the r16 sized-worker
        # discipline): speculation converts per-tick FIXED cost into
        # extra tokens, which is the accelerator regime — decode is
        # memory-bound there, so a k+1-wide verify is near-free — and
        # on XLA-CPU, where per-op compute scales with width, the
        # regime only exists while the model's per-token compute stays
        # small against the tick overhead. 2x64 keeps the bench in
        # that regime at real batch; long 256-token decodes amortize
        # each stream's pre-loop transient, where the n-gram drafter
        # has no self-similarity to mine yet.
        spec_model = GPT(vocab_size=64, max_len=512, embed_dim=64,
                         depth=2, num_heads=4, attention="reference")
        sdummy = jnp.ones((1, 32), jnp.int32)
        sparams = spec_model.init(jax.random.key(0), sdummy,
                                  train=False)["params"]
        spec_desc = "gpt 2x64 (vocab 64, max_len 512)"
        spec_slots, spec_reqs, spec_new = 4, 8, 256
        _log(f"spec leg only: {spec_reqs} requests x {spec_new} tokens "
             f"through {spec_slots} slots, k={args.spec_k} (curve "
             f"{k_values}), {spec_desc}")
        spec = _spec_leg(
            spec_model, {"params": sparams}, n_requests=spec_reqs,
            prompt_len=args.prompt_len, new_tokens=spec_new,
            slots=spec_slots, prefill_len=args.prefill_len,
            spec_k=args.spec_k, k_values=k_values, vocab=64,
            repeats=max(args.repeats, 5))
        record = {
            "metric": "online_serving_speculative",
            "unit": "tokens/sec aggregate (spec vs plain engine, "
                    "paired runs, matched batch)",
            "config": {
                "model": spec_desc,
                "slots": spec_slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prompt_len,
                "new_tokens": spec_new,
                "n_requests": spec_reqs,
                "spec_k": args.spec_k,
                "drafter": "shared n-gram prompt-lookup "
                           "(models/speculative.ngram_drafts), "
                           "zero extra weights",
                "spec": "per-slot draft/verify in the fused tick: one "
                        "[S, k+1] wide-logits verify dispatch, "
                        "accepted length a runtime [S] array "
                        "(serve/engine.py spec_k)",
            },
            "provenance": provenance(max(args.repeats, 5)),
            "results": {"spec": spec},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"spec: {spec['spec_tok_s']:,.0f} tok/s vs "
             f"{spec['baseline_tok_s']:,.0f} plain = "
             f"{spec['spec_speedup_x']}x at k={args.spec_k} (pairs "
             f"{spec['spec_speedup_per_pair']}), acceptance "
             f"{spec['acceptance_rate']:.2f}, "
             f"{spec['tokens_per_tick']} tok/tick; chaos "
             f"{spec['chaos']['requests_token_exact']} requests "
             f"token-exact ({spec['chaos']['replays']} replays, "
             f"{spec['chaos']['requests_migrated']} migrated)")
        _write_record(record, args.out)
        return

    if args.tier_only:
        mults = tuple(int(m) for m in args.tier_mults.split(",") if m)
        # The TTFT curve runs on the DEFAULT 4x256 model (the tier's
        # lever is prefill compute avoided — see _tier_leg's sizing
        # note); the fleet duplicate-prefill leg is a token-COUNT
        # proof, so a small model keeps its 2 replicas cheap.
        fleet_model = GPT(vocab_size=64, max_len=128, embed_dim=64,
                          depth=2, num_heads=4, attention="reference")
        fdummy = jnp.ones((1, 32), jnp.int32)
        fparams = fleet_model.init(jax.random.key(0), fdummy,
                                   train=False)["params"]
        _log(f"tier leg only: Zipf working sets {list(mults)}x a "
             f"2-prompt device pool, tiered vs evict-and-recompute, "
             f"{model_desc}; + 2-replica chain-pull leg (gpt 2x64)")
        repeats = max(args.repeats, 5)
        tier = _tier_leg(model, variables, repeats=repeats, mults=mults)
        fleet = _tier_fleet_leg(fleet_model, {"params": fparams},
                                repeats=repeats)
        record = {
            "metric": "online_serving_tiered_kv",
            "unit": "ratio (tiered/evict mean TTFT at matched traces; "
                    "duplicate prefill tokens, blind vs pulled)",
            "config": {
                "model": model_desc,
                "slots": 2,
                "prefill_len": 384,
                "prompt_len": tier["prompt_len"],
                "device_pool_blocks": tier["device_pool_blocks"],
                "zipf_a": tier["zipf_a"],
                "working_set_mults": list(mults),
                "tier": "byte-budgeted pinned-host spill tier under "
                        "the radix index; eviction demotes D2H, "
                        "admission promotes via host_promote "
                        "(serve/kvcache/hosttier.py)",
                "fleet": "2 LocalReplica + shadow host tier + "
                         "chain_pull_blocks=2 (drain-module chain "
                         "wire format)",
            },
            "provenance": provenance(repeats),
            "results": {"tier": tier, "fleet": fleet},
            "device": jax.devices()[0].device_kind,
        }
        at8 = next((c for c in tier["curve"]
                    if c["working_set_x"] == 8), None)
        head = (f"mean-TTFT tiered/evict "
                f"{at8['ttft_tiered_over_evict_x']}x at the 8x working "
                f"set, hit rate {at8['hit_rate_tiered']} vs "
                f"{at8['hit_rate_evict']}" if at8 is not None
                else "custom sweep (no 8x point)")
        _log(f"tier: {head} (curve "
             f"{[(c['working_set_x'], c['ttft_tiered_over_evict_x']) for c in tier['curve']]}, "
             f"all pairs directional: {tier['all_pairs_directional']}); "
             f"fleet duplicate prefill "
             f"{fleet['duplicate_prefill_tokens_blind']} -> "
             f"{fleet['duplicate_prefill_tokens_pulled']} tokens "
             f"({fleet['chain_pulls']} pulls)")
        _write_record(record, args.out)
        return

    if args.tenant_only:
        _log(f"tenant leg only: {2 * args.concurrent} requests over "
             f"{args.tenant_adapters} adapters + constrained mix, "
             f"{args.slots} slots, {model_desc}")
        tenant = _tenant_leg(
            model, variables, n_requests=2 * args.concurrent,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            slots=args.slots, prefill_len=args.prefill_len,
            n_adapters=args.tenant_adapters, vocab=args.vocab,
            repeats=args.repeats)
        record = {
            "metric": "online_serving_multi_tenant",
            "unit": "ratio (N merged copies / base+pool bytes; "
                    "tenant/plain tok_s; unconstrained/constrained "
                    "tok_s)",
            "config": {
                "model": model_desc,
                "slots": args.slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prompt_len,
                "new_tokens": args.new_tokens,
                "n_adapters": args.tenant_adapters,
                "tenant": "paged per-request LoRA adapters (LM-head "
                          "target, rank-8 pool, pin-on-admit/LRU) + "
                          "grammar token-mask decoding "
                          "(serve/tenant/, ops/lora.py)",
            },
            "provenance": provenance(args.repeats),
            "results": {"tenant": tenant},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"tenant: {args.tenant_adapters} adapters from one base "
             f"copy = {tenant['merged_copy_eliminated_x']}x merged-copy "
             f"elimination ({tenant['adapter_pool_bytes']} pool bytes "
             f"vs {tenant['base_params_bytes']} per copy); mixed-tenant "
             f"{tenant['mixed_tenant_tok_s']} tok/s = "
             f"{tenant['tenant_throughput_retained_x']}x the plain "
             f"engine; constrained decode mask overhead "
             f"{tenant['mask_overhead_x']}x")
        _write_record(record, args.out)
        return

    if args.paged_only:
        _log(f"paged leg only: {args.slots} concurrent streams x "
             f"{args.prefix_prompt_len}-token prompts at "
             f"{args.prefix_shared_frac:.0%} shared, paged vs "
             f"resident-row, {model_desc}")
        paged = _paged_leg(
            model, variables, prompt_len=args.prefix_prompt_len,
            shared_frac=args.prefix_shared_frac,
            new_tokens=args.prefix_new_tokens + 24,
            slots=args.slots,
            prefill_len=max(args.prefill_len, args.prefix_prompt_len),
            block_size=args.prefix_block_size, chunk=args.prefix_chunk,
            vocab=args.vocab, repeats=args.repeats)
        record = {
            "metric": "online_serving_paged_attention",
            "unit": "ratio (row/paged KV bytes for the same live "
                    "streams; row/paged prefix-hit admission TTFT)",
            "config": {
                "model": model_desc,
                "slots": args.slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prefix_prompt_len,
                "shared_frac": args.prefix_shared_frac,
                "prefix_block_size": args.prefix_block_size,
                "paged": "per-slot block tables over the shared pool; "
                         "pin-on-admit, in-place suffix append, "
                         "bookkeeping-only donation "
                         "(ops/attention.paged_decode_attention, "
                         "serve/engine.py paged mode)",
            },
            "provenance": provenance(args.repeats),
            "results": {"paged": paged},
            "device": jax.devices()[0].device_kind,
        }
        _log(f"paged: duplicate KV eliminated "
             f"{paged['duplicate_kv_eliminated_x']}x at matched pool "
             f"bytes ({paged['kv_bytes_used_row']} -> "
             f"{paged['kv_bytes_used_paged']} bytes for "
             f"{paged['tokens_resident']} resident tokens); prefix-hit "
             f"admission {paged['hit_admission_speedup_x']}x vs gather "
             f"({paged['hit_admission_ttft_row_s']}s -> "
             f"{paged['hit_admission_ttft_paged_s']}s; copy "
             f"{paged['admission_copy_us_row']}us -> "
             f"{paged['admission_copy_us_paged']}us per admission)")
        _write_record(record, args.out)
        return

    if args.obs_only:
        _log(f"observability leg only: {2 * args.concurrent} requests "
             f"x {args.new_tokens} tokens, tracing off vs on, "
             f"{model_desc}")
        obs = _obs_leg(
            model, variables, n_requests=2 * args.concurrent,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            slots=args.slots, prefill_len=args.prefill_len,
            vocab=args.vocab, repeats=args.repeats)
        record = {
            "metric": "online_serving_observability_overhead",
            "unit": "ratio (tracing on / off, paired runs)",
            "config": {
                "model": model_desc,
                "slots": args.slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prompt_len,
                "observability": "per-request spans (obs/trace.py) -> "
                                 "JSONL sink (obs/export.py); per-tick "
                                 "telemetry ring always on "
                                 "(obs/ring.py)",
            },
            "provenance": provenance(args.repeats),
            "results": {"obs": obs},
            "device": jax.devices()[0].device_kind,
        }
        _log_obs_leg(obs)
        _maybe_write_trace(args, model, variables)
        _write_record(record, args.out)
        return

    if args.faults_only:
        _log(f"fault leg only: {2 * args.concurrent} requests x "
             f"{args.new_tokens} tokens at {args.fault_rate:.1%} "
             f"injected faults, {model_desc}")
        faults = _fault_leg(
            model, variables, n_requests=2 * args.concurrent,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            slots=args.slots, prefill_len=args.prefill_len,
            fault_rate=args.fault_rate, vocab=args.vocab,
            repeats=args.repeats)
        record = {
            "metric": "online_serving_fault_tolerance",
            "unit": "ratio (faulted / clean, paired runs)",
            "config": {
                "model": model_desc,
                "slots": args.slots,
                "prefill_len": args.prefill_len,
                "prompt_len": args.prompt_len,
                "recovery": "retry (bounded exp backoff) + replay "
                            "(prompt re-prefill, tokens re-fed) + "
                            "degraded prefix cache on OOM",
            },
            "provenance": provenance(args.repeats),
            "results": {"faults": faults},
            "device": jax.devices()[0].device_kind,
        }
        _log_fault_leg(faults)
        _maybe_write_trace(args, model, variables)
        _write_record(record, args.out)
        return

    prompts = _make_requests(args.concurrent, args.prompt_len,
                             args.new_tokens, args.vocab)
    _log(f"head-to-head: {args.concurrent} requests x "
         f"{args.new_tokens} tokens, {model_desc}")
    seq_tps, seq_spread = _sequential_baseline(
        model, variables, prompts, args.new_tokens, repeats=args.repeats)
    eng_tps, eng_spread, eng = _engine_concurrent(
        model, variables, prompts, args.new_tokens, args.slots,
        args.prefill_len, repeats=args.repeats)
    counts = eng.compile_counts()
    speedup = eng_tps / seq_tps
    _log(f"sequential generate(): {seq_tps:,.0f} tok/s (spread "
         f"{seq_spread:.1f}%); engine ({args.slots} slots): "
         f"{eng_tps:,.0f} tok/s (spread {eng_spread:.1f}%, "
         f"{speedup:.2f}x); compile counts {counts}")

    # Offered loads relative to the measured closed-loop capacity:
    # comfortable, busy, oversaturated (the admission-control point).
    cap_rps = eng_tps / args.new_tokens
    record = {
        "metric": "online_serving_tokens_per_sec",
        "unit": "tokens/sec/chip",
        "config": {
            "model": model_desc,
            "slots": args.slots,
            "prefill_len": args.prefill_len,
            "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "concurrent": args.concurrent,
            "poisson_requests_per_load": args.poisson_requests,
            "max_queue_depth": args.max_queue_depth,
            "scheduler": "FCFS, prefill-token budget, typed QueueFull "
                         "shedding",
        },
        "provenance": provenance(args.repeats),
        "results": {
            "concurrent_sequential_tokens_per_s": round(seq_tps, 1),
            "concurrent_sequential_spread_pct": round(seq_spread, 2),
            "concurrent_engine_tokens_per_s": round(eng_tps, 1),
            "concurrent_engine_spread_pct": round(eng_spread, 2),
            "concurrent_speedup": round(speedup, 3),
            "engine_compile_counts_after_run": counts,
            # Tail latencies for the head-to-head engine run, not just
            # the throughput headline.
            "serve_metrics_snapshot": eng.metrics.snapshot(),
            "poisson": [],
        },
        "device": jax.devices()[0].device_kind,
    }

    prefix = _prefix_ttft_leg(
        model, variables, n_requests=args.prefix_requests,
        prompt_len=args.prefix_prompt_len,
        shared_frac=args.prefix_shared_frac,
        new_tokens=args.prefix_new_tokens, slots=args.prefix_requests,
        prefill_len=max(args.prefill_len, args.prefix_prompt_len),
        block_size=args.prefix_block_size, chunk=args.prefix_chunk,
        vocab=args.vocab, repeats=args.repeats)
    record["results"]["prefix"] = prefix
    _log(f"shared-prefix x{args.prefix_shared_frac}: mean TTFT "
         f"{prefix['mean_ttft_prefix_off_s']}s off -> "
         f"{prefix['mean_ttft_prefix_on_s']}s on "
         f"({prefix['ttft_reduction_x']}x, hit rate "
         f"{prefix['prefix_hit_rate']}, saved "
         f"{prefix['prefill_tokens_saved']} prefill tokens)")

    if args.fault_rate > 0:
        faults = _fault_leg(
            model, variables, n_requests=2 * args.concurrent,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            slots=args.slots, prefill_len=args.prefill_len,
            fault_rate=args.fault_rate, vocab=args.vocab,
            repeats=args.repeats)
        record["results"]["faults"] = faults
        _log_fault_leg(faults)

    obs = _obs_leg(
        model, variables, n_requests=2 * args.concurrent,
        prompt_len=args.prompt_len, new_tokens=args.new_tokens,
        slots=args.slots, prefill_len=args.prefill_len,
        vocab=args.vocab, repeats=args.repeats)
    record["results"]["obs"] = obs
    _log_obs_leg(obs)
    _maybe_write_trace(args, model, variables)

    for frac in (() if args.skip_poisson else (0.3, 0.6, 1.2)):
        res = _poisson_load(
            model, variables, offered_rps=frac * cap_rps,
            n_requests=args.poisson_requests,
            prompt_len=args.prompt_len, new_tokens=args.new_tokens,
            vocab=args.vocab, slots=args.slots,
            prefill_len=args.prefill_len,
            max_queue_depth=args.max_queue_depth, seed=int(frac * 100))
        res["offered_fraction_of_capacity"] = frac
        record["results"]["poisson"].append(res)
        _log(f"poisson x{frac}: offered {res['offered_tokens_per_s']} "
             f"tok/s -> served {res['tokens_per_s']} tok/s, TTFT p50 "
             f"{res['ttft_p50_s']}s p99 {res['ttft_p99_s']}s, queue "
             f"{res['mean_queue_depth']}, occupancy "
             f"{res['mean_slot_occupancy']}, rejected "
             f"{res['requests_rejected_queue_full']}")

    _write_record(record, args.out)


if __name__ == "__main__":
    main()
