"""Speculative decoding throughput on one chip (single-stream serving).

ARCHITECTURE.md §7e attributed single-stream decode to a fixed per-tick
serial-latency cost (~0.29 ms on v5e through the tunnel) and named
multi-token decoding as the remaining lever. This bench measures that
lever end to end: :func:`pddl_tpu.models.speculative.generate_speculative`
(prompt-lookup drafting, exact greedy output) against plain
:func:`~pddl_tpu.models.gpt.generate` on the SAME trained model and
prompts.

Honesty requirements baked in:

- The model is TRAINED (briefly, on the byte-level CPython corpus the
  convergence tracks use) — acceptance rate on random weights is
  meaningless because drafts are verified against the model's own argmax.
- Both the favorable case (real Python source prompts — repetitive, the
  draft's home turf) and the adversarial case (uniform-random token
  prompts, where lookup never helps and every tick still pays a
  draft_len+1-wide verify) are reported. The worst case bounds the
  regression a serving stack could ever see from leaving speculation on.
- Outputs are asserted EQUAL to plain greedy before any timing counts.

    PYTHONPATH=. python benchmarks/specdecode_bench.py \
        [--train-steps 600] [--out artifacts/gpt_bench/r05_specdecode.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.models.gpt import generate
from pddl_tpu.models.llama import Llama_Small
from pddl_tpu.models.speculative import generate_speculative
from pddl_tpu.utils.bench_artifact import provenance, timed_stats


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _train_on_pycorpus(model, steps: int, seq_len: int, batch: int,
                       work_dir: str, param_update: str = "plain"):
    """Brief byte-level LM training; returns (params, val_tokens)."""
    from examples.real_data_convergence import (_build_atomically,
                                                build_python_corpus)
    from pddl_tpu.data.text import load_token_corpus
    from pddl_tpu.parallel.single import SingleDeviceStrategy
    from pddl_tpu.train.loop import Trainer

    data_dir = os.path.join(work_dir, "pycorpus")
    _build_atomically(data_dir, build_python_corpus)
    train_ds, val_ds = load_token_corpus(
        data_dir, seq_len=seq_len, train_batch_size=batch,
        val_batch_size=batch)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-4,
                 strategy=SingleDeviceStrategy(), seed=0,
                 param_update=param_update,
                 input_key="tokens", target_key="targets")
    t0 = time.time()
    hist = tr.fit(train_ds, epochs=1, steps_per_epoch=steps, verbose=0)
    _log(f"trained {steps} steps in {time.time() - t0:.0f}s, "
         f"final loss {hist.history['loss'][-1]:.3f}")
    # Keep params ON DEVICE: host arrays would re-cross the (tunneled)
    # transport on every timed call and measure the link, not the chip.
    params = tr.state.params
    val_tokens = val_ds._tokens  # flat byte-token array (held-out split)
    return params, val_tokens, float(hist.history["loss"][-1])


def _bench_pair(model, variables, prompt, new_tokens: int,
                draft_len: int, ngram: int, temperature: float = 0.0,
                top_k=None, n_repeats: int = 3):
    """(plain tok/s, spec tok/s, stats, spreads) on one prompt batch —
    timing is median-over-``n_repeats`` with spread recorded
    (`pddl_tpu/utils/bench_artifact.py` discipline).

    Greedy: asserts speculative output == greedy output before timing.
    Sampling (temperature > 0): outputs are draws, not unique strings —
    the check becomes the SUPPORT invariant instead: every emitted
    token must have nonzero probability under the model's own
    recomputed FILTERED conditional. The filter must be sharp for the
    check to discriminate anything (with temperature alone the whole
    vocab is in support and the assertion is vacuous), which is why the
    sampled bench runs with ``top_k`` on — generation and verification
    share the same filter, so a token outside the recomputed top-k set
    is a real exactness violation."""
    import jax

    sample_kw = ({} if temperature <= 0
                 else {"temperature": temperature, "top_k": top_k,
                       "rng": jax.random.key(0)})
    out, stats = generate_speculative(
        model, variables, prompt, new_tokens, draft_len=draft_len,
        ngram=ngram, return_stats=True, **sample_kw)
    if temperature <= 0:
        ref = generate(model, variables, prompt, max_new_tokens=new_tokens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    else:
        from pddl_tpu.models.gpt import filtered_logits

        logits = model.apply(variables, out[:, :-1], train=False)
        flog = filtered_logits(logits, temperature=temperature, top_k=top_k)
        sel = np.take_along_axis(
            np.asarray(flog), np.asarray(out)[:, 1:, None], axis=-1)[..., 0]
        p = prompt.shape[1]
        assert np.all(np.isfinite(sel[:, p - 1:])), "token outside support"

    b = prompt.shape[0]
    sync = lambda x: int((x[0] if isinstance(x, tuple) else x)[0, -1])
    s_plain = timed_stats(
        lambda: generate(model, variables, prompt, max_new_tokens=new_tokens,
                         **sample_kw),
        sync, n_repeats=n_repeats)
    s_spec = timed_stats(
        lambda: generate_speculative(model, variables, prompt, new_tokens,
                                     draft_len=draft_len, ngram=ngram,
                                     **sample_kw),
        sync, n_repeats=n_repeats)
    spreads = {"plain": s_plain["spread_pct"], "spec": s_spec["spread_pct"]}
    return (b * new_tokens / s_plain["median_s"],
            b * new_tokens / s_spec["median_s"], stats, spreads)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--train-steps", type=int, default=600)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--train-batch", type=int, default=16)
    p.add_argument("--prompt-len", type=int, default=256)
    p.add_argument("--new-tokens", type=int, default=256)
    p.add_argument("--draft-len", type=int, default=7)
    p.add_argument("--ngram", type=int, default=3)
    p.add_argument("--int8", action="store_true",
                   help="also evaluate weight-only int8 serving: "
                        "val-loss delta of the quantized model on "
                        "held-out text, and int8 x speculative "
                        "throughput (exactness asserted against the "
                        "quantized model's own greedy decode)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="> 0: measure SAMPLED speculation (rejection "
                        "verifier; acceptance is probabilistic, so the "
                        "speedup is the honest serving number for "
                        "temperature sampling, lower than greedy's)")
    p.add_argument("--top-k", type=int, default=8,
                   help="sampled mode only: top-k filter applied to BOTH "
                        "generation and the support-invariant "
                        "verification pass. Must be sharp (small) for "
                        "the invariant to be discriminative — with "
                        "temperature alone every token is in support "
                        "and the check is vacuous. 0 disables (and "
                        "downgrades the exactness claim accordingly)")
    p.add_argument("--batches", default="1",
                   help="comma-joined batch sizes, e.g. 1,4,8. B>1 "
                        "quantifies the min-over-batch acceptance cost "
                        "(the KV caches share one scalar index, so each "
                        "tick emits the batch's WORST row's acceptance "
                        "— see speculative.py; the suite pins the "
                        "behavior in tests/test_speculative.py)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed repetitions per series (>= 3; median is "
                        "the headline, spread the drift detector)")
    p.add_argument("--family", default="llama_small",
                   choices=("llama_small", "llama_1b"),
                   help="llama_1b: the 1B-on-one-chip serving story -- "
                        "trained with the safe bf16 recipe (stochastic "
                        "rounding), where int8 x speculation matters "
                        "most (the 1B is weight-read-bound)")
    p.add_argument("--work-dir", default="/tmp/pddl_specdecode")
    p.add_argument("--out", default="")
    args = p.parse_args()

    # Serving configuration: bf16 storage + compute, same as decode_bench.
    if args.family == "llama_1b":
        from pddl_tpu.models.llama import Llama_1B

        model = Llama_1B(vocab_size=256, max_len=1024,
                         dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
        model_desc = "llama_1b (16x2048, GQA 32/8, vocab 256)"
        # bf16 params on one chip -> the measured-safe update rule
        # (docs/CONVERGENCE.md): stochastic rounding, bf16 moments.
        param_update = "stochastic_round"
        args.train_batch = min(args.train_batch, 8)
    else:
        model = Llama_Small(vocab_size=256, max_len=1024,
                            dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
        model_desc = "llama_small (12x768, GQA 12/4, vocab 256)"
        param_update = "plain"
    params, val_tokens, final_loss = _train_on_pycorpus(
        model, args.train_steps, args.seq_len, args.train_batch,
        args.work_dir, param_update)
    variables = {"params": params}

    # Real-text prompts: held-out Python source windows at spread-out
    # offsets (B>1 rows are DISTINCT windows — realistic mixed traffic,
    # each row drafting off its own self-similarity). Random prompts:
    # uniform bytes — the lookup's adversarial case.
    batches = [int(b) for b in args.batches.split(",")]

    def text_prompt(b):
        starts = [len(val_tokens) // 3 + i * (args.prompt_len + 37)
                  for i in range(b)]
        return jnp.stack([jnp.asarray(
            val_tokens[s:s + args.prompt_len], jnp.int32)
            for s in starts])

    def rand_prompt(b):
        return jax.random.randint(
            jax.random.key(7), (b, args.prompt_len), 0, 256,
            dtype=jnp.int32)

    record = {
        "metric": "speculative_decode_new_tokens_per_sec",
        "unit": "tokens/sec/chip",
        "config": {
            "model": model_desc,
            "param_update": param_update,
            "trained_steps": args.train_steps,
            "final_train_loss_nats": round(final_loss, 4),
            "prompt_len": args.prompt_len, "new_tokens": args.new_tokens,
            "draft_len": args.draft_len, "ngram": args.ngram,
            "dtype": "bfloat16", "batch": 1,
            "temperature": args.temperature,
            "top_k": (args.top_k or None) if args.temperature > 0 else None,
            "exactness": (
                "speculative output asserted equal to greedy generate() "
                "before every timed series" if args.temperature <= 0 else
                f"sampling mode: support invariant asserted against the "
                f"model's recomputed top_k={args.top_k} filtered "
                "conditional (generation and verification share the "
                "sharp filter, so an out-of-support token is a real "
                "violation)" if args.top_k else
                "sampling mode: support check run WITHOUT a sharp "
                "filter (top_k=0) — vacuous at these settings, speed "
                "numbers only"),
        },
        "provenance": provenance(args.repeats),
        "results": {},
        "device": jax.devices()[0].device_kind,
    }
    record["config"]["batches"] = batches
    for b in batches:
        for kind, prompt in (("pycorpus", text_prompt(b)),
                             ("random", rand_prompt(b))):
            plain, spec, stats, spreads = _bench_pair(
                model, variables, prompt, args.new_tokens,
                args.draft_len, args.ngram, args.temperature,
                top_k=(args.top_k or None) if args.temperature > 0
                else None, n_repeats=args.repeats)
            # B1 keeps the legacy key names so artifact consumers (and
            # round-over-round diffs) stay comparable.
            suffix = f"b{b}" if b > 1 else "b1"
            key = (f"{kind}_speedup" if b == 1
                   else f"{kind}_speedup_{suffix}")
            record["results"][f"{kind}_plain_{suffix}"] = round(plain, 1)
            record["results"][f"{kind}_speculative_{suffix}"] = round(
                spec, 1)
            record["results"][key] = round(spec / plain, 3)
            record["results"][f"{kind}_tokens_per_tick"
                              + ("" if b == 1 else f"_{suffix}")] = round(
                stats["tokens_per_tick"], 3)
            record["results"][f"{kind}_{suffix}_spread_pct"] = round(
                max(spreads.values()), 2)
            _log(f"{kind} B{b}: plain {plain:,.0f} tok/s, speculative "
                 f"{spec:,.0f} tok/s ({spec / plain:.2f}x, "
                 f"{stats['tokens_per_tick']:.2f} tokens/tick, spread "
                 f"{max(spreads.values()):.1f}%)")

    if args.int8:
        from pddl_tpu.ops.quant import (dequantize, quantize_int8,
                                        quantized_bytes)

        qparams = quantize_int8(params)

        # Quality: mean CE (nats/byte) over held-out windows, quantized
        # weights vs the bf16 originals — the number a serving owner
        # trades against the bytes.
        n_eval, ebatch = 16, 8
        win = args.seq_len + 1
        starts = np.linspace(0, len(val_tokens) - win, n_eval * ebatch,
                             dtype=np.int64)
        chunks = np.stack([np.asarray(val_tokens[s:s + win])
                           for s in starts]).astype(np.int32)

        @jax.jit
        def ce(p, tokens, targets):
            logits = model.apply({"params": p}, tokens, train=False)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(
                logp, targets[..., None], axis=-1))

        def eval_loss(p):
            losses = [float(ce(p, jnp.asarray(c[:, :-1]),
                               jnp.asarray(c[:, 1:])))
                      for c in np.split(chunks, n_eval)]
            return sum(losses) / len(losses)

        loss_bf16 = eval_loss(params)
        loss_int8 = eval_loss(dequantize(qparams))
        stored = quantized_bytes(qparams)
        dense = quantized_bytes(params)

        # Throughput: int8 x speculative, exact vs the QUANTIZED model's
        # own greedy decode (int8 changes the weights, so the oracle is
        # int8 plain generate, not the bf16 series above).
        qvars = {"params": qparams}
        prompt8 = text_prompt(1)
        ref8 = generate(model, qvars, prompt8,
                        max_new_tokens=args.new_tokens,
                        param_transform=dequantize)
        out8, stats8 = generate_speculative(
            model, qvars, prompt8, args.new_tokens,
            draft_len=args.draft_len, ngram=args.ngram,
            return_stats=True, param_transform=dequantize)
        np.testing.assert_array_equal(np.asarray(out8), np.asarray(ref8))
        sync = lambda x: int((x[0] if isinstance(x, tuple) else x)[0, -1])
        t_plain8 = timed_stats(
            lambda: generate(model, qvars, prompt8,
                             max_new_tokens=args.new_tokens,
                             param_transform=dequantize), sync,
            n_repeats=args.repeats)["median_s"]
        t_spec8 = timed_stats(
            lambda: generate_speculative(
                model, qvars, prompt8, args.new_tokens,
                draft_len=args.draft_len, ngram=args.ngram,
                param_transform=dequantize), sync,
            n_repeats=args.repeats)["median_s"]
        record["results"]["int8_val_loss_nats"] = round(loss_int8, 5)
        record["results"]["bf16_val_loss_nats"] = round(loss_bf16, 5)
        record["results"]["int8_val_loss_delta_pct"] = round(
            100.0 * (loss_int8 - loss_bf16) / loss_bf16, 3)
        record["results"]["int8_stored_mb"] = round(stored["bytes"] / 2**20, 1)
        record["results"]["bf16_stored_mb"] = round(dense["bytes"] / 2**20, 1)
        record["results"]["int8_pycorpus_plain_b1"] = round(
            args.new_tokens / t_plain8, 1)
        record["results"]["int8_pycorpus_speculative_b1"] = round(
            args.new_tokens / t_spec8, 1)
        record["results"]["int8_pycorpus_tokens_per_tick"] = round(
            stats8["tokens_per_tick"], 3)
        _log(f"int8: val loss {loss_int8:.5f} vs bf16 {loss_bf16:.5f} "
             f"({record['results']['int8_val_loss_delta_pct']:+.2f}%), "
             f"{stored['bytes'] / 2**20:.0f} MB vs "
             f"{dense['bytes'] / 2**20:.0f} MB; plain "
             f"{args.new_tokens / t_plain8:,.0f} tok/s, speculative "
             f"{args.new_tokens / t_spec8:,.0f} tok/s")

    line = json.dumps(record)
    print(line)
    if args.out:
        out_dir = os.path.dirname(args.out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
