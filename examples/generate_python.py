"""Train a byte-level GPT on real Python source, then sample from it.

End-to-end demonstration of the LM stack on a REAL trained model (the
unit tests exercise ``generate()`` on tiny random models): build the
CPython-stdlib corpus (same recipe as ``real_data_convergence.py``),
train GPT-Small for a few thousand steps on the chip, then generate
continuations of Python-looking prompts with the KV-cache sampler
(temperature + nucleus). Samples are written next to the convergence
artifacts so the repo carries evidence the trained model writes
plausible Python.

Run on the TPU chip::

    python examples/generate_python.py

Smoke mode (``PDDL_EXAMPLE_SMOKE=1``, used by tests/test_examples.py):
tiny model, a handful of steps, samples land in the work dir.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from examples.real_data_convergence import (  # noqa: E402
    ARTIFACTS,
    _build_atomically,
    build_python_corpus,
)

SMOKE = bool(os.environ.get("PDDL_EXAMPLE_SMOKE"))

# Equal byte lengths on purpose: one BATCHED generate() call compiles the
# prefill + the on-device decode scan exactly once (per-call closures
# re-jit, so four separate calls would compile four times).
PROMPTS = (
    b"def get_",
    b"class My",
    b"import o",
    b"    for ",
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--work-dir", default="/tmp/pddl_tpu_real_data")
    p.add_argument("--steps", type=int, default=30 if SMOKE else 3000)
    p.add_argument("--max-new", type=int, default=16 if SMOKE else 256)
    p.add_argument("--speculative", action="store_true",
                   help="sample via speculative (prompt-lookup) "
                        "decoding -- same distribution, fewer ticks")
    p.add_argument("--out", default=None,
                   help="samples file (default: committed artifacts dir; "
                        "the work dir in smoke mode)")
    args = p.parse_args(argv)
    if args.out is None:
        args.out = os.path.join(
            args.work_dir if SMOKE else ARTIFACTS, "pycorpus_samples.txt")

    # The decode-scan program is expensive to compile through remote-
    # compile transports (~minutes); persist it so reruns are instant.
    from pddl_tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pddl_tpu.data.text import load_token_corpus
    from pddl_tpu.models.gpt import GPT, generate
    from pddl_tpu.train.loop import Trainer

    data_dir = os.path.join(args.work_dir, "pycorpus")
    _build_atomically(data_dir, build_python_corpus)

    seq_len = 64 if SMOKE else 256
    batch = 8 if SMOKE else 32
    train_ds, _ = load_token_corpus(
        data_dir, seq_len=seq_len, train_batch_size=batch,
        val_batch_size=batch, seed=0)

    model = GPT(
        vocab_size=256, max_len=max(seq_len, 512 if not SMOKE else seq_len),
        embed_dim=32 if SMOKE else 768, depth=2 if SMOKE else 12,
        num_heads=2 if SMOKE else 12,
        attention="reference" if SMOKE else "flash",
        dtype=jnp.bfloat16 if not SMOKE else jnp.float32,
    )
    trainer = Trainer(
        model, optimizer="adamw", learning_rate=3e-4,
        input_key="tokens", target_key="targets",
        lr_schedule="cosine",
        lr_schedule_options={"decay_steps": args.steps, "warmup_steps":
                             max(2, args.steps // 30)},
        metrics=["accuracy", "perplexity"],
    )
    t0 = time.time()
    epochs = max(1, args.steps // 300)
    spe = args.steps // epochs
    hist = trainer.fit(train_ds, epochs=epochs, steps_per_epoch=spe,
                       verbose=0)
    print(f"trained {epochs * spe} steps in {time.time() - t0:.0f}s, "
          f"final loss {hist.history['loss'][-1]:.3f} nats/byte",
          file=sys.stderr)

    variables = {"params": trainer.state.params}
    prompts = jnp.asarray(np.stack([
        np.frombuffer(p, np.uint8).astype(np.int32) for p in PROMPTS
    ]))
    t0 = time.time()
    if args.speculative:
        from pddl_tpu.models.speculative import generate_speculative

        out, stats = generate_speculative(
            model, variables, prompts, args.max_new,
            temperature=0.8, top_p=0.95, rng=jax.random.key(0),
            return_stats=True)
        print(f"speculative sampling: {stats['tokens_per_tick']:.2f} "
              f"tokens/tick over {stats['ticks']} ticks", file=sys.stderr)
    else:
        out = generate(model, variables, prompts, args.max_new,
                       temperature=0.8, top_p=0.95, rng=jax.random.key(0))
    out = np.asarray(out)
    gen_s = time.time() - t0
    n_tok = len(PROMPTS) * args.max_new
    print(f"generated {n_tok} tokens in {gen_s:.1f}s "
          f"(incl. compile; one dispatch for the whole decode)",
          file=sys.stderr)

    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        f.write(f"# GPT samples after {epochs * spe} steps on the CPython "
                f"corpus (temperature 0.8, top-p 0.95, seed 0; "
                f"{gen_s:.1f}s for {n_tok} tokens incl. compile)\n")
        for row in out:
            text = bytes(row.astype(np.uint8)).decode(
                "utf-8", errors="replace")
            f.write("\n" + "-" * 60 + "\n" + text + "\n")
            print("-" * 60 + "\n" + text, file=sys.stderr)
    print(f"samples -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
