"""Long-context causal LM: train with remat, then sample with a KV cache.

Shows the pieces the reference has no analogue for: a GPT with
activation rematerialization ("dots" — recompute elementwise, keep
matmuls), compiled cosine LR schedule, perplexity tracking, and
top-k/nucleus sampling from the trained model.

`python examples/long_context_gpt.py`
"""

import jax
import jax.numpy as jnp

from pddl_tpu.data.synthetic import SyntheticLanguageModeling
from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.train import Trainer

data = SyntheticLanguageModeling(batch_size=32, seq_len=64, vocab_size=32)
model = tiny_gpt(vocab_size=32, max_len=128, remat="dots")

trainer = Trainer(
    model, optimizer="adamw", learning_rate=3e-3,
    lr_schedule="cosine", lr_schedule_options={"decay_steps": 48},
    metrics=["accuracy", "perplexity"],
    input_key="tokens", target_key="targets",
)
trainer.fit(data, epochs=6, steps_per_epoch=8, verbose=2)

prompt = jnp.asarray(data.batch(0)["tokens"][:2, :8])
out = generate(
    model, {"params": jax.device_get(trainer.state.params)}, prompt,
    max_new_tokens=16, temperature=0.7, top_k=8, top_p=0.95,
    rng=jax.random.key(0),
)
print("sampled continuation:", out[:, 8:].tolist())
