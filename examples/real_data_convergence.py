"""Real-data convergence evidence (VERDICT r1 #2).

The reference's whole purpose is training to an accuracy on a real dataset
(``/root/reference/imagenet-resnet50.py:67``: 50 epochs + early stopping on
ImageNet). Full ImageNet is not available in this environment (zero
egress), so this script trains on the two REAL datasets the machine ships
with, through the framework's real ingest paths, and records reproducible
loss curves:

- ``digits``  — the scikit-learn handwritten-digits set (1,797 genuine
  8x8 grayscale scans, 10 classes), materialized as a
  ``<split>/<class>/*.png`` folder tree and ingested through
  ``data/imagenet.py``'s image-folder path (source #3) exactly like an
  ImageNet folder layout; ResNet-18 classifier.
- ``pycorpus`` — the CPython 3.12 standard library source (~20 MB of real
  Python text), byte-tokenized through ``data/text.py`` and modeled with
  GPT-Small next-byte prediction.

Each track writes ``artifacts/convergence/<track>.jsonl`` — one JSON line
per epoch (the History), preceded by a header line recording the full
config + seed — which is committed to the repo along with the quoted
numbers in ``docs/CONVERGENCE.md``.

Run on the TPU chip (no env overrides needed)::

    python examples/real_data_convergence.py --track all
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import sysconfig
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ARTIFACTS = os.path.join(REPO, "artifacts", "convergence")

# Smoke mode (PDDL_EXAMPLE_SMOKE=1, used by tests/test_examples.py on the
# fake CPU mesh): tiny models and a handful of steps, with artifacts
# redirected into the work dir so the committed chip-run curves are never
# overwritten by a smoke pass.
SMOKE = bool(os.environ.get("PDDL_EXAMPLE_SMOKE"))


# --------------------------------------------------------------- datasets
def build_digits_folder(root: str, image_size: int = 32,
                        val_fraction: float = 1 / 6, seed: int = 0) -> dict:
    """Materialize sklearn digits as ``<split>/<class>/*.png`` (real scans).

    Images are nearest-neighbor upscaled 8->``image_size`` at write time so
    the on-disk tree looks like any small-image classification folder. The
    split is stratified per class with a seeded shuffle.
    """
    import numpy as np
    from sklearn.datasets import load_digits

    import tensorflow as tf  # CPU build; PNG encoding only

    digits = load_digits()
    images, labels = digits.images, digits.target  # [N, 8, 8] float 0..16
    rng = np.random.default_rng(seed)
    factor = image_size // 8
    counts = {"train": 0, "validation": 0}
    for cls in range(10):
        idx = np.flatnonzero(labels == cls)
        rng.shuffle(idx)
        n_val = max(1, int(len(idx) * val_fraction))
        for split, members in (("validation", idx[:n_val]),
                               ("train", idx[n_val:])):
            d = os.path.join(root, split, f"{cls:02d}")
            os.makedirs(d, exist_ok=True)
            for i in members:
                img = (images[i] * (255.0 / 16.0)).astype(np.uint8)
                img = np.kron(img, np.ones((factor, factor), np.uint8))
                rgb = np.repeat(img[..., None], 3, axis=-1)
                png = tf.io.encode_png(rgb).numpy()
                with open(os.path.join(d, f"{i:04d}.png"), "wb") as f:
                    f.write(png)
                counts[split] += 1
    return counts


def build_python_corpus(root: str, max_bytes: int = 20 << 20,
                        val_fraction: float = 0.05,
                        source_dir: str | None = None) -> dict:
    """Concatenate CPython stdlib sources into train.txt/val.txt.

    A real, public text corpus that ships with every machine. Files are
    walked in sorted order (deterministic), capped at ``max_bytes``; the
    tail ``val_fraction`` becomes the held-out split. ``source_dir``
    defaults to the RUNNING interpreter's stdlib (a hardcoded version
    path silently yields an empty corpus on any other interpreter).
    """
    if source_dir is None:
        source_dir = sysconfig.get_paths()["stdlib"]
    chunks, total = [], 0
    for dirpath, dirnames, filenames in sorted(os.walk(source_dir)):
        dirnames.sort()
        if "site-packages" in dirpath or "__pycache__" in dirpath:
            continue
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fname), "rb") as f:
                    data = f.read()
            except OSError:
                continue
            chunks.append(data)
            total += len(data)
            if total >= max_bytes:
                break
        if total >= max_bytes:
            break
    blob = b"\n".join(chunks)[:max_bytes]
    split = int(len(blob) * (1 - val_fraction))
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "train.txt"), "wb") as f:
        f.write(blob[:split])
    with open(os.path.join(root, "val.txt"), "wb") as f:
        f.write(blob[split:])
    return {"train_bytes": split, "val_bytes": len(blob) - split}


def _build_atomically(final_dir: str, builder) -> None:
    """Run ``builder(tmp_dir)`` then rename into place.

    A killed run must not leave a partial tree that later runs silently
    reuse as the dataset (the existence check gates on ``final_dir`` only,
    which appears atomically). Concurrent builders each use their own tmp
    dir; the rename loser just discards its copy.
    """
    if os.path.isdir(final_dir):
        return
    tmp = f"{final_dir}.building.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    builder(tmp)
    try:
        os.rename(tmp, final_dir)
    except OSError:
        if not os.path.isdir(final_dir):  # not just a lost race
            raise
        shutil.rmtree(tmp, ignore_errors=True)


# ----------------------------------------------------------------- tracks
def _write_history(path: str, header: dict, history) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps({"config": header}) + "\n")
        keys = sorted(history.history)
        for i, epoch in enumerate(history.epoch):
            row = {"epoch": int(epoch)}
            for k in keys:
                if i < len(history.history[k]):
                    row[k] = float(history.history[k][i])
            f.write(json.dumps(row) + "\n")


def run_digits(work_dir: str, out_path: str) -> dict:
    from pddl_tpu.config import get_preset
    from pddl_tpu.run import run_experiment

    data_dir = os.path.join(work_dir, "digits_png")
    _build_atomically(data_dir, build_digits_folder)
    counts = {
        split: sum(
            len(files)
            for _, _, files in os.walk(os.path.join(data_dir, split))
        )
        for split in ("train", "validation")
    }
    cfg = get_preset(
        "single",
        model="resnet18", num_classes=10, image_size=32,
        data_dir=data_dir, per_replica_batch=128,
        # Digits are orientation-sensitive: no horizontal flip.
        flip=False, epochs=30, seed=0, verbose=0,
    )
    if SMOKE:
        cfg = cfg.replace(model="tiny_resnet", epochs=2,
                          per_replica_batch=64)
    start = time.time()
    history = run_experiment(cfg, validation_steps=2)
    elapsed = time.time() - start
    header = {
        "track": "digits", "dataset": "sklearn load_digits (real scans)",
        "counts": counts, "model": cfg.model, "seed": cfg.seed,
        "batch": cfg.per_replica_batch, "epochs": cfg.epochs,
        "optimizer": cfg.optimizer, "learning_rate": cfg.learning_rate,
        "callbacks": "ReduceLROnPlateau + EarlyStopping (reference defaults)",
        "wall_seconds": round(elapsed, 1),
    }
    _write_history(out_path, header, history)
    return {
        "final_val_accuracy": float(history.history["val_accuracy"][-1]),
        "best_val_accuracy": float(max(history.history["val_accuracy"])),
        "final_val_loss": float(history.history["val_loss"][-1]),
        "epochs_ran": len(history.epoch),
        "wall_seconds": round(elapsed, 1),
    }


def run_pycorpus(work_dir: str, out_path: str, *,
                 model_name: str = "gpt_small",
                 track_name: str = "pycorpus",
                 param_dtype: str = "float32",
                 param_update: str = "plain") -> dict:
    from pddl_tpu.config import get_preset
    from pddl_tpu.run import run_experiment

    data_dir = os.path.join(work_dir, "pycorpus")
    _build_atomically(data_dir, build_python_corpus)
    sizes = {
        "train_bytes": os.path.getsize(os.path.join(data_dir, "train.txt")),
        "val_bytes": os.path.getsize(os.path.join(data_dir, "val.txt")),
    }
    cfg = get_preset(
        "single",
        model=model_name, num_classes=256, seq_len=256,
        data_dir=data_dir, per_replica_batch=32,
        learning_rate=3e-4, lr_schedule="cosine",
        lr_schedule_options={"decay_steps": 3000, "warmup_steps": 100},
        epochs=10, steps_per_epoch=300, seed=0, verbose=0,
        param_dtype=param_dtype, param_update=param_update,
    )
    if SMOKE:
        tiny = "tiny_llama" if "llama" in model_name else "tiny_gpt"
        cfg = cfg.replace(
            model=tiny, seq_len=128, per_replica_batch=8, epochs=2,
            steps_per_epoch=10,
            lr_schedule_options={"decay_steps": 20, "warmup_steps": 2},
        )
    start = time.time()
    history = run_experiment(cfg, validation_steps=20 if not SMOKE else 2)
    elapsed = time.time() - start
    header = {
        "track": track_name,
        "dataset": "CPython 3.12 stdlib source, byte-level (real text)",
        "sizes": sizes, "model": cfg.model, "seed": cfg.seed,
        "seq_len": cfg.seq_len, "batch": cfg.per_replica_batch,
        "steps": cfg.epochs * cfg.steps_per_epoch,
        "optimizer": cfg.optimizer, "learning_rate": cfg.learning_rate,
        "lr_schedule": cfg.lr_schedule, **cfg.lr_schedule_options,
        "param_dtype": cfg.param_dtype, "param_update": cfg.param_update,
        "wall_seconds": round(elapsed, 1),
    }
    _write_history(out_path, header, history)
    import math

    return {
        "final_val_loss_nats": float(history.history["val_loss"][-1]),
        "final_val_bits_per_byte": float(
            history.history["val_loss"][-1] / math.log(2)),
        "final_val_perplexity": float(
            history.history.get("val_perplexity", [float("nan")])[-1]),
        "epochs_ran": len(history.epoch),
        "wall_seconds": round(elapsed, 1),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--track",
                   choices=("digits", "pycorpus", "pycorpus-llama",
                            "bf16-recipe", "bf16-recipe-safe", "all"),
                   default="all")
    p.add_argument("--work-dir", default="/tmp/pddl_tpu_real_data",
                   help="where datasets are materialized (not committed)")
    p.add_argument("--artifacts-dir", default=None,
                   help="where the history JSONLs are written (the repo's "
                        "committed artifacts/convergence by default; the "
                        "work dir in smoke mode)")
    args = p.parse_args(argv)
    if args.artifacts_dir is None:
        args.artifacts_dir = (
            os.path.join(args.work_dir, "artifacts") if SMOKE else ARTIFACTS
        )

    results = {}
    if args.track in ("digits", "all"):
        results["digits"] = run_digits(
            args.work_dir, os.path.join(args.artifacts_dir, "digits.jsonl"))
    if args.track in ("pycorpus", "all"):
        results["pycorpus"] = run_pycorpus(
            args.work_dir, os.path.join(args.artifacts_dir, "pycorpus.jsonl"))
    if args.track in ("pycorpus-llama", "all"):
        # Same corpus, same token budget/schedule, modern-decoder family:
        # an apples-to-apples architecture comparison against pycorpus.
        results["pycorpus-llama"] = run_pycorpus(
            args.work_dir,
            os.path.join(args.artifacts_dir, "pycorpus_llama.jsonl"),
            model_name="llama_small", track_name="pycorpus-llama")
    if args.track == "bf16-recipe":
        # The 1B-on-one-chip recipe stores params AND Adam moments in
        # bf16 (halving weight+optimizer HBM). bf16 moments are a known
        # convergence hazard — prove the recipe TRAINS, not just steps
        # (VERDICT r3 task 6): identical mid-size llama runs, f32 vs
        # bf16 storage, same data/seed/schedule, curves committed.
        model = "llama_300m" if not SMOKE else "tiny_llama"
        for dtype in ("float32", "bfloat16"):
            tag = "f32" if dtype == "float32" else "bf16"
            results[f"bf16_recipe_{tag}"] = run_pycorpus(
                args.work_dir,
                os.path.join(args.artifacts_dir,
                             f"pycorpus_300m_{tag}.jsonl"),
                model_name=model, track_name=f"bf16-recipe-{tag}",
                param_dtype=dtype)
        delta = (results["bf16_recipe_bf16"]["final_val_loss_nats"]
                 - results["bf16_recipe_f32"]["final_val_loss_nats"])
        results["bf16_minus_f32_final_val_nats"] = round(delta, 5)
    if args.track == "bf16-recipe-safe":
        # The round-5 fix for the +2.4%: same 304M shape, same budget,
        # bf16 storage under the two safe update rules
        # (train/mixed_precision.py). Compared against the committed
        # round-4 f32/bf16-plain curves (same corpus/seed/schedule).
        model = "llama_300m" if not SMOKE else "tiny_llama"
        for mode in ("stochastic_round", "f32_master"):
            tag = "sr" if mode == "stochastic_round" else "master"
            results[f"bf16_safe_{tag}"] = run_pycorpus(
                args.work_dir,
                os.path.join(args.artifacts_dir,
                             f"pycorpus_300m_bf16_{tag}.jsonl"),
                model_name=model, track_name=f"bf16-recipe-{tag}",
                param_dtype="bfloat16", param_update=mode)
    print(json.dumps(results, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
