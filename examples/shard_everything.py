"""One mesh, every axis: DP x TP training, then sharded decoding.

Runs on 8 fake CPU devices (no TPU needed):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/shard_everything.py

On a real slice, drop the env vars — the same code spans the pod.
"""

import jax
import jax.numpy as jnp

from pddl_tpu.data.synthetic import SyntheticLanguageModeling
from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.parallel import TensorParallelStrategy
from pddl_tpu.train import Trainer

# data axis = devices/2, model axis = 2: Megatron TP inside DP.
strategy = TensorParallelStrategy(model_parallel=2)
data = SyntheticLanguageModeling(batch_size=32, seq_len=32, vocab_size=16)
model = tiny_gpt(vocab_size=16, max_len=64)

trainer = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                  strategy=strategy, input_key="tokens",
                  target_key="targets")
trainer.fit(data, epochs=4, steps_per_epoch=8, verbose=2)

# Decode SHARDED with the same strategy: weights stay in the Megatron
# layout, the KV cache splits by head over `model`.
prompt = jnp.asarray(data.batch(0)["tokens"][:2, :8])
out = generate(model, {"params": jax.device_get(trainer.state.params)},
               prompt, max_new_tokens=8, strategy=strategy)
print("sharded generation:", out[:, 8:].tolist())
