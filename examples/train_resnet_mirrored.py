"""Data-parallel ResNet training in ~20 lines (the mirrored-strategy story).

The reference needs an 86-line script for this
(`imagenet-resnet50-mirror.py`); here the strategy is one object and the
batch arithmetic (32 x replicas, its line 54) is `scale_batch_size`.

Run on anything: `python examples/train_resnet_mirrored.py` (real data:
swap SyntheticImageClassification for `pddl_tpu.data.load_imagenet`).
"""

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import ResNet50, tiny_resnet
from pddl_tpu.ops.augment import standard_augment
from pddl_tpu.parallel import MirroredStrategy
from pddl_tpu.train import Trainer
from pddl_tpu.train.callbacks import EarlyStopping, ReduceLROnPlateau

# Smoke config unless this file is RUN with --full: imports are always
# smoke-only (never a 50-epoch training), regardless of the host argv.
SMOKE = not (__name__ == "__main__" and "--full" in __import__("sys").argv)

strategy = MirroredStrategy()
model = tiny_resnet(num_classes=10) if SMOKE else ResNet50(num_classes=1000)
data = SyntheticImageClassification(
    batch_size=strategy.scale_batch_size(32),
    image_size=32 if SMOKE else 224,
    num_classes=10 if SMOKE else 1000,
)

trainer = Trainer(
    model, optimizer="adam", strategy=strategy,
    augment=standard_augment(crop=32 if SMOKE else 224),
)
history = trainer.fit(
    data, epochs=2 if SMOKE else 50, steps_per_epoch=4,
    validation_data=data, validation_steps=2,
    callbacks=[ReduceLROnPlateau(), EarlyStopping()], verbose=2,
)
print("final:", {k: round(v[-1], 4) for k, v in history.history.items()})
