"""Full-resolution reference-workflow rehearsal on the real chip.

The reference IS a workflow, not a library: train ResNet-50 at 224px
with val-loss-driven callbacks, checkpoint, survive interruption, and
``model.save`` at the end (``/root/reference/imagenet-resnet50.py:64-72``).
This driver executes that complete story through the REAL CLI (one
``python -m pddl_tpu`` process per leg, exactly what a user types) on
hardware, and asserts every seam:

1. ``single`` preset, synthetic 224×224 data, ResNet-50, enough epochs
   that the reference's own callbacks FIRE (plateau patience 5 →
   ReduceLROnPlateau; early-stop patience 10 on a plateauing val loss).
2. Mid-epoch SIGTERM (a Cloud-TPU preemption) → the PreemptionCheckpoint
   handler writes a consistent checkpoint and exits cleanly.
3. Relaunch with ``--resume`` → continues from the interrupted epoch,
   runs to the early stop, exports the final ``.h5``.
4. The ``.h5`` re-imports through the Keras-layout mapper and its logits
   match the orbax checkpoint state bit-for-bit — the train-here/
   serve-anywhere contract.

Proof obligations checked from artifacts alone (no trust in this
script's narration): the epoch count in the resumed log is < requested
(early stop fired), the checkpoint's learning rate ends < the initial
1e-3 (plateau fired ≥ once), and the logits comparison.

    python examples/workflow_rehearsal.py \
        [--artifacts-dir artifacts/workflow_rehearsal]

Writes ``rehearsal_log.txt`` (all three legs' stdout) and
``r05_workflow_rehearsal.json`` (the assertions' measured values).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Smoke mode (tests/test_examples.py, PDDL_EXAMPLE_SMOKE=1): the same
# four-leg workflow at toy scale on the CPU mesh — tiny model, .npz
# final artifact (the .h5 exporter is ResNet-50-layout) — so the
# script's own seams stay covered in the suite while the committed
# artifact comes from the full-resolution chip run.
SMOKE = bool(os.environ.get("PDDL_EXAMPLE_SMOKE"))

# The epoch budget leaves ~15 epochs of slack past the measured
# early-stop point (CPU calibration: floor ~epoch 16, stop ~40; the
# 224px task separates faster) — the stop must come from the callback,
# not the budget.
EPOCHS = 56 if SMOKE else 40
# 50 full-res steps/epoch, not fewer: BatchNorm moving stats (even at
# the rehearsal's momentum 0.9 — see _cli) need a few hundred updates
# before inference-mode val metrics mean anything — the reference's
# 40k-step ImageNet epochs converge them inside epoch 1, and a
# too-short synthetic epoch makes the val-loss callbacks stare at
# stat-settling noise instead of learning (measured: at 20 steps/epoch
# and Keras momentum 0.99, val loss starts at ~850 and takes ~25
# epochs just to settle).
STEPS = 16 if SMOKE else 50
BATCH = 8 if SMOKE else 32
IMAGE = 32 if SMOKE else 224
MODEL = "tiny_resnet" if SMOKE else "resnet50"
SIGTERM_AFTER = 120 if SMOKE else 600  # CAP on the epoch-marker wait
# Few classes: the synthetic class-mean task converges in a few epochs
# and then PLATEAUS — which is exactly what makes the reference's
# val-loss callbacks (plateau patience 5, early-stop patience 10) fire
# inside the budget.
NUM_CLASSES = 8 if SMOKE else 16


def _cli(workdir, *extra):
    final = "final.npz" if SMOKE else "final.h5"
    return [
        sys.executable, "-m", "pddl_tpu",
        "--preset", "single", "--synthetic", "--model", MODEL,
        "--image-size", str(IMAGE), "--batch", str(BATCH),
        "--num-classes", str(NUM_CLASSES),
        # Strong class separation: at the default (weak) signal the
        # replayed finite epoch lets ResNet-50 memorize instead of
        # generalize, val sits at chance, and the val-loss callbacks
        # fire on BN-settling noise — a coin flip. At 10 the task is
        # honestly learnable (like real ImageNet): val tracks train,
        # reaches its floor, and plateau/early-stop fire because
        # learning finished, not because noise paused.
        "--synthetic-signal", "10.0",
        # BN momentum 0.9 (not the Keras-parity 0.99): inference-mode
        # val metrics read the moving averages, which at 0.99 stay
        # half-initialized for hundreds of steps — longer than these
        # synthetic epochs. The reference never sees this (40k-step
        # ImageNet epochs converge them inside epoch 1); 0.9 gives this
        # short rehearsal the same converged-stats regime.
        "--bn-momentum", "0.9",
        # Smoke only: 3x the reference LR so the tiny model reaches its
        # val floor inside the budget; full-res keeps the reference's
        # exact Adam default (1e-3).
        *(["--lr", "3e-3"] if SMOKE else []),
        "--epochs", str(EPOCHS), "--steps-per-epoch", str(STEPS),
        "--checkpoint-dir", os.path.join(workdir, "ckpt"),
        "--save", os.path.join(workdir, final),
        "--seed", "0", "--verbose", "2", *extra,
    ]


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--artifacts-dir",
                   default=os.path.join(REPO, "artifacts",
                                        "workflow_rehearsal"))
    p.add_argument("--work-dir",
                   default="/tmp/pddl_workflow_rehearsal_smoke" if SMOKE
                   else "/tmp/pddl_workflow_rehearsal")
    args = p.parse_args()
    os.makedirs(args.artifacts_dir, exist_ok=True)
    os.makedirs(args.work_dir, exist_ok=True)
    log_path = os.path.join(args.artifacts_dir, "rehearsal_log.txt")
    log = open(log_path, "w")

    def leg(title, cmd, sigterm_after=None):
        log.write(f"\n===== {title}: {' '.join(cmd)} =====\n")
        log.flush()
        t0 = time.time()
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=log,
                                stderr=subprocess.STDOUT, text=True)
        interrupted = None
        if sigterm_after is not None:
            # Signal only once training is demonstrably underway (the
            # epoch marker appears in the log), not after a fixed sleep:
            # a warm compile cache can finish a whole smoke leg in under
            # any fixed delay, and then the preemption path was never
            # exercised. sigterm_after caps the wait. Smoke mode waits
            # for epoch 1 — post-compile smoke epochs run in milliseconds,
            # so waiting for epoch 2 races the natural end of the run,
            # while epoch 1 always spans the (slow) first-step trace; the
            # full-resolution run keeps epoch 2 (mid-TRAINING, not
            # mid-compile, and its epochs take seconds each).
            marker = "Epoch 1/" if SMOKE else "Epoch 2/"
            deadline = time.time() + sigterm_after
            while time.time() < deadline and proc.poll() is None:
                log.flush()
                if marker in open(log_path).read():
                    break
                time.sleep(0.2)
            # The signal only exercises the preemption path if the run
            # is still alive — record it so the caller can ASSERT the
            # preemption actually happened.
            interrupted = proc.poll() is None
            proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=3600)
        dt = time.time() - t0
        log.write(f"===== {title}: rc={rc} wall={dt:.1f}s "
                  f"interrupted={interrupted} =====\n")
        log.flush()
        return rc, dt, interrupted

    # Leg 1: fresh run, preempted mid-training by a real SIGTERM.
    # Enough delay to be INSIDE training (past compile) but well before
    # the natural end.
    rc1, t1, interrupted = leg("leg1-preempted", _cli(args.work_dir),
                               sigterm_after=SIGTERM_AFTER)
    assert interrupted, (
        f"leg1 finished before the {SIGTERM_AFTER}s SIGTERM — the "
        "preemption path was never exercised; lower SIGTERM_AFTER or "
        "raise the epoch budget")
    ckpt_dir = os.path.join(args.work_dir, "ckpt")
    steps_saved = sorted(
        int(d) for d in os.listdir(ckpt_dir) if d.isdigit())
    assert steps_saved, f"no checkpoint written by preemption (rc={rc1})"

    # Leg 2: resume from the preemption checkpoint; run to completion
    # (the early stop should end it before EPOCHS).
    rc2, t2, _ = leg("leg2-resume", _cli(args.work_dir, "--resume"))
    assert rc2 == 0, f"resume leg failed rc={rc2} (see {log_path})"
    h5_path = os.path.join(args.work_dir,
                           "final.npz" if SMOKE else "final.h5")
    assert os.path.exists(h5_path), "final model artifact was not exported"

    # ---- proof obligations, measured from the artifacts --------------
    # Scan ONLY the leg-2 section: the whole-log scan would fold leg1's
    # pre-preemption epochs into "epochs_seen" (mislabeling where the
    # resume restarted) and make the early-stop check vacuous if leg2
    # printed no epoch lines at all.
    text = open(log_path).read()
    leg2_text = text.split("===== leg2-resume", 1)[-1]
    epochs_leg2 = sorted(set(
        int(m) for m in re.findall(r"Epoch (\d+)/%d" % EPOCHS, leg2_text)))
    early_stopped = bool(epochs_leg2) and max(epochs_leg2) < EPOCHS

    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, REPO)
    from pddl_tpu.ckpt.checkpoint import Checkpointer
    from pddl_tpu.train.state import get_learning_rate

    if SMOKE:
        from pddl_tpu.models.resnet import tiny_resnet

        model = tiny_resnet(num_classes=NUM_CLASSES)
    else:
        from pddl_tpu.models.resnet import ResNet50

        model = ResNet50(num_classes=NUM_CLASSES)
    x = jax.random.normal(jax.random.key(0), (2, IMAGE, IMAGE, 3))
    variables = jax.jit(
        lambda: model.init(jax.random.key(0), x, train=False))()

    # LR in the final checkpoint proves ReduceLROnPlateau fired (0.1x
    # per firing from the preset's 1e-3).
    from pddl_tpu.train.state import TrainState, make_optimizer

    tx = make_optimizer("adam", 1e-3)
    target = TrainState(
        step=jnp.zeros((), jnp.int32), params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(variables["params"]))
    state = Checkpointer(ckpt_dir, read_only=True).restore(target)
    final_lr = get_learning_rate(state)
    plateau_fired = final_lr < 1e-3 * 0.99

    if SMOKE:
        # .npz round trip: exported params equal the checkpoint's.
        with np.load(h5_path) as z:
            flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
            deltas = []
            for path, leaf in flat:
                key = "/".join(str(getattr(p, "key", p)) for p in path)
                assert key in z.files, (key, z.files[:5])
                deltas.append(float(np.max(np.abs(
                    z[key] - np.asarray(leaf)))))
            logits_delta = max(deltas)
    else:
        # .h5 round trip: logits from the re-imported Keras-layout file
        # must match logits from the orbax state exactly (same arrays,
        # two serialization paths).
        from pddl_tpu.ckpt.keras_import import load_keras_resnet50_h5

        h5_vars = load_keras_resnet50_h5(h5_path, variables,
                                         require_head=True)
        fwd = jax.jit(lambda v: model.apply(
            {"params": v["params"], "batch_stats": v["batch_stats"]},
            x, train=False))
        logits_h5 = np.asarray(fwd(h5_vars))
        logits_ckpt = np.asarray(fwd(
            {"params": state.params, "batch_stats": state.batch_stats}))
        logits_delta = float(np.max(np.abs(logits_h5 - logits_ckpt)))

    record = {
        "metric": "workflow_rehearsal",
        "config": {"preset": "single", "model": MODEL,
                   "image_size": IMAGE, "batch": BATCH, "epochs": EPOCHS,
                   "steps_per_epoch": STEPS, "smoke": SMOKE},
        "leg1_preempted": {"rc": rc1, "wall_s": round(t1, 1),
                           "checkpoint_steps": steps_saved},
        "leg2_resume": {"rc": rc2, "wall_s": round(t2, 1),
                        "epochs_seen": epochs_leg2},
        "early_stopping_fired": early_stopped,
        "reduce_lr_fired": plateau_fired,
        "final_lr": final_lr,
        "h5_vs_checkpoint_max_logit_delta": logits_delta,
        "device": jax.devices()[0].device_kind,
    }
    out = os.path.join(args.artifacts_dir, "r05_workflow_rehearsal.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record, indent=1))
    # BOTH val-loss callbacks firing is a FULL-RESOLUTION obligation
    # only. The smoke leg's job is the mechanism — CLI, preemption,
    # resume, export, and that the callbacks demonstrably DROVE the run
    # — so smoke requires at least one of them. Which one fires on a
    # slowly-asymptoting toy loss is timing-sensitive: the interrupt
    # point shifts the resumed trajectory, and the two reference
    # callbacks use different min_deltas (plateau 1e-4, early-stop
    # 1e-3), so a loss improving 1e-4..1e-3 per epoch can stop with no
    # LR drop, or drop twice with no stop (all three observed across
    # identical configs under different host load). A criterion with
    # those tails has no place in a test; the committed chip artifact
    # is the evidence that both reference dynamics really run.
    ok = (logits_delta == 0.0 and rc2 == 0 and interrupted
          and bool(epochs_leg2)
          and ((early_stopped or plateau_fired) if SMOKE
               else (early_stopped and plateau_fired)))
    print("REHEARSAL", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
