// pddl_tpu native data-loader runtime.
//
// TPU-native counterpart of the reference's C++ input substrate: every
// map/batch/prefetch/shard call in the reference runs inside TensorFlow's
// C++ tf.data runtime (SURVEY.md §2b C15 — /root/reference/
// imagenet-resnet50.py:44-49 et al.). This library provides that layer for
// the packed-sample format written by pddl_tpu.data.native_loader:
//
//   * worker thread pool reading + assembling fixed-shape batches
//   * bounded ring buffer (prefetch queue) between IO threads and the
//     training loop — the .prefetch(AUTOTUNE) analogue
//   * deterministic per-epoch shuffling (seeded xorshift + Fisher-Yates),
//     per-process sharding for multi-host input (auto-shard DATA analogue,
//     imagenet-resnet50-multiworkers.py:66-69)
//   * zero-copy handoff: batches land directly in caller-owned numpy
//     buffers (pinned once, reused)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 dependency).
//
// Packed file format "PDL1" (little-endian):
//   magic u32 'PDL1' | u32 n_samples | u16 height | u16 width | u16 chans
//   | u16 reserved | then per sample: i32 label + h*w*c bytes (uint8 HWC).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "pddl_rng.h"

namespace {

constexpr uint32_t kMagic = 0x314C4450;  // "PDL1"

struct SampleRef {
  uint32_t file;    // index into files_
  uint64_t offset;  // byte offset of the sample record
};

struct Batch {
  std::vector<uint8_t> images;
  std::vector<int32_t> labels;
  long epoch;
};


class Loader {
 public:
  Loader(std::vector<std::string> paths, int batch, int shuffle,
         uint64_t seed, int shard_index, int shard_count, int prefetch_depth,
         int n_workers, int drop_remainder, int loop)
      : paths_(std::move(paths)),
        batch_(batch),
        shuffle_(shuffle),
        seed_(seed),
        shard_index_(shard_index),
        shard_count_(shard_count),
        depth_(std::max(1, prefetch_depth)),
        drop_remainder_(drop_remainder),
        loop_(loop) {
    if (!index()) {
      ok_ = false;
      return;
    }
    int workers = std::max(1, n_workers);
    for (int i = 0; i < workers; ++i) {
      threads_.emplace_back([this, i] { worker(i); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_slots_.notify_all();
    cv_items_.notify_all();
    for (auto& t : threads_) t.join();
  }

  bool ok() const { return ok_; }
  int height() const { return h_; }
  int width() const { return w_; }
  int channels() const { return c_; }
  long num_samples() const { return (long)samples_.size(); }
  long batches_per_epoch() const {
    long n = (long)samples_.size();
    return drop_remainder_ ? n / batch_ : (n + batch_ - 1) / batch_;
  }

  // Blocking pop into caller buffers. Returns the number of samples in the
  // batch (0 = end of epoch for non-looping loaders).
  int next(uint8_t* images_out, int32_t* labels_out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_items_.wait(lk, [this] {
      return stop_ || !queue_.empty() || (done_epoch_ && in_flight_ == 0);
    });
    if (stop_) return -1;
    if (queue_.empty()) return 0;  // epoch exhausted; reset() starts the next
    Batch b = std::move(queue_.front());
    queue_.pop();
    lk.unlock();
    cv_slots_.notify_one();
    int n = (int)b.labels.size();
    std::memcpy(images_out, b.images.data(), b.images.size());
    std::memcpy(labels_out, b.labels.data(), n * sizeof(int32_t));
    return n;
  }

  void reset() {
    std::unique_lock<std::mutex> lk(mu_);
    ++epoch_;
    done_epoch_ = false;
    cursor_ = 0;
    // Discard batches the workers prefetched past the epoch boundary (only
    // possible for non-drop_remainder tails).
    while (!queue_.empty()) queue_.pop();
    reshuffle();
    lk.unlock();
    cv_slots_.notify_all();
  }

 private:
  bool index() {
    for (uint32_t fi = 0; fi < paths_.size(); ++fi) {
      FILE* f = std::fopen(paths_[fi].c_str(), "rb");
      if (!f) return false;
      uint32_t magic = 0, count = 0;
      uint16_t h = 0, w = 0, c = 0, reserved = 0;
      if (std::fread(&magic, 4, 1, f) != 1 || magic != kMagic ||
          std::fread(&count, 4, 1, f) != 1 || std::fread(&h, 2, 1, f) != 1 ||
          std::fread(&w, 2, 1, f) != 1 || std::fread(&c, 2, 1, f) != 1 ||
          std::fread(&reserved, 2, 1, f) != 1) {
        std::fclose(f);
        return false;
      }
      if (h_ == 0) {
        h_ = h;
        w_ = w;
        c_ = c;
      } else if (h != h_ || w != w_ || c != c_) {
        std::fclose(f);
        return false;  // heterogeneous shapes across files
      }
      uint64_t sample_bytes = 4ull + (uint64_t)h_ * w_ * c_;
      uint64_t off = 16;
      for (uint32_t i = 0; i < count; ++i) {
        // Per-process sharding: every shard_count-th sample (DATA policy).
        if ((all_count_ % shard_count_) == (uint64_t)shard_index_) {
          samples_.push_back({fi, off});
        }
        ++all_count_;
        off += sample_bytes;
      }
      std::fclose(f);
    }
    order_.resize(samples_.size());
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    reshuffle();
    return !samples_.empty();
  }

  void reshuffle() {  // call with mu_ held (or before threads start)
    if (shuffle_) pddl::epoch_shuffle(order_, seed_, epoch_);
  }

  void worker(int) {
    // One pread-style FILE* per worker per file (no shared seek state).
    std::vector<FILE*> files;
    for (auto& p : paths_) files.push_back(std::fopen(p.c_str(), "rb"));
    uint64_t image_bytes = (uint64_t)h_ * w_ * c_;

    while (true) {
      // Claim the next batch's sample indices under the lock (order_ may be
      // reshuffled by another worker at an epoch boundary — copy, don't
      // alias).
      std::vector<size_t> idxs;
      long epoch;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_slots_.wait(lk, [this] {
          return stop_ ||
                 (!done_epoch_ && queue_.size() + in_flight_ < (size_t)depth_);
        });
        if (stop_) break;
        size_t begin = cursor_;
        size_t end = std::min(begin + (size_t)batch_, samples_.size());
        if (begin >= samples_.size() ||
            (drop_remainder_ && end - begin < (size_t)batch_)) {
          if (loop_) {
            ++epoch_;
            cursor_ = 0;
            reshuffle();
            continue;
          }
          done_epoch_ = true;
          lk.unlock();
          cv_items_.notify_all();
          continue;
        }
        cursor_ = end;
        idxs.assign(order_.begin() + begin, order_.begin() + end);
        ++in_flight_;
        epoch = epoch_;
      }

      Batch b;
      b.epoch = epoch;
      b.labels.resize(idxs.size());
      b.images.resize(idxs.size() * image_bytes);
      bool read_ok = true;
      for (size_t i = 0; i < idxs.size(); ++i) {
        const SampleRef& s = samples_[idxs[i]];
        FILE* f = files[s.file];
        if (!f || std::fseek(f, (long)s.offset, SEEK_SET) != 0) {
          read_ok = false;
          break;
        }
        int32_t label;
        if (std::fread(&label, 4, 1, f) != 1) {
          read_ok = false;
          break;
        }
        b.labels[i] = label;
        if (std::fread(b.images.data() + i * image_bytes, 1, image_bytes,
                       f) != image_bytes) {
          read_ok = false;
          break;
        }
      }

      {
        std::lock_guard<std::mutex> lk(mu_);
        --in_flight_;
        // Drop batches assembled for an epoch that reset() superseded —
        // their shuffle order is stale and their samples will be re-read.
        if (read_ok && b.epoch == epoch_) queue_.push(std::move(b));
      }
      cv_items_.notify_one();
      cv_slots_.notify_one();
    }
    for (FILE* f : files)
      if (f) std::fclose(f);
  }

  std::vector<std::string> paths_;
  int batch_, shuffle_;
  uint64_t seed_;
  int shard_index_, shard_count_, depth_, drop_remainder_, loop_;
  int h_ = 0, w_ = 0, c_ = 0;
  uint64_t all_count_ = 0;
  std::vector<SampleRef> samples_;
  std::vector<size_t> order_;
  std::vector<std::thread> threads_;

  std::mutex mu_;
  std::condition_variable cv_items_, cv_slots_;
  std::queue<Batch> queue_;
  size_t cursor_ = 0, in_flight_ = 0;
  long epoch_ = 0;
  bool done_epoch_ = false, stop_ = false, ok_ = true;
};

}  // namespace

extern "C" {

void* pddl_loader_open(const char** paths, int n_paths, int batch,
                       int shuffle, uint64_t seed, int shard_index,
                       int shard_count, int prefetch_depth, int n_workers,
                       int drop_remainder, int loop) {
  std::vector<std::string> ps;
  for (int i = 0; i < n_paths; ++i) ps.emplace_back(paths[i]);
  if (shard_count < 1 || shard_index < 0 || shard_index >= shard_count ||
      batch < 1)
    return nullptr;
  auto* l = new Loader(std::move(ps), batch, shuffle, seed, shard_index,
                       shard_count, prefetch_depth, n_workers, drop_remainder,
                       loop);
  if (!l->ok()) {
    delete l;
    return nullptr;
  }
  return l;
}

int pddl_loader_shape(void* handle, int* h, int* w, int* c) {
  auto* l = static_cast<Loader*>(handle);
  *h = l->height();
  *w = l->width();
  *c = l->channels();
  return 0;
}

long pddl_loader_num_samples(void* handle) {
  return static_cast<Loader*>(handle)->num_samples();
}

long pddl_loader_batches_per_epoch(void* handle) {
  return static_cast<Loader*>(handle)->batches_per_epoch();
}

// Returns samples filled (0 = end of epoch, -1 = closed).
int pddl_loader_next(void* handle, uint8_t* images, int32_t* labels) {
  return static_cast<Loader*>(handle)->next(images, labels);
}

void pddl_loader_reset(void* handle) {
  static_cast<Loader*>(handle)->reset();
}

void pddl_loader_close(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
