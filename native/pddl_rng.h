// Shared deterministic shuffling for the native loaders. Both the packed
// loader (pddl_io.cpp) and the TFRecord reader (pddl_tfrecord.cpp) must
// produce identical per-epoch orders for the same seed, so the PRNG and
// the epoch-seeding scheme live here once.
#ifndef PDDL_RNG_H_
#define PDDL_RNG_H_

#include <cstddef>
#include <cstdint>
#include <utility>
#include <vector>

namespace pddl {

// Deterministic 64-bit xorshift.
struct XorShift {
  uint64_t s;
  explicit XorShift(uint64_t seed) : s(seed ? seed : 0x9E3779B97F4A7C15ull) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
};

// In-place Fisher-Yates reshuffle of an index order, reseeded per epoch.
inline void epoch_shuffle(std::vector<size_t>& order, uint64_t seed,
                          long epoch) {
  XorShift rng(seed + 0x1000003ull * (uint64_t)(epoch + 1));
  for (size_t i = order.size(); i > 1; --i) {
    size_t j = rng.next() % i;
    std::swap(order[i - 1], order[j]);
  }
}

}  // namespace pddl

#endif  // PDDL_RNG_H_
