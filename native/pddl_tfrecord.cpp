// Native TFRecord reader.
//
// The reference's ImageNet ingest runs on TFDS-prepared TFRecord shards,
// read and validated inside TensorFlow's C++ tf.data runtime
// (SURVEY.md §2b C15 — /root/reference/imagenet-resnet50.py:20-34). This
// file is the framework's own record-level substrate for that path:
//
//   * full-file indexing of the TFRecord framing
//     (u64 length | u32 masked-crc32c(length) | payload | u32
//     masked-crc32c(payload)) with CRC validation, so corruption is
//     detected at open time rather than mid-epoch
//   * per-process sharding over the global record sequence (the DATA
//     auto-shard analogue, imagenet-resnet50-multiworkers.py:66-69)
//   * deterministic per-epoch shuffling (same xorshift/Fisher-Yates
//     scheme as the packed loader)
//   * a reader thread filling a bounded record queue, overlapping disk
//     IO with the consumer — the .prefetch(AUTOTUNE) analogue
//
// Payload decode (tf.Example proto, JPEG) stays above this layer, exactly
// as TFRecordDataset is decode-agnostic in tf.data.
//
// Plain C ABI, ctypes-consumed (no pybind11).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "pddl_rng.h"

namespace {

// CRC32C (Castagnoli, reflected polynomial 0x82F63B78), byte-table driven.
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
  }
};

uint32_t crc32c(const uint8_t* data, size_t n) {
  static const Crc32cTable table;
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = table.t[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// TFRecord's masking, applied so CRCs stored alongside CRC-bearing data
// don't collide with themselves.
uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t c = crc32c(data, n);
  return ((c >> 15) | (c << 17)) + 0xA282EAD8u;
}

struct RecordRef {
  uint32_t file;
  uint64_t offset;  // offset of the 12-byte header
  uint32_t length;  // payload bytes
};

struct QueuedRecord {
  std::vector<uint8_t> payload;
  bool bad = false;  // read or payload-CRC failure at this position
};

class TFRecordReader {
 public:
  TFRecordReader(std::vector<std::string> paths, int shuffle, uint64_t seed,
                 int shard_index, int shard_count, int verify_payload_crc,
                 int prefetch_depth)
      : paths_(std::move(paths)),
        shuffle_(shuffle),
        seed_(seed),
        shard_index_(shard_index),
        shard_count_(shard_count),
        verify_(verify_payload_crc),
        depth_(std::max(1, prefetch_depth)) {
    if (!index()) {
      ok_ = false;
      return;
    }
    reader_ = std::thread([this] { reader_main(); });
  }

  ~TFRecordReader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_slots_.notify_all();
    cv_items_.notify_all();
    if (reader_.joinable()) reader_.join();
  }

  bool ok() const { return ok_; }
  long count() const { return (long)records_.size(); }
  long total_count() const { return (long)all_count_; }
  long max_length() const { return (long)max_len_; }

  // Blocking pop of the next record into caller memory. Returns payload
  // bytes written (>= 0 — zero-length records are legal TFRecord framing),
  // -4 at end of epoch, -1 closed, -2 cap too small, -3 a read/CRC error
  // was hit at this position.
  long next(uint8_t* out, long cap) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_items_.wait(lk, [this] {
      return stop_ || !queue_.empty() || done_epoch_;
    });
    if (stop_) return -1;
    if (queue_.empty()) return -4;  // epoch exhausted; reset() rearms
    if ((long)queue_.front().payload.size() > cap)
      return -2;  // leave the record queued; caller can retry bigger
    QueuedRecord rec = std::move(queue_.front());
    queue_.pop();
    lk.unlock();
    cv_slots_.notify_one();
    if (rec.bad) return -3;
    std::memcpy(out, rec.payload.data(), rec.payload.size());
    return (long)rec.payload.size();
  }

  void reset() {
    std::unique_lock<std::mutex> lk(mu_);
    ++epoch_;
    done_epoch_ = false;
    cursor_ = 0;
    while (!queue_.empty()) queue_.pop();
    reshuffle();
    lk.unlock();
    cv_slots_.notify_all();
  }

 private:
  bool index() {
    for (uint32_t fi = 0; fi < paths_.size(); ++fi) {
      FILE* f = std::fopen(paths_[fi].c_str(), "rb");
      if (!f) return false;
      uint64_t off = 0;
      while (true) {
        uint8_t hdr[12];
        size_t got = std::fread(hdr, 1, 12, f);
        if (got == 0) break;  // clean EOF
        if (got != 12) {
          std::fclose(f);
          return false;  // truncated header
        }
        uint64_t len;
        uint32_t len_crc;
        std::memcpy(&len, hdr, 8);
        std::memcpy(&len_crc, hdr + 8, 4);
        if (masked_crc(hdr, 8) != len_crc || len > (1ull << 31)) {
          std::fclose(f);
          return false;  // corrupt length field
        }
        // Skip payload + its CRC at index time; payload CRC is checked on
        // read (if verify_) so indexing a pod-scale shard set stays fast.
        if (std::fseek(f, (long)(len + 4), SEEK_CUR) != 0) {
          std::fclose(f);
          return false;
        }
        if ((all_count_ % shard_count_) == (uint64_t)shard_index_) {
          records_.push_back({fi, off, (uint32_t)len});
          max_len_ = std::max(max_len_, len);
        }
        ++all_count_;
        off += 12 + len + 4;
      }
      std::fclose(f);
    }
    order_.resize(records_.size());
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    reshuffle();
    return !records_.empty();
  }

  void reshuffle() {  // call with mu_ held (or before the thread starts)
    if (shuffle_) pddl::epoch_shuffle(order_, seed_, epoch_);
  }

  void reader_main() {
    // Files open lazily and stay cached up to a cap, so a pod-scale shard
    // set (ImageNet: 1024 train files) never exhausts the fd limit.
    constexpr size_t kMaxOpenFiles = 64;
    std::vector<FILE*> files(paths_.size(), nullptr);
    std::vector<uint32_t> open_order;
    auto file_for = [&](uint32_t fi) -> FILE* {
      if (files[fi]) return files[fi];
      if (open_order.size() >= kMaxOpenFiles) {
        uint32_t evict = open_order.front();
        open_order.erase(open_order.begin());
        std::fclose(files[evict]);
        files[evict] = nullptr;
      }
      files[fi] = std::fopen(paths_[fi].c_str(), "rb");
      if (files[fi]) open_order.push_back(fi);
      return files[fi];
    };

    while (true) {
      size_t idx;
      long epoch;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_slots_.wait(lk, [this] {
          return stop_ || (!done_epoch_ && queue_.size() < (size_t)depth_);
        });
        if (stop_) break;
        if (cursor_ >= records_.size()) {
          done_epoch_ = true;
          lk.unlock();
          cv_items_.notify_all();
          continue;
        }
        idx = order_[cursor_++];
        epoch = epoch_;
      }

      const RecordRef& r = records_[idx];
      QueuedRecord rec;
      rec.payload.resize(r.length);
      FILE* f = file_for(r.file);
      if (!f || std::fseek(f, (long)(r.offset + 12), SEEK_SET) != 0 ||
          (r.length && std::fread(rec.payload.data(), 1, r.length, f) !=
                           r.length)) {
        rec.bad = true;
      } else if (verify_) {
        uint32_t stored;
        if (std::fread(&stored, 4, 1, f) != 1 ||
            masked_crc(rec.payload.data(), rec.payload.size()) != stored)
          rec.bad = true;
      }
      if (rec.bad) rec.payload.clear();

      {
        std::lock_guard<std::mutex> lk(mu_);
        if (epoch == epoch_) queue_.push(std::move(rec));
      }
      cv_items_.notify_one();
    }
    for (FILE* f : files)
      if (f) std::fclose(f);
  }

  std::vector<std::string> paths_;
  int shuffle_;
  uint64_t seed_;
  int shard_index_, shard_count_, verify_, depth_;
  uint64_t all_count_ = 0, max_len_ = 0;
  std::vector<RecordRef> records_;
  std::vector<size_t> order_;
  std::thread reader_;

  std::mutex mu_;
  std::condition_variable cv_items_, cv_slots_;
  std::queue<QueuedRecord> queue_;
  size_t cursor_ = 0;
  long epoch_ = 0;
  bool done_epoch_ = false, stop_ = false, ok_ = true;
};

}  // namespace

extern "C" {

void* pddl_tfr_open(const char** paths, int n_paths, int shuffle,
                    uint64_t seed, int shard_index, int shard_count,
                    int verify_payload_crc, int prefetch_depth) {
  if (shard_count < 1 || shard_index < 0 || shard_index >= shard_count)
    return nullptr;
  std::vector<std::string> ps;
  for (int i = 0; i < n_paths; ++i) ps.emplace_back(paths[i]);
  auto* r = new TFRecordReader(std::move(ps), shuffle, seed, shard_index,
                               shard_count, verify_payload_crc,
                               prefetch_depth);
  if (!r->ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

long pddl_tfr_count(void* h) {
  return static_cast<TFRecordReader*>(h)->count();
}

long pddl_tfr_total_count(void* h) {
  return static_cast<TFRecordReader*>(h)->total_count();
}

long pddl_tfr_max_length(void* h) {
  return static_cast<TFRecordReader*>(h)->max_length();
}

long pddl_tfr_next(void* h, uint8_t* out, long cap) {
  return static_cast<TFRecordReader*>(h)->next(out, cap);
}

void pddl_tfr_reset(void* h) { static_cast<TFRecordReader*>(h)->reset(); }

void pddl_tfr_close(void* h) { delete static_cast<TFRecordReader*>(h); }

// Exposed for parity tests against Python/TF implementations.
uint32_t pddl_crc32c(const uint8_t* data, long n) {
  return crc32c(data, (size_t)n);
}

uint32_t pddl_masked_crc32c(const uint8_t* data, long n) {
  return masked_crc(data, (size_t)n);
}

}  // extern "C"
