"""pddl_tpu — a TPU-native parallel & distributed deep-learning framework.

A brand-new JAX/XLA/pjit/shard_map framework with the capabilities of
``rrrickyz/Parallel-and-Distributed-Deep-Learning`` (the reference, 8 standalone
TensorFlow scripts that train ResNet-50 on ImageNet-2012 under four
distribution strategies — see ``/root/reference`` and ``SURVEY.md``), redesigned
TPU-first:

- **One SPMD core, four strategy façades** — every distribution mode
  (single device, mirrored, multi-worker, parameter-server, Horovod-compat)
  lowers to a ``jax.sharding.Mesh`` + ``NamedSharding`` + XLA collectives
  over ICI/DCN. Zero CUDA / NCCL / MPI / gRPC data plane.
- **Keras-fit-like workflow** — ``Trainer`` mirrors the reference's
  ``compile``/``fit`` surface (callbacks, History, validation) as a custom
  jitted train loop.
- **Model zoo** — Flax ResNet family with exact ``tf.keras.applications``
  architecture parity (pretrained-weight import/export via Keras ``.h5``),
  ViT family (incl. the pipeline-parallel ``GPipeViT``), and the causal
  GPT family for long-context work.
- **Every parallelism axis, composable** — data / tensor (Megatron) /
  sequence (ring attention) / expert (Switch-MoE) / pipeline (GPipe) over
  one mesh (``data``/``model``/``seq``/``expert``/``stage``), plus
  ZeRO-style sharded state (the PS strategy).
- **First-class long-context / distributed ops** — Pallas flash attention
  (fused forward AND backward), ring attention, GPipe schedule, MoE
  dispatch (``pddl_tpu.ops``).

The package name abbreviates the reference repo name
(Parallel-and-Distributed-Deep-Learning → ``pddl``) + ``_tpu``.
"""

from pddl_tpu.version import __version__

# Re-exports of the primary public API.  Heavy submodules (models, data,
# train) are imported lazily by user code; core mesh/strategy types are cheap.
from pddl_tpu.core.mesh import MeshConfig, build_mesh, local_device_count
from pddl_tpu.core import collectives
from pddl_tpu.core.sharding import MinSizePartitioner

__all__ = [
    "__version__",
    "MeshConfig",
    "build_mesh",
    "local_device_count",
    "collectives",
    "MinSizePartitioner",
]
