"""pddl_tpu — a TPU-native parallel & distributed deep-learning framework.

A brand-new JAX/XLA/pjit/shard_map framework with the capabilities of
``rrrickyz/Parallel-and-Distributed-Deep-Learning`` (the reference, 8 standalone
TensorFlow scripts that train ResNet-50 on ImageNet-2012 under four
distribution strategies — see ``/root/reference`` and ``SURVEY.md``), redesigned
TPU-first:

- **One SPMD core, four strategy façades** — every distribution mode
  (single device, mirrored, multi-worker, parameter-server, Horovod-compat)
  lowers to a ``jax.sharding.Mesh`` + ``NamedSharding`` + XLA collectives
  over ICI/DCN. Zero CUDA / NCCL / MPI / gRPC data plane.
- **Keras-fit-like workflow** — ``Trainer`` mirrors the reference's
  ``compile``/``fit`` surface (callbacks, History, validation) as a custom
  jitted train loop.
- **Model zoo** — Flax ResNet family with exact ``tf.keras.applications``
  architecture parity (pretrained-weight import/export via Keras ``.h5``),
  ViT family (incl. the pipeline-parallel ``GPipeViT``), and the causal
  GPT family for long-context work.
- **Every parallelism axis, composable** — data / tensor (Megatron) /
  sequence (ring attention) / expert (Switch-MoE) / pipeline (GPipe) over
  one mesh (``data``/``model``/``seq``/``expert``/``stage``), plus
  ZeRO-style sharded state (the PS strategy).
- **First-class long-context / distributed ops** — Pallas flash attention
  (fused forward AND backward), ring attention, GPipe schedule, MoE
  dispatch (``pddl_tpu.ops``).

The package name abbreviates the reference repo name
(Parallel-and-Distributed-Deep-Learning → ``pddl``) + ``_tpu``.
"""

from pddl_tpu.version import __version__

# Re-exports of the primary public API, resolved LAZILY (PEP 562): the
# names below behave exactly as eager imports for user code
# (``pddl_tpu.build_mesh``, ``from pddl_tpu import MeshConfig``), but
# importing the bare package no longer pulls in jax. That keeps
# import-free tooling import-free — ``python -m pddl_tpu.analysis``
# (graftlint) is pure-AST by contract and must never pay (or depend
# on) a jax import just to reach its own package.
_LAZY_EXPORTS = {
    "MeshConfig": ("pddl_tpu.core.mesh", "MeshConfig"),
    "build_mesh": ("pddl_tpu.core.mesh", "build_mesh"),
    "local_device_count": ("pddl_tpu.core.mesh", "local_device_count"),
    "collectives": ("pddl_tpu.core.collectives", None),
    "MinSizePartitioner": ("pddl_tpu.core.sharding", "MinSizePartitioner"),
}

__all__ = ["__version__"] + sorted(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
