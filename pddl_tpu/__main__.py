"""``python -m pddl_tpu`` — the CLI entry (see :mod:`pddl_tpu.run`)."""

import sys

from pddl_tpu.run import main

sys.exit(main())
