"""graftlint — static invariant analysis for the pddl_tpu engine.

``python -m pddl_tpu.analysis --check pddl_tpu/`` machine-checks the
stack's load-bearing conventions (pin/release pairing, donation
discipline, recompile hazards, site vocabularies, exposition parity,
snapshot hygiene) at pure-AST level: no jax import, no module
execution, sub-second over the whole tree — cheap enough for every
test run (``tests/test_analysis.py``, marker ``analysis``).

See ``docs/ANALYSIS.md`` for the invariant catalogue, suppression and
baseline syntax, and how to add a checker.
"""

from __future__ import annotations

from pddl_tpu.analysis.core import (  # noqa: F401 - the public surface
    DEFAULT_BASELINE,
    Finding,
    Project,
    Rule,
    all_rules,
    apply_baseline,
    load_baseline,
    run_analysis,
)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "Project",
    "Rule",
    "all_rules",
    "apply_baseline",
    "load_baseline",
    "run_analysis",
]
