"""The graftlint CLI: ``python -m pddl_tpu.analysis [--check] [paths]``.

Exit codes: 0 clean (every finding suppressed or baselined, no stale
baseline entries, no parse errors), 1 findings/stale entries, 2 usage
or parse errors. ``--check`` is the CI mode tier-1 runs
(tests/test_analysis.py pins it clean over ``pddl_tpu/``); without it
the run additionally lists baselined findings for a human pass.
"""

from __future__ import annotations

import argparse
import sys

from pddl_tpu.analysis.core import (
    DEFAULT_BASELINE,
    all_rules,
    apply_baseline,
    load_baseline,
    run_analysis,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pddl_tpu.analysis",
        description="graftlint: static invariant analysis "
                    "(pure AST — never imports the checked code)")
    parser.add_argument("paths", nargs="*", default=["pddl_tpu"],
                        help="files/directories to analyze "
                             "(default: pddl_tpu)")
    parser.add_argument("--check", action="store_true",
                        help="CI mode: succeed silently, fail loudly")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON of justified exceptions")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (show everything)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule names to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.name}: {rule.doc}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    findings, errors, analyzed = run_analysis(args.paths, rules=rules)
    for err in errors:
        print(f"error: {err}", file=sys.stderr)

    try:
        entries = [] if args.no_baseline else load_baseline(args.baseline)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    kept, used, stale = apply_baseline(
        findings, entries, analyzed_paths=analyzed,
        active_rules={r.name for r in rules})

    for f in kept:
        print(f.format())
    if not args.check and used:
        print(f"-- {len(findings) - len(kept)} baselined finding(s) "
              f"under {len(used)} justified exception(s)")
    for e in stale:
        print(f"stale baseline entry (nothing matches it — remove): "
              f"[{e['rule']}] {e['path']} :: {e['symbol']}",
              file=sys.stderr)

    failed = bool(kept) or bool(stale) or bool(errors)
    if failed:
        print(f"graftlint: {len(kept)} finding(s), {len(stale)} stale "
              f"baseline entr(y/ies), {len(errors)} error(s)",
              file=sys.stderr)
    elif not args.check:
        print(f"graftlint: clean ({len(rules)} rules"
              + (f", {len(findings)} baselined" if findings else "")
              + ")")
    # Per the contract above: 2 = broken RUN (bad paths, unparseable
    # files), 1 = findings/stale entries, 0 = clean. A CI wrapper must
    # be able to tell "the tree has a bug" from "the gate never ran".
    if errors:
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
