"""The graftlint rule registry — one module per invariant.

Each checker file's docstring names the invariant it encodes and the
CHANGES.md incident that motivated it; ``docs/ANALYSIS.md`` is the
catalogue. Adding a checker: subclass
:class:`pddl_tpu.analysis.core.Rule`, set ``name``/``doc``, implement
``run(project)``, append the class here, and give it a seeded-bad
fixture + good twin under ``tests/fixtures/graftlint/``.
"""

from __future__ import annotations

from pddl_tpu.analysis.checkers.donation import DonationRule
from pddl_tpu.analysis.checkers.epoch_vocab import EpochVocabRule
from pddl_tpu.analysis.checkers.exposition import ExpositionParityRule
from pddl_tpu.analysis.checkers.pin_release import PinReleaseRule
from pddl_tpu.analysis.checkers.recompile import RecompileHazardRule
from pddl_tpu.analysis.checkers.role_vocab import RoleVocabRule
from pddl_tpu.analysis.checkers.site_vocab import SiteVocabRule
from pddl_tpu.analysis.checkers.snapshot_vocab import SnapshotHygieneRule
from pddl_tpu.analysis.checkers.trace_vocab import TraceVocabRule

RULES = (
    PinReleaseRule,
    DonationRule,
    RecompileHazardRule,
    SiteVocabRule,
    ExpositionParityRule,
    SnapshotHygieneRule,
    RoleVocabRule,
    TraceVocabRule,
    EpochVocabRule,
)

__all__ = ["RULES"] + [cls.__name__ for cls in RULES]
