"""Rule ``donation``: donated buffers are dead after the call, and
donated trees must never carry host-numpy leaves.

Two sub-invariants, one rule id:

**(a) read-after-donation.** Programs built with ``jax.jit(fn,
donate_argnums=...)`` consume the buffers at the donated positions —
the engine's contract is "the caller always adopts the returned tree"
(serve/engine.py's donation-discipline note). A name passed at a
donated position and then *read* later in the function, without being
reassigned from the call's result, is a use-after-free that XLA only
sometimes punishes (the container-jaxlib heap corruptions of r10/r13
were exactly this class surfacing as flaky garbage reads).

**(b) host-numpy leaves riding donation.** A ``np.*`` (host) array
stored into a tree that later rides a donated site gives the runtime a
donated buffer it does not own — the documented tier-1 flake
(ROADMAP "Known flake": ``train/state.set_learning_rate`` stored a
host-numpy LR scalar into ``opt_state``, which the donated train step
then consumed; the LR would intermittently read back as
float32-bits-of-int). Detection is lexical: a ``np.``-constructed
value stored into a subscript/attribute of a donated-tree-ish name
(``*hp*``/``hyperparams``/``opt_state``/``*cache*``/``*pool*``).
Device (``jnp``) stamps are the fix and pass clean.

Scope: per-module. Donated programs are collected from ``jax.jit``
calls with a literal ``donate_argnums`` assigned to ``self._X`` /
module names; call sites both direct and through the repo's
``_device_call(site, fn, *args)`` boundary are checked. Positions
past a ``*star`` argument cannot be mapped and are skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Finding,
    Module,
    Project,
    Rule,
    call_name,
    unparse,
    walk_functions,
)

_TREE_NAME_PARTS = {"hp", "hyperparams", "hyperparam", "opt_state",
                    "cache", "pool"}


def _is_jit_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return True
    if isinstance(fn, ast.Name) and fn.id == "jit":
        return True
    return False


def _donate_argnums(node: ast.Call) -> Optional[Set[int]]:
    for kw in node.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        # ``(0,) if cond else ()`` — take the donating branch: the
        # checker guards the donating configuration.
        if isinstance(value, ast.IfExp):
            value = value.body if isinstance(value.body, ast.Tuple) \
                and value.body.elts else value.orelse
        if isinstance(value, (ast.Tuple, ast.List)):
            nums = set()
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                int):
                    nums.add(elt.value)
                else:
                    return None
            return nums
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return {value.value}
        return None
    return None


class DonationRule(Rule):
    name = "donation"
    doc = ("names passed at donated jit positions must not be read "
           "after the call; donated trees must not carry host-numpy "
           "leaves")

    def run(self, project: Project) -> Iterable[Finding]:
        for module in project.modules:
            donated = self._collect_donated(module)
            for fn in walk_functions(module.tree):
                yield from self._check_read_after(module, fn, donated)
                yield from self._check_host_leaves(module, fn)

    # ---------------------------------------------------- collection
    def _collect_donated(self, module: Module) -> Dict[str, Set[int]]:
        """``{assigned-name: donated argnums}`` for every
        ``X = jax.jit(fn, donate_argnums=...)`` in the module. Keys are
        the bare attribute/name (``_tick_p`` for ``self._tick_p``)."""
        donated: Dict[str, Set[int]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (isinstance(value, ast.Call) and _is_jit_call(value)):
                continue
            nums = _donate_argnums(value)
            if not nums:
                continue
            for target in node.targets:
                if isinstance(target, ast.Attribute):
                    donated[target.attr] = nums
                elif isinstance(target, ast.Name):
                    donated[target.id] = nums
        return donated

    # ------------------------------------------------ read-after-free
    # Simple (non-compound) statement types: only these claim calls —
    # compound statements (if/try/while) have no assignment targets,
    # so letting them claim a child's call would make every adopted
    # donation look like a read-after-free.
    _SIMPLE = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
               ast.Return, ast.Raise, ast.Assert)

    def _check_read_after(self, module: Module, fn: ast.FunctionDef,
                          donated: Dict[str, Set[int]]) -> Iterable[Finding]:
        if not donated:
            return
        for stmt, continuation in self._stmts_with_continuations(fn):
            for call in [n for n in ast.walk(stmt)
                         if isinstance(n, ast.Call)]:
                spec = self._donated_args(call, donated)
                if spec is None:
                    continue
                prog, args = spec
                targets = self._stmt_targets(stmt)
                for pos, arg in args:
                    path = self._pathable(arg)
                    if path is None:
                        continue
                    if path in targets:
                        continue  # result adopted over the donated name
                    bad = self._first_read_after(continuation, path)
                    if bad is not None:
                        yield self.finding(
                            module, bad,
                            f"`{path}` was donated to `{prog}` (argnum "
                            f"{pos}, line {call.lineno}) and is read "
                            "here without reassignment — its buffer "
                            "was consumed by the donated program")

    def _stmts_with_continuations(self, fn: ast.FunctionDef):
        """Every simple statement paired with the statements that can
        actually execute AFTER it: the rest of its own block, then the
        rest of each enclosing block, flattened — never the sibling
        arm of an `if` the statement sits in, never a different nested
        function, never an except handler the normal path skips. Loop
        back-edges are not modeled (documented limitation)."""

        def walk_block(block: List[ast.stmt], after: List[ast.stmt]):
            for i, stmt in enumerate(block):
                rest = block[i + 1:] + after
                if isinstance(stmt, self._SIMPLE):
                    yield stmt, rest
                elif isinstance(stmt, ast.If):
                    yield from walk_block(stmt.body, rest)
                    yield from walk_block(stmt.orelse, rest)
                elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    yield from walk_block(stmt.body, rest)
                    yield from walk_block(stmt.orelse, rest)
                elif isinstance(stmt, ast.Try):
                    body_after = stmt.orelse + stmt.finalbody + rest
                    yield from walk_block(stmt.body, body_after)
                    yield from walk_block(stmt.orelse,
                                          stmt.finalbody + rest)
                    for handler in stmt.handlers:
                        yield from walk_block(handler.body,
                                              stmt.finalbody + rest)
                    yield from walk_block(stmt.finalbody, rest)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    yield from walk_block(stmt.body, rest)
                # Nested defs/classes: their bodies are separate
                # control-flow universes — visited by the caller's
                # walk_functions pass, not here.

        yield from walk_block(fn.body, [])

    def _donated_args(self, call: ast.Call,
                      donated: Dict[str, Set[int]]
                      ) -> Optional[Tuple[str, List[Tuple[int, ast.expr]]]]:
        """(program-name, [(argnum, expr)]) when ``call`` dispatches a
        known donated program, directly or via ``_device_call``."""
        name = call_name(call)
        args = call.args
        prog: Optional[str] = None
        if name in donated:
            prog, offset = name, 0
        elif name == "_device_call" and len(args) >= 2:
            fn_arg = args[1]
            fn_name = (fn_arg.attr if isinstance(fn_arg, ast.Attribute)
                       else fn_arg.id if isinstance(fn_arg, ast.Name)
                       else None)
            if fn_name in donated:
                prog, offset = fn_name, 2
            else:
                return None
        else:
            return None
        out: List[Tuple[int, ast.expr]] = []
        for num in sorted(donated[prog]):
            idx = offset + num
            if idx >= len(args):
                return None
            # A *starred arg before the donated position breaks the
            # positional mapping — skip rather than guess.
            if any(isinstance(a, ast.Starred) for a in args[:idx + 1]):
                return None
            out.append((num, args[idx]))
        return prog, out

    @staticmethod
    def _pathable(arg: ast.expr) -> Optional[str]:
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Subscript)):
            for node in ast.walk(arg):
                if isinstance(node, ast.Call):
                    return None
            return unparse(arg)
        return None

    def _stmt_targets(self, stmt: ast.stmt) -> Set[str]:
        targets: Set[str] = set()
        nodes: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            nodes = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            nodes = [stmt.target]
        for t in nodes:
            if isinstance(t, (ast.Tuple, ast.List)):
                nodes.extend(t.elts)
            else:
                targets.add(unparse(t))
        return targets

    def _first_read_after(self, stmts: List[ast.stmt],
                          path: str) -> Optional[int]:
        """Line of the first Load of ``path`` before any Store of it
        along the continuation, else None. The scan ends at an
        unconditional block-level Return/Raise/Break/Continue — the
        enclosing-block tail behind it is unreachable from here (a
        conditional exit nested in a later compound does not stop it).
        """
        for stmt in stmts:
            stored = path in self._stmt_targets(stmt)
            loaded: Optional[int] = None
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Name, ast.Attribute,
                                     ast.Subscript)) \
                        and isinstance(getattr(node, "ctx", None),
                                       ast.Load) \
                        and unparse(node) == path:
                    loaded = node.lineno
                    break
            if loaded is not None and not stored:
                return loaded
            if stored:
                return None
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                return None
        return None

    # ------------------------------------------------ host-numpy leaf
    def _check_host_leaves(self, module: Module,
                           fn: ast.FunctionDef) -> Iterable[Finding]:
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.Assign):
                continue
            if not self._is_np_call(stmt.value):
                continue
            for target in stmt.targets:
                root = self._tree_store_root(target)
                if root is not None:
                    yield self.finding(
                        module, stmt.lineno,
                        f"host-numpy value stored into `{root}` — this "
                        "tree rides a donated device call, and donating "
                        "a host-owned buffer corrupts the heap (the "
                        "set_learning_rate tier-1 flake class); stamp "
                        "a device array (jnp) instead")

    @staticmethod
    def _is_np_call(value: ast.expr) -> bool:
        if not isinstance(value, ast.Call):
            return False
        fn = value.func
        root = fn
        while isinstance(root, ast.Attribute):
            root = root.value
        if not isinstance(root, ast.Name):
            return False
        return root.id in ("np", "numpy", "_np")

    @staticmethod
    def _tree_store_root(target: ast.expr) -> Optional[str]:
        """The base name of a subscript/attribute store whose
        identifier parts mark a donated tree (hp/hyperparams/
        opt_state/cache/pool)."""
        if not isinstance(target, (ast.Subscript, ast.Attribute)):
            return None
        base = target.value
        while isinstance(base, (ast.Subscript, ast.Attribute)):
            base = base.value if isinstance(base, ast.Subscript) \
                else base.value
        if not isinstance(base, ast.Name):
            return None
        parts = set(base.id.lower().strip("_").split("_"))
        if parts & _TREE_NAME_PARTS or "opt_state" in base.id:
            return base.id
        return None
