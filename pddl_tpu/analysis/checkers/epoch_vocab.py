"""Rule ``epoch-vocab``: the fencing-epoch command manifest must agree
across the driver that stamps it and the worker that enforces it.

ISSUE 20 made the router's fencing epoch the single-writer token: a
deposed-but-alive primary is kept off the fleet because every
worker-bound fleet MUTATOR carries ``cmd["epoch"]`` and the worker's
dispatch gate refuses stale ones. That guarantee is exactly as strong
as two literal tuples staying equal — ``EPOCH_CMDS`` on the driver
side (`serve/fleet/replica.py`: the commands whose emit sites stamp
the epoch) and ``FENCED_CMDS`` on the worker side
(`serve/fleet/worker.py`: the commands the fence gate intercepts).
A command stamped but not gated is fencing theatre (the worker
ignores the field); a command gated but never stamped is a hole a
deposed primary can still drive the fleet through. Neither direction
fails a test until a split-brain actually happens — which is why the
manifest is machine-checked here instead.

Checked:

- in a module declaring ``EPOCH_CMDS``: every ``{"cmd": <literal>}``
  dict built by a function that stamps the epoch (an inline
  ``"epoch"`` key, or a ``...["epoch"] = ...`` assignment in the same
  function) names a command the manifest declares — and every
  manifest entry has at least one such stamped emit site (no stale
  manifest entries);
- in a module declaring ``FENCED_CMDS``: the tuple is SET-EQUAL to
  the paired driver module's ``EPOCH_CMDS`` (both directions:
  extra and missing reported), and every gated command actually
  appears in the worker's dispatch table (a ``== "<cmd>"``
  comparison) — gating a command no branch serves hides a typo
  forever.

Pairing: a module declaring both tuples is self-paired (test
fixtures); otherwise the path map below (worker → replica), resolved
through the project so fixtures can shadow it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    const_str_tuple,
)

# Fence-gate mirror -> the authoritative driver-side manifest.
WORKER_DRIVER_PAIRS = (
    ("pddl_tpu/serve/fleet/worker.py", "pddl_tpu/serve/fleet/replica.py"),
)


def _module_const(tree: ast.AST,
                  name: str) -> Optional[Tuple[List[str], int]]:
    """A module-level ``NAME = ("a", "b", ...)`` string tuple:
    ``(values, line)``, or None."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == name:
                vals = const_str_tuple(node.value)
                if vals is not None:
                    return vals, node.lineno
    return None


def _stamped_cmd_literals(tree: ast.AST) -> List[Tuple[str, int, bool]]:
    """Every ``{"cmd": "<name>", ...}`` dict literal, function by
    function: ``(name, line, stamped)`` where ``stamped`` means the
    dict carries an inline ``"epoch"`` key OR the enclosing function
    assigns ``something["epoch"] = ...`` (the conditional-stamp
    idiom)."""
    out: List[Tuple[str, int, bool]] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fn_stamps = False
        literals: List[Tuple[str, int, bool]] = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                for target in sub.targets:
                    if (isinstance(target, ast.Subscript)
                            and isinstance(target.slice, ast.Constant)
                            and target.slice.value == "epoch"):
                        fn_stamps = True
            if not isinstance(sub, ast.Dict):
                continue
            name: Optional[str] = None
            inline_epoch = False
            for key, value in zip(sub.keys, sub.values):
                if not isinstance(key, ast.Constant):
                    continue
                if key.value == "cmd" and isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    name = value.value
                elif key.value == "epoch":
                    inline_epoch = True
            if name is not None:
                literals.append((name, sub.lineno, inline_epoch))
        out.extend((name, line, inline or fn_stamps)
                   for name, line, inline in literals)
    return out


def _eq_str_literals(tree: ast.AST) -> Set[str]:
    """Every string compared with ``==``/``!=`` anywhere in the module
    — the dispatch table's branch labels."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        for op, comparator in zip(node.ops, node.comparators):
            if not isinstance(op, (ast.Eq, ast.NotEq)):
                continue
            if isinstance(comparator, ast.Constant) \
                    and isinstance(comparator.value, str):
                out.add(comparator.value)
        if isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str):
            out.add(node.left.value)
    return out


class EpochVocabRule(Rule):
    name = "epoch-vocab"
    doc = ("the fencing-epoch command manifest (driver EPOCH_CMDS / "
           "worker FENCED_CMDS) must agree both directions, every "
           "manifested command must have a stamped emit site, and "
           "every gated command a dispatch branch")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            yield from self._check_driver(module)
            yield from self._check_worker(project, module)

    # --------------------------------------------------- driver side
    def _check_driver(self, module: Module) -> Iterable:
        declared = _module_const(module.tree, "EPOCH_CMDS")
        if declared is None:
            return
        cmds, cmds_line = declared
        stamped_names: Set[str] = set()
        for name, line, stamped in _stamped_cmd_literals(module.tree):
            if not stamped:
                continue
            stamped_names.add(name)
            if name not in cmds:
                yield self.finding(
                    module, line,
                    f"command {name!r} is emitted with an epoch stamp "
                    "but EPOCH_CMDS does not declare it — the worker "
                    "fence gate will not intercept it, so a deposed "
                    "primary can still drive the fleet through it")
        for cmd in cmds:
            if cmd not in stamped_names:
                yield self.finding(
                    module, cmds_line,
                    f"EPOCH_CMDS entry {cmd!r} has no epoch-stamped "
                    "emit site — a stale manifest entry claiming a "
                    "fence the driver never arms")

    # --------------------------------------------------- worker side
    def _driver_manifest(self, project: Project, module: Module
                         ) -> Optional[Tuple[List[str], Module, int]]:
        own = _module_const(module.tree, "EPOCH_CMDS")
        if own is not None:
            return own[0], module, own[1]
        for left, right in WORKER_DRIVER_PAIRS:
            if module.rel.endswith(left):
                driver_mod = project.module_by_suffix(right)
                if driver_mod is None:
                    return None
                paired = _module_const(driver_mod.tree, "EPOCH_CMDS")
                if paired is not None:
                    return paired[0], driver_mod, paired[1]
        return None

    def _check_worker(self, project: Project,
                      module: Module) -> Iterable:
        mirror = _module_const(module.tree, "FENCED_CMDS")
        if mirror is None:
            return
        mirror_vals, mirror_line = mirror
        manifest = self._driver_manifest(project, module)
        if manifest is not None:
            auth_vals, auth_mod, auth_line = manifest
            if set(mirror_vals) != set(auth_vals):
                extra = sorted(set(mirror_vals) - set(auth_vals))
                missing = sorted(set(auth_vals) - set(mirror_vals))
                detail = []
                if extra:
                    detail.append(f"gates unstamped commands {extra}")
                if missing:
                    detail.append(f"is missing stamped commands "
                                  f"{missing}")
                yield self.finding(
                    module, mirror_line,
                    f"FENCED_CMDS disagrees with the driver manifest "
                    f"EPOCH_CMDS ({auth_mod.rel}:{auth_line}): "
                    f"{'; '.join(detail)} — fencing is only as strong "
                    "as the stalest binary's table")
        dispatch = _eq_str_literals(module.tree)
        for cmd in mirror_vals:
            if cmd not in dispatch:
                yield self.finding(
                    module, mirror_line,
                    f"FENCED_CMDS entry {cmd!r} has no dispatch branch "
                    f"(no == {cmd!r} comparison) — the gate guards a "
                    "command no branch serves, hiding a typo forever")
