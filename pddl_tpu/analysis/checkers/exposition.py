"""Rule ``exposition-parity``: every metrics field must be reachable
from the Prometheus exposition renderers.

The runtime drift guard (tests/test_obs.py) asserts snapshot keys and
rendered series agree — but it can only see fields that made it INTO
``snapshot()``. A counter recorded on the class and never added to the
snapshot dict is invisible to both the exposition and the drift guard:
it silently never exports (found on the first run of this rule:
``ServeMetrics.retry_sites`` — per-site retry attribution recorded
since r08, never exported). This rule closes that gap statically:

- every public instance attribute a metrics class initializes in
  ``__init__`` must surface in its ``snapshot()`` dict literal — by
  exact key, or as a stem of a derived key (``ttft_s`` reservoirs
  surface as ``ttft_p50_s``/``ttft_p99_s``); attributes assigned from
  constructor parameters (configuration, not measurements) are exempt;
- every name in a ``*_COUNTER_KEYS`` frozenset (obs/export.py's
  counter-typing vocabulary) must be a key the paired snapshot
  function actually emits — a stale declaration types a ghost metric.

A class participates when it defines BOTH an ``__init__`` with
``self.*`` assignments and a ``snapshot()`` returning a dict literal
(ServeMetrics, fixture twins). Counter-key sets pair with snapshot
keys in the same module, else through ``COUNTER_KEY_BINDINGS``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    const_str_tuple,
    string_keys,
)

# obs/export.py counter vocabularies -> (module holding the snapshot
# keys, function/class scope that emits them).
COUNTER_KEY_BINDINGS = (
    ("pddl_tpu/obs/export.py", "SERVE_COUNTER_KEYS",
     "pddl_tpu/serve/metrics.py", "ServeMetrics"),
    ("pddl_tpu/obs/export.py", "TRAIN_COUNTER_KEYS",
     "pddl_tpu/train/loop.py", "Trainer"),
)


def _class_defs(tree: ast.AST) -> List[ast.ClassDef]:
    return [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _init_attrs(init: ast.FunctionDef) -> Dict[str, Tuple[int, bool]]:
    """``{attr: (line, from_param)}`` for every ``self.x = ...`` in
    __init__ (public names only)."""
    params = {a.arg for a in init.args.args} - {"self"}
    out: Dict[str, Tuple[int, bool]] = {}
    for node in ast.walk(init):
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Attribute) and isinstance(t.value,
                                                           ast.Name) \
                    and t.value.id == "self" \
                    and not t.attr.startswith("_"):
                from_param = any(
                    isinstance(n, ast.Name) and n.id in params
                    for n in ast.walk(value))
                out.setdefault(t.attr, (t.lineno, from_param))
    return out


def _snapshot_keys(fn: ast.FunctionDef,
                   cls: ast.ClassDef) -> Set[str]:
    """String keys of every dict literal in ``fn``; a ``**self.X``
    splat additionally pulls the keys of ``self.X``'s __init__ dict
    literal (the Trainer's fault_stats pattern)."""
    keys: Set[str] = set()
    splats: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k, _ in string_keys(node):
                keys.add(k)
            for k, v in zip(node.keys, node.values):
                if k is None:  # **splat
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.Attribute) \
                                and isinstance(sub.value, ast.Name) \
                                and sub.value.id == "self":
                            splats.add(sub.attr)
    if splats:
        init = _method(cls, "__init__")
        if init is not None:
            for node in ast.walk(init):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) \
                                and t.attr in splats \
                                and isinstance(node.value, ast.Dict):
                            for k, _ in string_keys(node.value):
                                keys.add(k)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and isinstance(node.target, ast.Attribute) \
                        and node.target.attr in splats \
                        and isinstance(node.value, ast.Dict):
                    for k, _ in string_keys(node.value):
                        keys.add(k)
    return keys


def _covered(attr: str, keys: Set[str]) -> bool:
    """``attr`` surfaces in some snapshot key: exact, or every ``_``
    part of the attribute appears in order inside a key (``ttft_s`` →
    ``ttft_p50_s``, ``ttft_by_priority`` → ``ttft_p99_s_by_priority``).
    """
    if attr in keys:
        return True
    parts = []
    for p in attr.split("_"):
        if not p:
            continue
        # Plural-tolerant: ``step_times`` surfaces as
        # ``step_time_p50_s``.
        stem = p[:-1] if (p.endswith("s") and len(p) > 2) else p
        parts.append(re.escape(stem) + "s?")
    pattern = re.compile(".*".join(parts))
    return any(pattern.search(key) for key in keys)


class ExpositionParityRule(Rule):
    name = "exposition-parity"
    doc = ("every metrics field must surface in snapshot()/the "
           "exposition; counter-key declarations must match emitted "
           "keys")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            for cls in _class_defs(module.tree):
                init = _method(cls, "__init__")
                snap = _method(cls, "snapshot")
                if init is None or snap is None:
                    continue
                keys = _snapshot_keys(snap, cls)
                if not keys:
                    continue
                for attr, (line, from_param) in sorted(
                        _init_attrs(init).items()):
                    if from_param or _covered(attr, keys):
                        continue
                    yield self.finding(
                        module, line,
                        f"{cls.name}.{attr} is recorded but never "
                        "surfaces in snapshot() — invisible to the "
                        "exposition AND to the runtime drift guard")
            yield from self._check_counter_sets(project, module)

    # ------------------------------------------- counter-key parity
    def _check_counter_sets(self, project: Project,
                            module: Module) -> Iterable:
        local_snapshot_keys = self._module_snapshot_keys(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            target = node.targets[0] if node.targets else None
            if not (isinstance(target, ast.Name)
                    and target.id.endswith("_COUNTER_KEYS")):
                continue
            declared = const_str_tuple(node.value)
            if declared is None:
                continue
            emitted = self._bound_keys(project, module, target.id,
                                       local_snapshot_keys)
            if emitted is None:
                continue
            for key in sorted(set(declared) - emitted):
                yield self.finding(
                    module, node.lineno,
                    f"{target.id} declares {key!r} but no paired "
                    "snapshot emits that key — stale counter typing")

    def _module_snapshot_keys(self, module: Module) -> Set[str]:
        keys: Set[str] = set()
        for cls in _class_defs(module.tree):
            snap = _method(cls, "snapshot")
            if snap is not None:
                keys |= _snapshot_keys(snap, cls)
        return keys

    def _bound_keys(self, project: Project, module: Module,
                    set_name: str,
                    local_keys: Set[str]) -> Optional[Set[str]]:
        for export_suffix, name, metrics_suffix, cls_name in \
                COUNTER_KEY_BINDINGS:
            if module.rel.endswith(export_suffix) and set_name == name:
                target = project.module_by_suffix(metrics_suffix)
                if target is None:
                    return None
                for cls in _class_defs(target.tree):
                    if cls.name != cls_name:
                        continue
                    for fname in ("snapshot", "fault_snapshot"):
                        fn = _method(cls, fname)
                        if fn is not None:
                            return _snapshot_keys(fn, cls)
                return None
        # Same-module pairing (fixtures): counter keys next to the
        # class that emits them.
        return local_keys if local_keys else None
