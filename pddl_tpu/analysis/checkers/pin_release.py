"""Rule ``pin-release``: pin/allocate must pair with exactly one
release on every path out of the acquiring function.

The invariant (docs/ARCHITECTURE.md §7e, docs/ANALYSIS.md): a radix
chain, pool block list, or adapter row that a function pins or
allocates must, by every exit of that function, have been either

- released exactly once (``unpin``/``release``/``unassign``/``free``
  on the same receiver), or
- handed off — stored into longer-lived state (``self.*`` /
  a subscript / a container that is itself stored), passed to an
  attaching call (``extend`` et al.), or returned to the caller.

Both historical failure modes of this invariant were caught by review,
not tooling, which is why this rule exists:

- **r13 parked-slice drop** (CHANGES.md PR 8 review pass): a parked
  mid-prefill slice was dropped on the paged-world reset still holding
  allocated block ids and a pinned index node — a leak on an early
  exit path.
- **r14 adapter double-release** (CHANGES.md PR 9 review pass): a
  faulted install unwound an adapter pin twice — a refcount underflow
  on an exception path.

Analysis is intraprocedural over a structural abstract interpretation
of each function body (if/for/while/try handled; loop bodies analyzed
once). Branch merges use MAY-release semantics — an obligation
survives a merge only if it is live on *every* incoming path — so a
release on either arm of a conditional counts, and the rule errs
quiet. Exception handlers are entered with the state at ``try`` entry
(obligations acquired before the try are live there; the handler must
discharge them before re-raising). Double-release tracking is
MUST-based: a second release only fires when the first happened on
every path. Pins that legitimately outlive the function (pin at
admission, unpin at park) discharge through the hand-off rules above.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Finding,
    Module,
    Project,
    Rule,
    call_name,
    receiver_str,
    unparse,
    walk_functions,
)

# Verbs that create an obligation. "Value" acquires return the
# resource (``ids = pool.allocate(n)``); "arg" acquires take it as the
# first argument (``prefix.pin(node)``). ``pin_chain`` is the host
# tier's match-and-pin (ISSUE 13, `serve/kvcache/hosttier.py`): a
# promotion acquires the host chain through it and must ``unpin`` the
# returned tip on every exit — the demote/promote pin pair this
# vocabulary grew to cover (fixture: a promotion path that leaks the
# host pin on fault-unwind, `pin_release_bad_hosttier.py`).
ACQUIRE_VALUE = frozenset({"allocate", "assign", "acquire", "pin_chain"})
ACQUIRE_ARG = frozenset({"pin"})
RELEASE = frozenset({"release", "unpin", "unassign", "free"})
# Hand-off to longer-lived structure needs no verb list: passing a
# resource-carrying name to ANY non-release call (extend/append/
# submit/...) transfers ownership — see _handle_calls.


@dataclasses.dataclass
class _Obligation:
    key: Tuple[str, str, int]   # (receiver, resource-name, line)
    receiver: str
    resource: str
    verb: str
    line: int


class _State:
    """Abstract state along one path."""

    __slots__ = ("held", "aliases", "released", "terminated")

    def __init__(self):
        self.held: Dict[Tuple, _Obligation] = {}
        # variable name -> obligation keys it carries (aliasing via
        # plain assignment / container literals).
        self.aliases: Dict[str, Set[Tuple]] = {}
        # (receiver, path-expr, root-name) released on EVERY path so
        # far — the double-release (must) tracking set.
        self.released: Set[Tuple[str, str, str]] = set()
        self.terminated = False

    def copy(self) -> "_State":
        st = _State()
        st.held = dict(self.held)
        st.aliases = {k: set(v) for k, v in self.aliases.items()}
        st.released = set(self.released)
        st.terminated = self.terminated
        return st

    @staticmethod
    def merge(states: List["_State"]) -> "_State":
        live = [s for s in states if not s.terminated]
        if not live:
            st = _State()
            st.terminated = True
            return st
        st = _State()
        # MAY-release: an obligation survives only if live everywhere.
        keys = set(live[0].held)
        for s in live[1:]:
            keys &= set(s.held)
        st.held = {k: live[0].held[k] for k in keys}
        # MUST-release: only what every path released.
        st.released = set(live[0].released)
        for s in live[1:]:
            st.released &= s.released
        for s in live:
            for name, obls in s.aliases.items():
                st.aliases.setdefault(name, set()).update(obls)
        return st


class PinReleaseRule(Rule):
    name = "pin-release"
    doc = ("pinned/allocated resources must be released exactly once "
           "on every exit path, or handed off to longer-lived state")

    def run(self, project: Project) -> Iterable[Finding]:
        for module in project.modules:
            for fn in walk_functions(module.tree):
                yield from self._check_function(module, fn)

    # ------------------------------------------------------- function
    def _check_function(self, module: Module,
                        fn: ast.FunctionDef) -> Iterable[Finding]:
        self._findings: List[Finding] = []
        self._module = module
        self._seen: Set[Tuple[int, str]] = set()
        # Enclosing ``finally`` bodies, innermost last: Python runs
        # them before a return/raise completes, so exit-time leak
        # checks must apply their releases first.
        self._finally_stack: List[List[ast.stmt]] = []
        out = self._exec_block(fn.body, _State())
        if not out.terminated:
            self._check_exit(out, fn.body[-1] if fn.body else fn,
                             "falls off the end of the function")
        return self._findings

    def _emit(self, line: int, message: str) -> None:
        if (line, message) not in self._seen:
            self._seen.add((line, message))
            self._findings.append(self.finding(self._module, line, message))

    # ------------------------------------------------------ execution
    def _exec_block(self, stmts: List[ast.stmt], st: _State) -> _State:
        for stmt in stmts:
            if st.terminated:
                break
            st = self._exec_stmt(stmt, st)
        return st

    def _exec_stmt(self, stmt: ast.stmt, st: _State) -> _State:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._handle_assign(stmt, st)
            return st
        if isinstance(stmt, ast.Expr):
            self._handle_calls(stmt.value, st, stmt)
            return st
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._handle_calls(stmt.value, st, stmt)
                self._discharge_names(stmt.value, st)
            self._check_exit(st, stmt, "returns")
            st.terminated = True
            return st
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._handle_calls(stmt.exc, st, stmt)
            self._check_exit(st, stmt, "raises")
            st.terminated = True
            return st
        if isinstance(stmt, ast.If):
            self._handle_calls(stmt.test, st, stmt)
            s1 = self._exec_block(stmt.body, st.copy())
            s2 = self._exec_block(stmt.orelse, st.copy())
            return _State.merge([s1, s2])
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._handle_calls(stmt.iter, st, stmt)
            self._kill_target(stmt.target, st)
            body = self._exec_block(stmt.body, st.copy())
            tail = self._exec_block(stmt.orelse, st.copy()) \
                if stmt.orelse else st.copy()
            return _State.merge([body, tail, st])
        if isinstance(stmt, ast.While):
            self._handle_calls(stmt.test, st, stmt)
            body = self._exec_block(stmt.body, st.copy())
            return _State.merge([body, st])
        if isinstance(stmt, ast.Try):
            entry = st.copy()
            if stmt.finalbody:
                self._finally_stack.append(stmt.finalbody)
            try:
                after = self._exec_block(stmt.body, st)
                if stmt.orelse and not after.terminated:
                    after = self._exec_block(stmt.orelse, after)
                results = [after]
                for handler in stmt.handlers:
                    # Conservative handler entry: the state at try
                    # ENTRY — obligations acquired before the try are
                    # live and the handler owns their unwind.
                    results.append(
                        self._exec_block(handler.body, entry.copy()))
                merged = _State.merge(results)
            finally:
                if stmt.finalbody:
                    self._finally_stack.pop()
            if stmt.finalbody:
                merged = self._exec_block(stmt.finalbody, merged)
            return merged
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._handle_calls(item.context_expr, st, stmt)
            return self._exec_block(stmt.body, st)
        if isinstance(stmt, (ast.Break, ast.Continue)):
            # Loop bodies run once here; treat as end-of-path without
            # an exit check (the loop's merge keeps obligations live).
            st.terminated = True
            return st
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return st  # nested defs are analyzed as their own functions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._handle_calls(child, st, stmt)
        return st

    # ----------------------------------------------------- assignment
    def _handle_assign(self, stmt, st: _State) -> None:
        value = stmt.value
        if value is None:  # bare annotation
            return
        self._handle_calls(value, st, stmt, skip_value_acquire=True)
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]

        # A value-producing acquire assigned to a name creates the
        # obligation on that name.
        acquired = self._value_acquire(value)
        plain_names = [t.id for t in targets if isinstance(t, ast.Name)]
        if acquired is not None and plain_names:
            receiver, verb, line = acquired
            name = plain_names[0]
            self._kill_name(name, st)
            obl = _Obligation((receiver, name, line), receiver, name,
                              verb, line)
            st.held[obl.key] = obl
            st.aliases.setdefault(name, set()).add(obl.key)
            return

        carried: Set[Tuple] = set()
        for node in ast.walk(value):
            if isinstance(node, ast.Name):
                carried |= st.aliases.get(node.id, set())
        for target in targets:
            if isinstance(target, ast.Name):
                # Rebinding a name drops its old aliases, then inherits
                # whatever the RHS carries (``node = tip``).
                self._kill_name(target.id, st)
                if carried:
                    st.aliases.setdefault(target.id, set()).update(carried)
            else:
                # Store into an attribute/subscript: the carried
                # resources now live in longer-lived state — hand-off.
                for key in carried:
                    st.held.pop(key, None)
                # Mutating a path (``sl["private"] = []``) invalidates
                # its released-before record.
                path = unparse(target)
                st.released = {e for e in st.released if e[1] != path}

    def _kill_name(self, name: str, st: _State) -> None:
        st.aliases.pop(name, None)
        st.released = {e for e in st.released if e[2] != name}

    def _kill_target(self, target: ast.expr, st: _State) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                self._kill_name(node.id, st)

    def _value_acquire(self, value: ast.expr) -> Optional[Tuple[str, str,
                                                                int]]:
        if isinstance(value, ast.Call):
            verb = call_name(value)
            recv = receiver_str(value)
            if verb in ACQUIRE_VALUE and recv is not None and (
                    value.args or value.keywords):
                return recv, verb, value.lineno
        return None

    # ---------------------------------------------------------- calls
    def _handle_calls(self, expr: ast.expr, st: _State, stmt: ast.stmt,
                      skip_value_acquire: bool = False) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            verb = call_name(node)
            recv = receiver_str(node)
            if verb in ACQUIRE_ARG and recv is not None and node.args:
                res = node.args[0]
                if isinstance(res, ast.Name):
                    obl = _Obligation((recv, res.id, node.lineno), recv,
                                      res.id, verb, node.lineno)
                    st.held[obl.key] = obl
                    st.aliases.setdefault(res.id, set()).add(obl.key)
                    st.released = {e for e in st.released
                                   if e[2] != res.id}
                continue
            if verb in RELEASE and recv is not None and node.args:
                self._handle_release(node, recv, st)
                continue
            if verb in ACQUIRE_VALUE and recv is not None:
                # Handled at assignment level; a bare-expression
                # acquire (result dropped) is itself a leak.
                if not skip_value_acquire and isinstance(stmt, ast.Expr) \
                        and (node.args or node.keywords):
                    self._emit(
                        node.lineno,
                        f"result of {recv}.{verb}(...) is dropped — the "
                        "acquired resource can never be released")
                continue
            # Any other call a resource-carrying name is passed to is a
            # hand-off (extend/insert/append/submit adopt ownership).
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        for key in st.aliases.get(sub.id, set()):
                            st.held.pop(key, None)

    def _handle_release(self, node: ast.Call, recv: str,
                        st: _State) -> None:
        arg = node.args[0]
        names = [n.id for n in ast.walk(arg) if isinstance(n, ast.Name)]
        discharged = False
        for name in names:
            for key in list(st.aliases.get(name, set())):
                if key in st.held and key[0] == recv:
                    st.held.pop(key)
                    st.aliases[name].discard(key)
                    discharged = True
        # Double-release tracking is keyed by the SIMPLE path of the
        # released expression (a bare name, ``sl["row"]``, ``self.x``)
        # — walked sub-names like the ``self`` in ``self._private[i]``
        # must not collide across distinct resources.
        path = self._simple_path(arg)
        if path is None:
            return
        root = path.split(".")[0].split("[")[0]
        entry = (recv, path, root)
        if discharged:
            st.released.add(entry)
            return
        # Nothing held: either releasing state owned elsewhere (fine —
        # park/unwind paths do this constantly) or a second release of
        # a resource this function already released on every path.
        if entry in st.released:
            self._emit(
                node.lineno,
                f"{recv}.{call_name(node)}({path}) releases a resource "
                "already released on this path — refcount underflow "
                "(the r14 adapter double-release class)")
            return
        st.released.add(entry)

    @staticmethod
    def _simple_path(arg: ast.expr) -> Optional[str]:
        """A stable identity string for name/attribute/subscript chains
        with no embedded calls; None for anything fancier."""
        for node in ast.walk(arg):
            if isinstance(node, ast.Call):
                return None
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Subscript)):
            return unparse(arg)
        return None

    # ----------------------------------------------------------- exits
    def _discharge_names(self, expr: ast.expr, st: _State) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name):
                for key in st.aliases.get(node.id, set()):
                    st.held.pop(key, None)

    def _check_exit(self, st: _State, stmt: ast.stmt, how: str) -> None:
        if self._finally_stack:
            # Run enclosing finally bodies (innermost first) on a copy
            # — their releases discharge obligations before the exit
            # actually happens. The stack is cleared while doing so:
            # a return inside a finally must not re-apply it.
            st = st.copy()
            stack, self._finally_stack = self._finally_stack, []
            try:
                for fb in reversed(stack):
                    st = self._exec_block(fb, st)
            finally:
                self._finally_stack = stack
        for obl in st.held.values():
            self._emit(
                stmt.lineno,
                f"{obl.resource} ({obl.receiver}.{obl.verb} at line "
                f"{obl.line}) is still held where the function {how} — "
                "pinned resource escapes without release (the r13 "
                "parked-slice class)")
