"""Rule ``recompile-hazard``: per-request variation must enter traced
program bodies as runtime arrays, never as interpolated Python scalars.

The zero-recompiles-after-warmup contract (pinned by every serving
test via ``pin_zero_recompiles``) holds because the engine stamps ALL
per-request variation — sampling params, adapter ids, grammar masks,
block tables, offsets — into fixed-shape runtime arrays. A traced
body that instead closes over a request/config attribute bakes that
value into the executable: every distinct value is a silent recompile,
which on a serving tick is a multi-second stall.

Detection: functions that are jit-compiled in the module (passed to
``jax.jit`` by name — both arms of an ``a if cond else b`` selector —
or decorated with ``@jax.jit`` / ``@partial(jax.jit, ...)``) must not
read attribute chains rooted at request/config-ish names
(``request``/``req``/``handle``/``cfg``/``config``/``sampling``/
``spec``). Model topology closed over at build time (layer counts,
vocab sizes) is deliberately NOT flagged — it cannot vary per request;
the hazard is the per-request axis only.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    call_name,
    walk_functions,
)

_REQUEST_ROOTS = frozenset({"request", "req", "handle", "cfg", "config",
                            "sampling", "spec"})


def _is_jit_func(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return True
    if isinstance(fn, ast.Name) and fn.id == "jit":
        return True
    return False


def _jitted_names(tree: ast.AST) -> Set[str]:
    """Names of functions passed (by reference) to jax.jit anywhere in
    the module, both arms of conditional selections included."""
    names: Set[str] = set()

    def collect(expr: ast.expr) -> None:
        if isinstance(expr, ast.Name):
            names.add(expr.id)
        elif isinstance(expr, ast.IfExp):
            collect(expr.body)
            collect(expr.orelse)
        elif isinstance(expr, ast.Call) and call_name(expr) == "partial" \
                and expr.args:
            # jax.jit(partial(fn, cfg)) still traces fn's body.
            collect(expr.args[0])

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_func(node) and node.args:
            collect(node.args[0])
    return names


def _has_jit_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            if _is_jit_func(dec):
                return True
            if call_name(dec) == "partial" and dec.args \
                    and isinstance(dec.args[0], (ast.Attribute, ast.Name)):
                first = dec.args[0]
                attr = first.attr if isinstance(first, ast.Attribute) \
                    else first.id
                if attr == "jit":
                    return True
        elif isinstance(dec, (ast.Attribute, ast.Name)):
            attr = dec.attr if isinstance(dec, ast.Attribute) else dec.id
            if attr == "jit":
                return True
    return False


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    doc = ("traced program bodies must not interpolate request/config "
           "attributes as Python scalars — stamp runtime arrays")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            jitted = _jitted_names(module.tree)
            for fn in walk_functions(module.tree):
                if fn.name in jitted or _has_jit_decorator(fn):
                    yield from self._check_body(module, fn)

    def _check_body(self, module: Module, fn: ast.FunctionDef) -> Iterable:
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Attribute):
                continue
            if not isinstance(node.ctx, ast.Load):
                continue
            root = self._root_name(node)
            if root is None or root in params:
                # Arguments are traced values — attribute access on
                # them is array access, not interpolation.
                continue
            if root.lower() in _REQUEST_ROOTS:
                yield self.finding(
                    module, node.lineno,
                    f"traced body `{fn.name}` reads `{root}.{node.attr}` "
                    "from its closure — a per-request Python scalar "
                    "baked into the trace recompiles on every distinct "
                    "value; pass it as a runtime array argument instead")

    @staticmethod
    def _root_name(node: ast.Attribute) -> Optional[str]:
        base = node.value
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            base = base.value
        return base.id if isinstance(base, ast.Name) else None
