"""Rule ``role-vocab``: the disaggregation-era control-plane
vocabularies — journal record kinds, route/via labels, and replica
roles — must agree across the modules that mint and consume them.

ISSUE 17 split the fleet into roles (``prefill``/``decode``/
``unified``) and added a new WAL record kind (``handoff``). Each of
those vocabularies now lives in two places by construction: the
router mints route labels the journal's forensic reader must
classify (``VIA_LABELS``), the journal's recovery fold dispatches on
the ``"rec"`` kinds its encoders emit (``RECORD_KINDS``), and the
worker entrypoint validates the role string the replica driver
declares (``ROLES``, authoritative in ``serve/fleet/disagg.py``).
A label or kind minted on one side and missing on the other is a
binding the reader silently cannot classify — the same
vocabulary-drift class ``site-vocab`` closes for fault sites.

Checked:

- in a module declaring ``RECORD_KINDS``: every ``"rec"`` literal an
  ``encode*`` function emits is listed, and every listed kind is
  emitted by some encoder (no stale kinds);
- in a module declaring ``ROUTE_LABELS``: every label appears in the
  paired journal module's ``VIA_LABELS``;
- literal ``via`` arguments at ``encode_route(...)`` call sites
  appear in ``VIA_LABELS``;
- a module declaring a ``ROLES`` mirror (``worker.py``) matches the
  authoritative ``ROLES`` in ``disagg.py`` exactly.

Pairing: a module declaring ``VIA_LABELS`` itself is self-paired
(test fixtures); otherwise the path maps below (router → journal,
worker → disagg), resolved through the project so fixtures can
shadow them.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    call_name,
    const_str_tuple,
)

# Label-minting module -> the journal module declaring VIA_LABELS.
ROUTER_JOURNAL_PAIRS = (
    ("pddl_tpu/serve/fleet/router.py", "pddl_tpu/serve/fleet/journal.py"),
)

# ROLES mirror -> the authoritative ROLES declaration.
ROLES_PAIRS = (
    ("pddl_tpu/serve/fleet/worker.py", "pddl_tpu/serve/fleet/disagg.py"),
)


def _module_const(tree: ast.AST,
                  name: str) -> Optional[Tuple[List[str], int]]:
    """A module-level ``NAME = ("a", "b", ...)`` string tuple:
    ``(values, line)``, or None."""
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == name:
                vals = const_str_tuple(node.value)
                if vals is not None:
                    return vals, node.lineno
    return None


def _emitted_rec_kinds(tree: ast.AST) -> List[Tuple[str, int]]:
    """Every literal ``"rec": "<kind>"`` a ``*encode*`` function
    emits: ``(kind, line)``."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and "encode" in node.name):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Dict):
                continue
            for key, value in zip(sub.keys, sub.values):
                if isinstance(key, ast.Constant) and key.value == "rec" \
                        and isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    out.append((value.value, value.lineno))
    return out


def _route_call_vias(tree: ast.AST) -> List[Tuple[str, int]]:
    """Literal ``via`` arguments at ``encode_route(...)`` call sites
    (third positional or ``via=`` keyword)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) == "encode_route"):
            continue
        arg: Optional[ast.expr] = None
        if len(node.args) >= 3:
            arg = node.args[2]
        else:
            for kw in node.keywords:
                if kw.arg == "via":
                    arg = kw.value
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


class RoleVocabRule(Rule):
    name = "role-vocab"
    doc = ("journal RECORD_KINDS, router ROUTE_LABELS/via literals, "
           "and replica ROLES must agree across their declaring "
           "modules")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            yield from self._check_record_kinds(module)
            yield from self._check_route_labels(project, module)
            yield from self._check_roles(project, module)

    # --------------------------------------------------- record kinds
    def _check_record_kinds(self, module: Module) -> Iterable:
        declared = _module_const(module.tree, "RECORD_KINDS")
        if declared is None:
            return
        kinds, kinds_line = declared
        emitted = _emitted_rec_kinds(module.tree)
        for kind, line in emitted:
            if kind not in kinds:
                yield self.finding(
                    module, line,
                    f"encoder emits record kind {kind!r} that "
                    "RECORD_KINDS does not declare — recovery's fold "
                    "has no reader-side decision for it (rebuild vs "
                    "audit-only)")
        emitted_set = {k for k, _ in emitted}
        for kind in kinds:
            if kind not in emitted_set:
                yield self.finding(
                    module, kinds_line,
                    f"RECORD_KINDS entry {kind!r} is emitted by no "
                    "encoder — stale vocabulary lying about the wire")

    # --------------------------------------------------- route labels
    def _via_labels(self, project: Project,
                    module: Module) -> Optional[Tuple[List[str],
                                                      Module, int]]:
        own = _module_const(module.tree, "VIA_LABELS")
        if own is not None:
            return own[0], module, own[1]
        for left, right in ROUTER_JOURNAL_PAIRS:
            if module.rel.endswith(left):
                journal_mod = project.module_by_suffix(right)
                if journal_mod is None:
                    return None
                paired = _module_const(journal_mod.tree, "VIA_LABELS")
                if paired is not None:
                    return paired[0], journal_mod, paired[1]
        return None

    def _check_route_labels(self, project: Project,
                            module: Module) -> Iterable:
        labels = _module_const(module.tree, "ROUTE_LABELS")
        vias = self._via_labels(project, module)
        if labels is not None and vias is not None:
            label_vals, labels_line = labels
            via_vals, via_mod, via_line = vias
            for label in label_vals:
                if label not in via_vals:
                    yield self.finding(
                        module, labels_line,
                        f"ROUTE_LABELS entry {label!r} is missing "
                        f"from VIA_LABELS ({via_mod.rel}:{via_line}) "
                        "— a route record the forensic reader cannot "
                        "classify")
        if vias is not None:
            via_vals = vias[0]
            for via, line in _route_call_vias(module.tree):
                if via not in via_vals:
                    yield self.finding(
                        module, line,
                        f"encode_route called with via={via!r}, which "
                        "VIA_LABELS does not declare — an "
                        "unclassifiable binding provenance")

    # ---------------------------------------------------------- roles
    def _check_roles(self, project: Project, module: Module) -> Iterable:
        mirror = _module_const(module.tree, "ROLES")
        if mirror is None:
            return
        for left, right in ROLES_PAIRS:
            if not module.rel.endswith(left):
                continue
            auth_mod = project.module_by_suffix(right)
            if auth_mod is None:
                continue
            auth = _module_const(auth_mod.tree, "ROLES")
            if auth is None:
                continue
            mirror_vals, mirror_line = mirror
            auth_vals, auth_line = auth
            if set(mirror_vals) != set(auth_vals):
                extra = sorted(set(mirror_vals) - set(auth_vals))
                missing = sorted(set(auth_vals) - set(mirror_vals))
                detail = []
                if extra:
                    detail.append(f"declares unknown roles {extra}")
                if missing:
                    detail.append(f"is missing roles {missing}")
                yield self.finding(
                    module, mirror_line,
                    f"ROLES mirror disagrees with the authoritative "
                    f"vocabulary ({auth_mod.rel}:{auth_line}): "
                    f"{'; '.join(detail)} — the worker would "
                    "accept/reject roles the fleet does not")
