"""Rule ``site-vocab``: one site-name vocabulary per engine —
``_device_call`` literals, ``compile_counts()`` keys, and the paired
``FaultPlan.SITES`` tuple must agree.

The fault-injection machinery (utils/faults.py) validates every
scheduled fault coordinate against ``FaultPlan.SITES`` "so a typo'd
coordinate cannot silently never fire" — but nothing validated SITES
itself against the engine it describes. A site added to the engine
(a new compiled program + ``_device_call`` boundary) that never lands
in the faults vocabulary is a device-call path chaos testing can
never reach; a stale SITES entry is a vocabulary lying about the
engine. (Found on the first run of this rule: ``adapter_load`` —
added in r14 — was dispatchable and counted but missing from
``serve/faults.py`` SITES, so no chaos profile could target the
adapter-load path.)

Checked per engine module:

- every literal first argument of a ``_device_call(...)`` appears in
  the module's ``compile_counts()`` key set;
- every ``compile_counts()`` key appears in the paired faults module's
  ``SITES`` tuple;
- every ``SITES`` entry appears in ``compile_counts()`` keys.

Pairing: a module containing both ``_device_call`` sites and a
``SITES`` class is self-paired (test fixtures); otherwise the
``ENGINE_FAULTS_PAIRS`` path map below (engine → faults module).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    call_name,
    const_str_tuple,
    string_keys,
)

# Engine module -> its faults-vocabulary module (repo-relative path
# suffixes; resolved through the project so fixtures can shadow them).
ENGINE_FAULTS_PAIRS = (
    ("pddl_tpu/serve/engine.py", "pddl_tpu/serve/faults.py"),
    ("pddl_tpu/train/loop.py", "pddl_tpu/train/faults.py"),
)

# Storage-gate module -> its storage-faults vocabulary (ISSUE 18):
# the journal VFS's ``_storage_op`` gate literals, its STORAGE_OPS
# manifest, and ``StorageFaultPlan.SITES`` are one vocabulary — same
# invariant as the device leg, one layer down the stack.
STORAGE_FAULTS_PAIRS = (
    ("pddl_tpu/serve/fleet/journal.py", "pddl_tpu/utils/faults.py",
     "StorageFaultPlan"),
)


def _device_call_sites(tree: ast.AST) -> List[Tuple[str, int]]:
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) == "_device_call" \
                and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                sites.append((first.value, node.lineno))
    return sites


def _compile_counts_keys(tree: ast.AST) -> Optional[Dict[str, int]]:
    """String keys mentioned in the module's ``compile_counts``
    function(s): dict-literal keys, ``counts["x"] = ...`` stores, and
    literal first elements of tuple iterations."""
    keys: Dict[str, int] = {}
    found = False
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == "compile_counts"):
            continue
        found = True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for key, line in string_keys(sub):
                    keys.setdefault(key, line)
            elif isinstance(sub, ast.Assign):
                for target in sub.targets:
                    if isinstance(target, ast.Subscript) \
                            and isinstance(target.slice, ast.Constant) \
                            and isinstance(target.slice.value, str):
                        keys.setdefault(target.slice.value, target.lineno)
            elif isinstance(sub, ast.Tuple) and sub.elts \
                    and isinstance(sub.elts[0], ast.Constant) \
                    and isinstance(sub.elts[0].value, str):
                keys.setdefault(sub.elts[0].value, sub.lineno)
    return keys if found else None


def _sites_tuples(tree: ast.AST) -> List[Tuple[Set[str], int, str]]:
    """Every class-level ``SITES = (...)`` assignment: (values, line,
    class name)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "SITES":
                    vals = const_str_tuple(value)
                    if vals is not None and vals:
                        out.append((set(vals), stmt.lineno, node.name))
    return out


def _storage_op_sites(tree: ast.AST) -> List[Tuple[str, int]]:
    """Literal first arguments of ``_storage_op(...)`` gate calls."""
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) == "_storage_op" \
                and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                sites.append((first.value, node.lineno))
    return sites


def _storage_ops_tuple(tree: ast.AST) -> Optional[Tuple[Set[str], int]]:
    """The module-level ``STORAGE_OPS = (...)`` manifest, if any."""
    for node in ast.walk(tree):
        targets: List[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "STORAGE_OPS":
                vals = const_str_tuple(value)
                if vals is not None and vals:
                    return set(vals), node.lineno
    return None


class SiteVocabRule(Rule):
    name = "site-vocab"
    doc = ("_device_call sites, compile_counts() keys, and the paired "
           "FaultPlan.SITES must be one vocabulary")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            counts = _compile_counts_keys(module.tree)
            if counts is None:
                continue
            sites = _device_call_sites(module.tree)
            if not sites and not counts:
                continue
            # Every dispatched literal site must be a counted program.
            for site, line in sites:
                if site not in counts:
                    yield self.finding(
                        module, line,
                        f"_device_call site {site!r} is not a "
                        "compile_counts() key — the dispatch is "
                        "invisible to the zero-recompile pin and "
                        "untargetable by chaos")
            vocab = self._paired_vocab(project, module)
            if vocab is None:
                continue
            sites_set, faults_mod, vocab_line, cls = vocab
            for key, line in sorted(counts.items()):
                if key not in sites_set:
                    yield self.finding(
                        module, line,
                        f"compile_counts() key {key!r} is missing from "
                        f"{cls}.SITES ({faults_mod.rel}:{vocab_line}) — "
                        "no fault profile can target this device-call "
                        "site")
            for site in sorted(sites_set - set(counts)):
                yield self.finding(
                    faults_mod, vocab_line,
                    f"{cls}.SITES entry {site!r} matches no "
                    f"compile_counts() key of {module.rel} — stale "
                    "vocabulary")
        yield from self._run_storage(project)

    def _run_storage(self, project: Project) -> Iterable:
        """The storage leg (ISSUE 18): ``_storage_op`` gate literals,
        the STORAGE_OPS manifest, and the paired
        ``StorageFaultPlan.SITES`` must be one vocabulary."""
        for module in project.modules:
            ops = _storage_ops_tuple(module.tree)
            gates = _storage_op_sites(module.tree)
            if ops is None or not gates:
                continue
            ops_set, ops_line = ops
            for op, line in gates:
                if op not in ops_set:
                    yield self.finding(
                        module, line,
                        f"_storage_op gate {op!r} is not in the "
                        "STORAGE_OPS manifest — the VFS dispatches an "
                        "op no storage-fault profile can target")
            gated = {op for op, _ in gates}
            for op in sorted(ops_set - gated):
                yield self.finding(
                    module, ops_line,
                    f"STORAGE_OPS entry {op!r} matches no _storage_op "
                    "gate — stale manifest")
            vocab = self._paired_storage_vocab(project, module)
            if vocab is None:
                continue
            sites_set, faults_mod, vocab_line, cls = vocab
            for op in sorted(ops_set - sites_set):
                yield self.finding(
                    module, ops_line,
                    f"STORAGE_OPS entry {op!r} is missing from "
                    f"{cls}.SITES ({faults_mod.rel}:{vocab_line}) — the "
                    "plan's schedule validation would reject a "
                    "coordinate the journal actually gates")
            for op in sorted(sites_set - ops_set):
                yield self.finding(
                    faults_mod, vocab_line,
                    f"{cls}.SITES entry {op!r} matches no STORAGE_OPS "
                    f"entry of {module.rel} — stale vocabulary")

    def _paired_storage_vocab(self, project: Project, module: Module):
        own = [t for t in _sites_tuples(module.tree)
               if t[2] == "StorageFaultPlan"]
        if own:
            vals, line, cls = own[0]
            return vals, module, line, cls
        for gate_suffix, faults_suffix, cls_name in STORAGE_FAULTS_PAIRS:
            if module.rel.endswith(gate_suffix):
                faults_mod = project.module_by_suffix(faults_suffix)
                if faults_mod is None:
                    return None
                tuples = [t for t in _sites_tuples(faults_mod.tree)
                          if t[2] == cls_name]
                if not tuples:
                    return None
                vals, line, cls = tuples[0]
                return vals, faults_mod, line, cls
        return None

    def _paired_vocab(self, project: Project, module: Module):
        own = _sites_tuples(module.tree)
        if own:
            vals, line, cls = own[0]
            return vals, module, line, cls
        for engine_suffix, faults_suffix in ENGINE_FAULTS_PAIRS:
            if module.rel.endswith(engine_suffix):
                faults_mod = project.module_by_suffix(faults_suffix)
                if faults_mod is None:
                    return None
                tuples = _sites_tuples(faults_mod.tree)
                if not tuples:
                    return None
                vals, line, cls = tuples[0]
                return vals, faults_mod, line, cls
        return None
