"""Rule ``snapshot-hygiene``: wire-format keys may only change with a
version bump, and bench-artifact headline keys must have a direction
in the ``bench_artifact`` vocabulary.

**(a) versioned wire manifests.** ``serve/drain.py`` owns the serving
snapshot wire format; r12 (priority), r13 (block tables) and r14
(adapter/constraint) each changed the entry shape WITH a version bump
plus forward/backward-compat pins. The failure mode this rule closes:
a key added or renamed without the bump — every restoring engine
happily reads the versioned header, then mis-decodes the entries.
Mechanism: the module must carry a literal manifest named
``ENTRY_KEYS_V{SNAPSHOT_VERSION}`` matching the keys its encode
functions actually emit (dict-literal keys plus ``entry["k"] = ...``
stores in ``encode*``-named functions). Changing the encoder without
updating the manifest fails; updating the manifest forces its name —
and therefore ``SNAPSHOT_VERSION`` — through review. The SAME
discipline covers the control-plane WAL (ISSUE 14):
``serve/fleet/journal.py`` carries ``RECORD_KEYS_V{JOURNAL_VERSION}``
pinned against its record encoders — a record-shape change without a
``JOURNAL_VERSION`` bump fails the tree, because a recovering router
mis-decoding its own log is the quietest way to lose requests.

**(b) bench-artifact direction vocabulary.** The perf gate
(``utils/bench_artifact.compare``) only guards keys it can assign a
direction; a headline metric whose name matches no vocabulary rule
silently exits the gate (the "quietest regression" the r11 review
called out for vanished leaves — this is the same hole for NEW
leaves). Every committed artifact leaf that is headline-shaped (ends
in ``_x``, or a ``*tok_s``/``*tokens_per_s`` rate) must get a nonzero
direction from the vocabulary parsed out of ``bench_artifact.py``
(``_HIGHER_BETTER``/``_LOWER_BETTER``/``_NEVER`` — AST-extracted, no
import).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    const_str_tuple,
    string_keys,
)

BENCH_VOCAB_SUFFIX = "pddl_tpu/utils/bench_artifact.py"
_HEADLINE_RE = re.compile(r"(_x$|tok_s$|tokens_per_s$)")

# The versioned-manifest families this rule enforces: (version
# constant, manifest prefix). serve/drain.py carries SNAPSHOT_VERSION
# + ENTRY_KEYS_V<n>; serve/fleet/journal.py carries JOURNAL_VERSION +
# RECORD_KEYS_V<n> (ISSUE 14) — one mechanism, two wire formats.
_MANIFEST_FAMILIES = (
    ("SNAPSHOT_VERSION", "ENTRY_KEYS_V"),
    ("JOURNAL_VERSION", "RECORD_KEYS_V"),
)


class SnapshotHygieneRule(Rule):
    name = "snapshot-hygiene"
    doc = ("snapshot/journal wire keys change only with a version "
           "bump; artifact headline keys need a gate direction")

    def __init__(self, artifacts_root: Optional[str] = None):
        # Injectable for tests; default: the repo's committed series.
        self._artifacts_root = artifacts_root

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            for version_name, prefix in _MANIFEST_FAMILIES:
                yield from self._check_manifest(module, version_name,
                                                prefix)
        yield from self._check_artifacts(project)

    # ----------------------------------------------- entry manifests
    def _check_manifest(self, module: Module, version_name: str,
                        prefix: str) -> Iterable:
        manifest_re = re.compile("^" + re.escape(prefix) + r"(\d+)$")
        version: Optional[Tuple[int, int]] = None    # (value, line)
        manifests: List[Tuple[int, List[str], int]] = []  # (v, keys, line)
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not isinstance(target, ast.Name):
                    continue
                if target.id == version_name \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int):
                    version = (node.value.value, node.lineno)
                m = manifest_re.match(target.id)
                if m:
                    keys = const_str_tuple(node.value)
                    if keys is not None:
                        manifests.append((int(m.group(1)), keys,
                                          node.lineno))
        if version is None:
            return
        vnum, vline = version
        current = [m for m in manifests if m[0] == vnum]
        if not current:
            yield self.finding(
                module, vline,
                f"{version_name} is {vnum} but no {prefix}{vnum} "
                "manifest exists — the wire format is unreviewable; "
                "declare the key manifest next to the version")
            return
        _, declared, mline = current[0]
        # A helper named encode_<key> for a DECLARED entry key is a
        # nested sub-encoder (its dict keys live under that entry key,
        # e.g. encode_sampling/encode_spec) — only the entry-level
        # encoders define the wire manifest.
        sub_encoders = {f"encode_{k}" for k in declared}
        encoded = self._encoded_keys(module.tree, sub_encoders)
        if encoded is None:
            return
        actual = set(encoded)
        if set(declared) != actual:
            added = sorted(actual - set(declared))
            removed = sorted(set(declared) - actual)
            detail = []
            if added:
                detail.append(f"encoder emits undeclared {added}")
            if removed:
                detail.append(f"manifest declares unemitted {removed}")
            yield self.finding(
                module, mline,
                f"wire keys changed without a {version_name} bump: "
                f"{'; '.join(detail)} — bump the version, rename the "
                f"manifest to {prefix}{vnum + 1}, and extend the "
                "compat pins")

    @staticmethod
    def _encoded_keys(tree: ast.AST,
                      sub_encoders: Set[str]) -> Optional[Set[str]]:
        """Keys the encode path emits: dict-literal keys in functions
        named ``*encode*`` plus ``entry["k"] = ...`` stores there.
        ``sub_encoders`` (``encode_<declared key>`` helpers) are
        skipped — their dicts nest under an entry key."""
        keys: Set[str] = set()
        found = False
        for node in ast.walk(tree):
            if not (isinstance(node, ast.FunctionDef)
                    and "encode" in node.name
                    and node.name not in sub_encoders):
                continue
            found = True
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for k, _ in string_keys(sub):
                        keys.add(k)
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.slice, ast.Constant) \
                                and isinstance(t.slice.value, str):
                            keys.add(t.slice.value)
        return keys if found else None

    # --------------------------------------------- artifact headlines
    def _check_artifacts(self, project: Project) -> Iterable:
        vocab_mod = project.module_by_suffix(BENCH_VOCAB_SUFFIX)
        if vocab_mod is None:
            return
        vocab = self._direction_vocab(vocab_mod.tree)
        if vocab is None:
            return
        higher, lower, never = vocab
        root = self._artifacts_root
        if root is None:
            root = os.path.join(project.root, "artifacts")
        if not os.path.isdir(root):
            return
        flagged: Set[str] = set()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            for name in sorted(filenames):
                if not name.endswith(".json"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    with open(path, encoding="utf-8") as f:
                        record = json.load(f)
                except (OSError, ValueError):
                    continue
                rel = os.path.relpath(path, project.root) \
                    if path.startswith(project.root) else path
                for key in self._leaf_keys(record):
                    if not _HEADLINE_RE.search(key):
                        continue
                    # None = a _NEVER match, a deliberate ruling ("not
                    # a headline") — only direction 0 is a vocab GAP.
                    if self._direction(key, higher, lower,
                                       never) != 0:
                        continue
                    if key in flagged:
                        continue
                    flagged.add(key)
                    yield Finding_for_artifact(self, vocab_mod, rel, key)

    @staticmethod
    def _direction_vocab(tree: ast.AST):
        found: Dict[str, List[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id in (
                        "_HIGHER_BETTER", "_LOWER_BETTER", "_NEVER"):
                    vals = const_str_tuple(node.value)
                    if vals is not None:
                        found[target.id] = vals
        if set(found) != {"_HIGHER_BETTER", "_LOWER_BETTER", "_NEVER"}:
            return None
        return (found["_HIGHER_BETTER"], found["_LOWER_BETTER"],
                found["_NEVER"])

    @staticmethod
    def _direction(key: str, higher, lower, never) -> Optional[int]:
        """Mirror of bench_artifact.metric_direction over the
        AST-extracted vocabulary, except a _NEVER match returns None
        (an explicit ruling) rather than 0 (no ruling at all)."""
        k = key.lower()
        if any(m in k for m in never):
            return None
        for m in higher:
            if m in k:
                return 1
        for m in lower:
            if m in k:
                return -1
        return 0

    def _leaf_keys(self, record) -> Iterable[str]:
        if isinstance(record, dict):
            for k, v in record.items():
                if isinstance(v, (dict, list)):
                    yield from self._leaf_keys(v)
                elif isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    yield str(k)
        elif isinstance(record, list):
            for item in record:
                yield from self._leaf_keys(item)


def Finding_for_artifact(rule: SnapshotHygieneRule, vocab_mod: Module,
                         artifact_rel: str, key: str):
    return rule.finding(
        vocab_mod, 1,
        f"artifact {artifact_rel} headline key {key!r} gets no "
        "direction from the bench_artifact vocabulary — the perf gate "
        "silently skips it; extend _HIGHER_BETTER/_LOWER_BETTER (or "
        "_NEVER it with cause)")
