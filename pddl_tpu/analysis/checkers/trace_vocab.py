"""Rule ``trace-vocab``: tracer event-name literals must match the
trace assembler's vocabulary.

ISSUE 19 stitched per-request spans from N worker processes and the
router into one fleet trace, and the assembler's gap checker and
TTFT critical-path attribution (``obs/assemble.py``) dispatch on
EVENT NAMES: ``first_token`` anchors the attribution, ``admitted``
carries the queue wait, ``prefill_chunk`` splits by site,
``handoff``/``handoff_export``/``handoff_import`` prove the
disaggregated hand-off left no gap. An event minted under a name the
assembler does not know is silently invisible to every report — the
stream LOOKS traced, the segment table just quietly misattributes it
— and a vocabulary entry no emitter mints is the assembler promising
coverage that cannot exist. Same drift class ``site-vocab`` and
``role-vocab`` close for fault sites and replica roles.

Checked, for every module declaring a ``TRACE_EVENTS`` tuple
(authoritative: ``pddl_tpu/obs/assemble.py``):

- **forward** — every event-name literal emitted by the declaring
  module or a module pairing to it (the first string-constant
  positional argument at ``event`` / ``_event`` / ``_chain_span`` /
  ``_named`` call sites) is declared in ``TRACE_EVENTS``;
- **reverse** — every ``TRACE_EVENTS`` entry is emitted at some such
  call site (no stale vocabulary).

``_engine_event`` call sites are deliberately NOT collected: the
engine-event stream (retries, fault injections, checkpoints) is a
separate vocabulary the assembler never dispatches on.

Pairing: ``TRACE_PAIRS`` maps emitter modules onto the assembler,
resolved through the project so test fixtures (which declare
``TRACE_EVENTS`` themselves and are thus self-paired) can shadow it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from pddl_tpu.analysis.core import (
    Module,
    Project,
    Rule,
    call_name,
)
from pddl_tpu.analysis.checkers.role_vocab import _module_const

# Emitter module -> the module declaring the authoritative
# TRACE_EVENTS vocabulary it must match.
TRACE_PAIRS = (
    ("pddl_tpu/obs/trace.py", "pddl_tpu/obs/assemble.py"),
    ("pddl_tpu/obs/propagate.py", "pddl_tpu/obs/assemble.py"),
)

# Call names whose first string-constant positional argument is a
# trace event name (Span.event / TraceCollector._event /
# propagate._chain_span / assemble._named).
_EVENT_CALLS = ("event", "_event", "_chain_span", "_named")


def _event_literals(tree: ast.AST) -> List[Tuple[str, int]]:
    """Every event-name literal at a collected call site: the FIRST
    positional argument that is a string constant (the callees place
    the name behind a clock/rid/record argument)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) in _EVENT_CALLS):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                out.append((arg.value, node.lineno))
                break
    return out


class TraceVocabRule(Rule):
    name = "trace-vocab"
    doc = ("tracer event-name literals and the assembler's "
           "TRACE_EVENTS vocabulary must agree — an unknown event is "
           "invisible to gap checks and TTFT attribution, a stale "
           "entry promises coverage no emitter mints")

    def run(self, project: Project) -> Iterable:
        for module in project.modules:
            decl = _module_const(module.tree, "TRACE_EVENTS")
            if decl is None:
                continue
            vocab, vocab_line = decl
            emitters = [module]
            for left, right in TRACE_PAIRS:
                if not module.rel.endswith(right):
                    continue
                paired = project.module_by_suffix(left)
                if paired is not None and paired is not module:
                    emitters.append(paired)
            seen: set = set()
            for emitter in emitters:
                for name, line in _event_literals(emitter.tree):
                    seen.add(name)
                    if name not in vocab:
                        yield self.finding(
                            emitter, line,
                            f"trace event {name!r} is not in "
                            f"TRACE_EVENTS ({module.rel}:{vocab_line})"
                            " — the assembler's gap checker and "
                            "critical-path attribution cannot see it")
            for name in vocab:
                if name not in seen:
                    yield self.finding(
                        module, vocab_line,
                        f"TRACE_EVENTS entry {name!r} is emitted at no "
                        "tracer call site — stale vocabulary promising "
                        "coverage no emitter mints")
