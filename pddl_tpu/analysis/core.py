"""graftlint core: the import-free AST checker framework.

The serving/training stack runs on a handful of load-bearing
conventions — pin-before-allocate with a release on every unwind path,
all per-request variation as runtime arrays, one donated tree per
site, shared site-name vocabularies, strict metrics-exposition parity,
snapshot-version bumps — and every recent review pass caught
violations of exactly these (CHANGES.md r08/r13/r14). This package
machine-checks them at the AST level, RacerD/error-prone-style:

- **no runtime import** of jax (or of any checked module) — rules see
  syntax trees only, so the whole suite runs in well under a second
  and is safe inside every tier-1 test run;
- **per-rule visitor registry** (`pddl_tpu/analysis/checkers/`), each
  rule encoding one repo invariant and documented in
  ``docs/ANALYSIS.md`` next to the incident that motivated it;
- **suppressions**: ``# graftlint: disable=<rule>[,<rule>]`` on the
  flagged line (or the line above), ``# graftlint: disable-file=<rule>``
  anywhere at a line's start for a whole file;
- **baseline** (:func:`load_baseline`): a JSON list of justified
  exceptions keyed by ``(rule, path, symbol)`` with a mandatory
  ``reason`` — the escape hatch for true-but-accepted findings; stale
  entries FAIL the run so the baseline can only shrink honestly.

The CLI lives in ``pddl_tpu/analysis/__main__.py``:
``python -m pddl_tpu.analysis --check pddl_tpu/``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Repo root: the directory that contains the `pddl_tpu` package this
# module is part of.  Cross-file rules (site vocabularies, exposition
# parity, artifact vocab) resolve their companion files against it.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([\w,\- ]+)")
_DISABLE_FILE_RE = re.compile(r"^\s*#\s*graftlint:\s*disable-file=([\w,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, addressable three ways: by ``path:line``
    (the human jump-to), by ``(rule, path, symbol)`` (the baseline
    key — line numbers drift, enclosing-function names rarely do), and
    by the suppression comment on the flagged line."""

    rule: str
    path: str        # repo-root-relative, forward slashes
    line: int        # 1-indexed
    symbol: str      # enclosing def/class qualname, or "<module>"
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}" \
               f" (in {self.symbol})"


class Module:
    """One parsed source file plus the lint-directive index."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set(rule) suppressions; "*" suppresses every rule.
        self.line_disables: Dict[int, set] = {}
        self.file_disables: set = set()
        for i, text in enumerate(self.lines, 1):
            m = _DISABLE_FILE_RE.match(text)
            if m:
                self.file_disables.update(
                    r.strip() for r in m.group(1).split(",") if r.strip())
                continue
            m = _DISABLE_RE.search(text)
            if m:
                self.line_disables[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}
        # Qualname index: line -> enclosing def/class chain.
        self._symbols: List[Tuple[int, int, str]] = []
        self._index_symbols(self.tree, [])

    def _index_symbols(self, node, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = ".".join(stack + [child.name])
                end = getattr(child, "end_lineno", child.lineno)
                self._symbols.append((child.lineno, end, qual))
                self._index_symbols(child, stack + [child.name])
            else:
                self._index_symbols(child, stack)

    def symbol_at(self, line: int) -> str:
        best = "<module>"
        best_span = None
        for lo, hi, qual in self._symbols:
            if lo <= line <= hi:
                span = hi - lo
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables or "*" in self.file_disables:
            return True
        for ln in (line, line - 1):
            rules = self.line_disables.get(ln)
            if rules and (rule in rules or "*" in rules):
                return True
        return False


class Project:
    """The file set one analysis run sees, with lazy cross-file loads.

    ``paths`` (files or directories) define the modules rules iterate;
    :meth:`module_by_suffix` additionally resolves companion files
    (e.g. the faults module paired with an engine) from the scanned set
    first and the repo root second, so the vocabulary rules work both
    on the real tree and on self-contained test fixtures.
    """

    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        self.root = os.path.abspath(root or REPO_ROOT)
        self.errors: List[str] = []
        self._by_rel: Dict[str, Module] = {}
        self._extra: Dict[str, Optional[Module]] = {}
        for p in paths:
            # A path that does not exist must be an ERROR, never a
            # silent zero-file "clean" — the gate's green must mean
            # "analyzed and found nothing", not "found nothing to
            # analyze" (typo'd path, wrong cwd).
            if not os.path.exists(p):
                self.errors.append(f"{p}: no such file or directory")
            elif os.path.isfile(p) and not p.endswith(".py"):
                self.errors.append(
                    f"{p}: not a Python source file (.py)")
        for path in self._expand(paths):
            rel = self._relpath(path)
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                self._by_rel[rel] = Module(path, rel, source)
            except (OSError, SyntaxError) as e:
                self.errors.append(f"{rel}: cannot parse: {e}")
        if not self._by_rel and not self.errors:
            self.errors.append(
                f"no Python files found under {list(paths)!r}")

    def _relpath(self, path: str) -> str:
        path = os.path.abspath(path)
        if path.startswith(self.root + os.sep):
            path = os.path.relpath(path, self.root)
        return path.replace(os.sep, "/")

    @staticmethod
    def _expand(paths: Sequence[str]) -> Iterable[str]:
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d != "__pycache__" and not d.startswith("."))
                    for name in sorted(filenames):
                        if name.endswith(".py"):
                            yield os.path.join(dirpath, name)
            elif p.endswith(".py"):
                yield p

    @property
    def modules(self) -> List[Module]:
        return [self._by_rel[k] for k in sorted(self._by_rel)]

    def module_by_suffix(self, suffix: str) -> Optional[Module]:
        """The scanned module whose relative path ends with ``suffix``,
        else a lazily-parsed load from the repo root, else None."""
        for rel in sorted(self._by_rel):
            if rel.endswith(suffix):
                return self._by_rel[rel]
        if suffix not in self._extra:
            path = os.path.join(self.root, suffix)
            mod = None
            if os.path.isfile(path):
                try:
                    with open(path, encoding="utf-8") as f:
                        mod = Module(path, suffix, f.read())
                except (OSError, SyntaxError) as e:
                    self.errors.append(f"{suffix}: cannot parse: {e}")
            self._extra[suffix] = mod
        return self._extra[suffix]

    def module_for_path(self, rel: str) -> Optional[Module]:
        """The module a finding's path refers to — scanned set first,
        then lazily-loaded companions, so suppression comments work
        identically whether the file was a CLI argument or a
        cross-file resolve."""
        mod = self._by_rel.get(rel)
        if mod is not None:
            return mod
        for extra in self._extra.values():
            if extra is not None and extra.rel == rel:
                return extra
        return None


class Rule:
    """Base class: subclasses set ``name``/``doc`` and implement
    :meth:`run` yielding raw findings; the framework applies
    suppressions and the baseline afterwards."""

    name: str = ""
    doc: str = ""

    def run(self, project: Project) -> Iterable[Finding]:
        raise NotImplementedError

    # Convenience for rules that visit one module at a time.
    def finding(self, module: Module, line: int, message: str) -> Finding:
        return Finding(self.name, module.rel, line,
                       module.symbol_at(line), message)


# --------------------------------------------------------------- baseline

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "graftlint_baseline.json")


def load_baseline(path: Optional[str]) -> List[dict]:
    """The justified-exception list: ``[{rule, path, symbol, reason},
    ...]``. Every entry must carry a non-empty ``reason`` — an
    unexplained baseline is just a disabled checker."""
    if path is None or not os.path.isfile(path):
        return []
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path!r} must be a JSON list")
    seen = set()
    for e in entries:
        for key in ("rule", "path", "symbol", "reason"):
            if not isinstance(e.get(key), str) or not e[key].strip():
                raise ValueError(
                    f"baseline entry {e!r} needs a non-empty {key!r}")
        k = (e["rule"], e["path"], e["symbol"])
        if k in seen:
            # One justification per location; a duplicate would match
            # nothing and masquerade as a stale entry.
            raise ValueError(f"duplicate baseline entry for {k}")
        seen.add(k)
    return entries


def apply_baseline(findings: List[Finding],
                   entries: List[dict],
                   *,
                   analyzed_paths: Optional[set] = None,
                   active_rules: Optional[set] = None
                   ) -> Tuple[List[Finding], List[dict], List[dict]]:
    """Split findings into (kept, used_entries, stale_entries). An
    entry absorbs EVERY finding with its (rule, path, symbol) — one
    justification per code location, not per occurrence.

    Staleness is judged only INSIDE the run's scope: an entry whose
    path was not analyzed this run (``analyzed_paths``) or whose rule
    did not run (``active_rules``) is out of scope — neither used nor
    stale — so a targeted ``--rules``/single-file invocation never
    demands removal of a justified exception it could not re-observe.
    """
    kept: List[Finding] = []
    used = {i: False for i in range(len(entries))}
    index = {}
    for i, e in enumerate(entries):
        index.setdefault((e["rule"], e["path"], e["symbol"]), i)
    for f in findings:
        i = index.get((f.rule, f.path, f.symbol))
        if i is None:
            kept.append(f)
        else:
            used[i] = True
    stale = []
    for i, u in used.items():
        if u:
            continue
        e = entries[i]
        if analyzed_paths is not None and e["path"] not in analyzed_paths:
            continue
        if active_rules is not None and e["rule"] not in active_rules:
            continue
        stale.append(e)
    return kept, [entries[i] for i, u in used.items() if u], stale


# -------------------------------------------------------------------- run


def all_rules() -> List[Rule]:
    from pddl_tpu.analysis.checkers import RULES

    return [cls() for cls in RULES]


def run_analysis(paths: Sequence[str], *,
                 rules: Optional[Sequence[Rule]] = None,
                 root: Optional[str] = None
                 ) -> Tuple[List[Finding], List[str], set]:
    """Run ``rules`` (default: every registered checker) over ``paths``.
    Returns ``(findings, errors, analyzed_paths)`` with suppressions
    already applied — baseline filtering is the caller's second step
    (the CLI's, usually); ``analyzed_paths`` (scanned modules plus
    lazily-resolved companions) scopes the staleness judgment in
    :func:`apply_baseline`."""
    project = Project(paths, root=root)
    findings: List[Finding] = []
    seen = set()
    for rule in (rules if rules is not None else all_rules()):
        for f in rule.run(project):
            if f in seen:  # nested defs can be visited twice
                continue
            seen.add(f)
            mod = project.module_for_path(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    analyzed = set(project._by_rel) | {
        m.rel for m in project._extra.values() if m is not None}
    return findings, project.errors, analyzed


# ------------------------------------------------------------ AST helpers
# Shared by the checkers; kept here so each rule file stays about its
# invariant, not about AST plumbing.


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - very old py
        return "<expr>"


def call_name(node: ast.Call) -> Optional[str]:
    """The called attribute/function name: ``x.y.pin(...)`` -> "pin",
    ``jit(...)`` -> "jit"."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def receiver_str(node: ast.Call) -> Optional[str]:
    """The dotted receiver of a method call: ``self._prefix.pin(n)`` ->
    "self._prefix"; None for bare-name calls."""
    if isinstance(node.func, ast.Attribute):
        return unparse(node.func.value)
    return None


def string_keys(d: ast.Dict) -> List[Tuple[str, int]]:
    out = []
    for k in d.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.append((k.value, k.lineno))
    return out


def const_str_tuple(node: ast.AST) -> Optional[List[str]]:
    """A tuple/list/set literal of string constants, or None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            vals.append(elt.value)
        return vals
    if isinstance(node, ast.Call) and call_name(node) == "frozenset" \
            and node.args:
        return const_str_tuple(node.args[0])
    return None


def walk_functions(tree: ast.AST):
    """Every FunctionDef in the module, nested ones included."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            yield node
