"""Multi-plane chaos campaigns (ISSUE 18).

One fault plane per test file proves each recovery path in isolation;
production failures arrive COMPOSED — a wire storm while a disk
degrades while a replica grays out while the scheduler SIGKILLs the
router. `conductor.py` turns the repo's fault planes (device
`FaultPlan` sites, `WireFaultPlan`, `StorageFaultPlan`, gray
slow-walls, hard kills, router crash+recover) into seeded randomized
campaigns against a full fleet, with an invariant referee after every
run.
"""

from pddl_tpu.chaos.conductor import (CampaignReport, ChaosAction,
                                      ChaosConductor, ReplicaChaos,
                                      local_kill)

__all__ = ["CampaignReport", "ChaosAction", "ChaosConductor",
           "ReplicaChaos", "local_kill"]
