"""Seeded multi-plane chaos conductor + invariant referee.

Every robustness round so far exercised ONE fault plane per test:
device faults (r08/r10), replica death (r11), wire storms (r14),
router SIGKILL (r19), disk failure (this round). Jepsen-style
campaign testing and the gray-failure literature (Huang et al.,
HotOS '17) both make the same argument: real incidents COMPOSE — and
a recovery path that survives each plane alone can still deadlock,
double-free, or silently lose a stream when two planes overlap.

:class:`ChaosConductor` owns that composition. From one seed it draws
a randomized schedule of :class:`ChaosAction` coordinates — hard
kills, gray slow-wall spans, storage-fault storms, a router crash, a
primary/standby PARTITION (ISSUE 20: the primary goes silent but stays
alive and keeps trying to command after the standby promotes — the
split-brain mode, distinct from kill) — fires them against a live
fleet while the passive planes (device fault-plan rates, wire
fault-plan rates) run underneath, then settles the workload and runs
the INVARIANT REFEREE:

- **acked_terminal** — every acked stream reached a terminal state;
- **token_exact** — every finished stream matches the greedy oracle
  token-for-token (survivors, migrants, and revived streams alike);
- **zero_recompiles** — no survivor's engine compiled anything twice
  (recovery must ride warm executables, the repo's north-star rule);
- **pins_balanced** — every reachable radix/prefix refcount returned
  to zero (no stream leaked a pin through a mid-flight death);
- **recover_idempotent** — :func:`~pddl_tpu.serve.fleet.journal.
  read_state` over the WAL directory is bit-stable across two reads
  (recovery is a pure fold, running it twice changes nothing);
- **recovery_bounded** — the router crash+recover cycle, when the
  campaign includes one, completed within ``recovery_bound_s``;
- **exposition_round_trip** — the surviving fleet's Prometheus
  exposition still parses under the strict referee;
- **trace_complete** — with distributed tracing armed
  (``router_kw=dict(dtrace=True)``), every acked stream's stitched
  fleet trace is gap-free across kills, migrations, and hand-offs
  (`pddl_tpu.obs.assemble`); auto-skipped when tracing is off;
- **single_writer** — with the ``partition`` plane armed, no two
  routers' commands are accepted in the same epoch interval: every
  command the deposed primary attempted after the standby promoted
  was refused by epoch fencing (typed reject, counted); auto-skipped
  when the plane did not fire.

The conductor is deliberately duck-typed over fleets: the caller
supplies replica factories, per-replica :class:`ReplicaChaos` handles
(which knobs exist on a local vs process replica differs), the oracle,
and router policy; the conductor supplies the schedule, the drive
loop, the crash/recover choreography, and the referee. The same seed
against the same factories replays the same campaign — a failing
campaign is a reproducible bug report, not a flake.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.fleet.journal import RouterJournal
from pddl_tpu.serve.fleet.replica import EpochFenced
from pddl_tpu.serve.fleet.router import FleetRouter
from pddl_tpu.serve.fleet.standby import (HotStandby, Lease, LeaseKeeper,
                                          WalShipper)
from pddl_tpu.utils.faults import FaultKind


def local_kill(plan) -> None:
    """Schedule a hard KILL at an in-process replica's next engine
    tick — the :class:`~pddl_tpu.utils.faults.FaultPlan` analog of
    SIGKILLing a worker process."""
    step = max(plan.step_idx + 1, 0)
    plan._sched.setdefault((step, "tick"), []).append(FaultKind.KILL)


@dataclasses.dataclass
class ReplicaChaos:
    """One replica's chaos surface — whichever knobs its driver type
    actually has. ``plan`` (device FaultPlan) and ``wire_plan`` are
    PASSIVE planes: armed at construction, they fire by their own
    seeded rates while the campaign runs. ``slow_fn(delay_s)`` turns
    the gray slow-wall on (``0.0`` turns it off); ``kill_fn()`` is the
    un-drainable hard death."""

    replica_id: int
    plan: Optional[object] = None
    wire_plan: Optional[object] = None
    slow_fn: Optional[Callable[[float], None]] = None
    kill_fn: Optional[Callable[[], None]] = None


@dataclasses.dataclass(frozen=True)
class ChaosAction:
    """One scheduled campaign event: at drive-loop step ``step``, do
    ``kind`` (``kill`` / ``slow_on`` / ``slow_off`` / ``storm_on`` /
    ``storm_off`` / ``router_crash`` / ``partition``) to
    ``replica_id`` (fleet-wide actions carry None)."""

    step: int
    kind: str
    replica_id: Optional[int] = None
    value: Optional[float] = None


@dataclasses.dataclass
class CampaignReport:
    """What one campaign did and what the referee concluded."""

    seed: int
    planes: Tuple[str, ...]
    actions: List[ChaosAction]
    steps: int
    wall_s: float
    recovery_s: Optional[float]
    injected: Dict[str, int]
    invariants: Dict[str, bool]
    violations: List[str]
    skipped: List[str]
    failover_s: Optional[float] = None  # partition plane: silence ->
    #                                     promoted standby serving

    @property
    def ok(self) -> bool:
        return all(self.invariants.values())


def _state_fingerprint(journal_dir: str) -> str:
    """Canonical JSON of the WAL fold — two reads of an untouched
    directory must produce identical bytes (recover() idempotence)."""
    entries, next_rid = journal_io.read_state(journal_dir)
    return json.dumps(
        {"next_rid": next_rid,
         "entries": [[rid, entries[rid]] for rid in sorted(entries)]},
        sort_keys=True, separators=(",", ":"))


def _fold_injected(chaos: Sequence[ReplicaChaos],
                   acc: Dict[str, int]) -> None:
    """Accumulate passive-plane injection counts out of a chaos
    surface — called before the surface is replaced at a router crash,
    so pre-crash wire/device injections survive into the report."""
    for c in chaos:
        if c.plan is not None:
            acc["device"] = acc.get("device", 0) + int(
                getattr(c.plan, "total_injected", 0))
        if c.wire_plan is not None:
            acc["wire"] = acc.get("wire", 0) + int(
                getattr(c.wire_plan, "total_injected", 0))


def _pins_balanced(fleet) -> Tuple[bool, List[str]]:
    """Every reachable in-process prefix index back at refcount zero
    (process replicas keep their pools behind the pipe — their engines
    check the same invariant under their own tests)."""
    bad: List[str] = []
    for slot in getattr(fleet, "replicas", []):
        engine = getattr(slot.driver, "engine", None)
        prefix = getattr(engine, "_prefix", None)
        if prefix is None:
            continue
        stack = [prefix._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not prefix._root and node.ref != 0:
                bad.append(f"replica {slot.replica_id}: block "
                           f"{node.block_id} ref={node.ref}")
    return (not bad), bad


class ChaosConductor:
    """Seeded campaign engine over a fleet factory.

    Args:
      make_replicas: ``fn() -> list[driver]`` — FRESH replica drivers
        (called once to build the fleet, again for crash recovery).
      make_chaos: ``fn(fleet) -> list[ReplicaChaos]`` — the chaos
        surface for the CURRENT fleet's replicas.
      oracle: ``fn(prompt, max_new_tokens) -> list[int]`` — the greedy
        reference the token-exact invariant compares against.
      journal_dir: WAL directory; arms the journal + the router-crash
        plane + the recover-idempotence referee. ``None`` = no WAL.
      storage_plan: the :class:`~pddl_tpu.utils.faults.
        StorageFaultPlan` shared with the journal (the conductor
        drives its storm spans); ``None`` disables the storage plane.
      router_kw / journal_kw: policy forwarded to every
        :class:`FleetRouter` / :class:`RouterJournal` built here.
      recovery_bound_s: the bounded-recovery invariant's ceiling.
      seed: campaign PRNG seed — same seed, same schedule.
    """

    def __init__(self, make_replicas, make_chaos, oracle, *,
                 journal_dir: Optional[str] = None,
                 storage_plan=None,
                 router_kw: Optional[Dict] = None,
                 journal_kw: Optional[Dict] = None,
                 recovery_bound_s: float = 60.0,
                 seed: int = 0):
        self._make_replicas = make_replicas
        self._make_chaos = make_chaos
        self._oracle = oracle
        self.journal_dir = journal_dir
        self.storage_plan = storage_plan
        self._router_kw = dict(router_kw or {})
        self._journal_kw = dict(journal_kw or {})
        self.recovery_bound_s = float(recovery_bound_s)
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------ schedule
    def _draw_schedule(self, planes: Sequence[str], horizon: int,
                       chaos: List[ReplicaChaos], *, kills: int,
                       slow_delay_s: float,
                       storm_rate: float) -> List[ChaosAction]:
        rng = self._rng
        actions: List[ChaosAction] = []
        lo, hi = 2, max(3, int(horizon * 0.6))
        # Span-shaped planes (gray, storage storm) start EARLY so they
        # overlap live traffic — a storm over a drained fleet touches
        # no disk ops and proves nothing.
        early_hi = max(lo + 1, horizon // 4)
        if "kill" in planes:
            victims = [c for c in chaos if c.kill_fn is not None]
            for _ in range(min(kills, len(victims))):
                victim = victims[int(rng.integers(len(victims)))]
                actions.append(ChaosAction(int(rng.integers(lo, hi)),
                                           "kill", victim.replica_id))
        if "gray" in planes:
            slowables = [c for c in chaos if c.slow_fn is not None]
            if slowables:
                victim = slowables[int(rng.integers(len(slowables)))]
                start = int(rng.integers(lo, early_hi))
                span = int(rng.integers(4, max(5, horizon // 3)))
                actions.append(ChaosAction(start, "slow_on",
                                           victim.replica_id,
                                           slow_delay_s))
                actions.append(ChaosAction(start + span, "slow_off",
                                           victim.replica_id, 0.0))
        if "storage" in planes and self.storage_plan is not None:
            start = int(rng.integers(lo, early_hi))
            span = int(rng.integers(4, max(5, horizon // 3)))
            actions.append(ChaosAction(start, "storm_on", None,
                                       storm_rate))
            actions.append(ChaosAction(start + span, "storm_off"))
        if "router" in planes and self.journal_dir is not None:
            # After the mid-campaign window so the crash lands on a
            # fleet already carrying composed damage.
            actions.append(ChaosAction(int(rng.integers(hi, horizon)),
                                       "router_crash"))
        if "partition" in planes and self.journal_dir is not None:
            # Early-mid window, strictly BEFORE the router-crash
            # window: the partition's promoted standby is the router
            # the crash plane then gets to SIGKILL — the planes
            # compose instead of fighting over one takeover.
            actions.append(ChaosAction(int(rng.integers(lo, hi)),
                                       "partition"))
        actions.sort(key=lambda a: (a.step, a.kind))
        return actions

    # --------------------------------------------------------------- build
    def _build_journal(self) -> Optional[RouterJournal]:
        if self.journal_dir is None:
            return None
        return RouterJournal(self.journal_dir,
                             storage_plan=self.storage_plan,
                             **self._journal_kw)

    def _arm_ha(self, fleet, lease_ttl_s: float) -> Dict[str, object]:
        """The partition plane's precondition: a lease-armed primary
        (epoch stamped on every worker-bound command) with a hot
        standby tailing its WAL over the framed transport."""
        lease = Lease(os.path.join(self.journal_dir, "ha_lease.json"),
                      ttl_s=lease_ttl_s)
        keeper = LeaseKeeper(lease, "primary", seed=self.seed)
        fleet.set_epoch(keeper.acquire())
        standby = HotStandby(
            self.journal_dir, [s.driver for s in fleet.replicas],
            lease=lease, holder="standby", seed=self.seed + 1,
            router_kw=self._router_kw,
            journal_kw={"storage_plan": self.storage_plan,
                        **self._journal_kw})
        shipper = WalShipper(fleet._journal, standby.feed)
        standby.attach(shipper)
        return {"lease": lease, "keeper": keeper, "standby": standby,
                "shipper": shipper, "partitioned": False,
                "promoted": False, "probes_attempted": 0,
                "probes_refused": 0, "counted": 0}

    # ----------------------------------------------------------------- run
    def run(self, workload: Sequence[Tuple[Sequence[int], int]], *,
            planes: Sequence[str] = ("device", "wire", "storage",
                                     "gray", "kill", "router"),
            horizon: int = 40, kills: int = 1,
            slow_delay_s: float = 0.01, storm_rate: float = 1.0,
            max_wall_s: float = 120.0,
            pace_s: float = 0.0, lease_ttl_s: float = 0.25,
            partition_probes: int = 3) -> CampaignReport:
        """One campaign: build fleet, submit workload, fire the drawn
        schedule while stepping, settle, referee. Prompts must be
        unique per campaign (they key the token-exact check across a
        router crash).

        ``pace_s`` sleeps between steps WHILE actions are pending:
        process fleets step orders of magnitude faster than their
        workers produce tokens, so an unpaced schedule can fire its
        whole horizon before any traffic exists for the planes to
        overlap. Once the schedule drains, settling spins unpaced."""
        t0 = time.monotonic()
        prompts = [tuple(int(t) for t in p) for p, _ in workload]
        if len(set(prompts)) != len(prompts):
            raise ValueError("campaign prompts must be unique")
        reps = self._make_replicas()
        fleet = FleetRouter(reps, journal=self._build_journal(),
                            **self._router_kw)
        chaos = self._make_chaos(fleet)
        by_id = {c.replica_id: c for c in chaos}
        ha = (self._arm_ha(fleet, lease_ttl_s)
              if "partition" in planes and self.journal_dir is not None
              else None)
        failover_s: Optional[float] = None
        schedule = self._draw_schedule(planes, horizon, chaos,
                                       kills=kills,
                                       slow_delay_s=slow_delay_s,
                                       storm_rate=storm_rate)
        pending = list(schedule)
        expect = {tuple(int(t) for t in p): list(self._oracle(list(p), n))
                  for p, n in workload}
        handles = [(tuple(int(t) for t in p), int(n),
                    fleet.submit(list(p), int(n)))
                   for p, n in workload]
        violations: List[str] = []
        skipped: List[str] = []
        injected_acc: Dict[str, int] = {}
        storm_baseline: Optional[int] = None
        finished_pre_crash: List[Tuple[tuple, List[int]]] = []
        recovery_s: Optional[float] = None
        revived_handles: Dict[int, object] = {}
        crashed = False
        step_idx = 0
        deadline = t0 + max_wall_s
        while time.monotonic() < deadline:
            while pending and pending[0].step <= step_idx:
                action = pending.pop(0)
                if action.kind in ("kill", "slow_on", "slow_off"):
                    target = by_id.get(action.replica_id)
                    fn = (target.kill_fn if action.kind == "kill"
                          else target.slow_fn) if target else None
                    if fn is not None:
                        try:
                            if action.kind == "kill":
                                fn()
                            else:
                                fn(action.value or 0.0)
                        except Exception:  # noqa: BLE001 - chaos on an
                            pass           # already-dead target is a no-op
                elif action.kind == "storm_on":
                    self.storage_plan._rates = (
                        float(action.value or 1.0), 0.0, 0.0, 0.0)
                    storm_baseline = int(
                        self.storage_plan.total_injected)
                elif action.kind == "storm_off":
                    live_now = (revived_handles.values() if crashed
                                else [fh for _, _, fh in handles])
                    if (storm_baseline is not None
                            and int(self.storage_plan.total_injected)
                            == storm_baseline
                            and not all(fh.done for fh in live_now)):
                        # The storm has not touched a single disk op
                        # yet (workers may still be prefilling): hold
                        # it until it bites or the fleet drains — a
                        # storm over an idle journal proves nothing.
                        pending.append(
                            ChaosAction(step_idx + 1, "storm_off"))
                        pending.sort(key=lambda a: (a.step, a.kind))
                        continue
                    storm_baseline = None
                    self.storage_plan._rates = (0.0, 0.0, 0.0, 0.0)
                elif action.kind == "partition":
                    if ha is None:
                        continue
                    # Full bidirectional silence: the primary stops
                    # being stepped and stops renewing — but the
                    # OBJECT stays alive, and after the standby
                    # promotes it keeps trying to command (the mode
                    # kill can never produce).
                    ha["partitioned"] = True
                    if self.storage_plan is not None:
                        # Promotion arms a FRESH journal against the
                        # disk exactly like cold recovery does — a
                        # still-raging storm would fail that open, so
                        # the partition ends the storm (same call the
                        # router_crash plane makes below).
                        self.storage_plan._rates = (0.0, 0.0, 0.0, 0.0)
                        storm_baseline = None
                    t_part = time.monotonic()
                    promoted = None
                    while time.monotonic() < deadline:
                        out = ha["standby"].step()
                        if out is not None:
                            promoted = out
                            break
                        time.sleep(0.005)
                    if promoted is None:
                        violations.append(
                            "standby never promoted during partition")
                        continue
                    new_fleet, reborn = promoted
                    failover_s = time.monotonic() - t_part
                    ha["promoted"] = True
                    # The deposed-but-alive primary issues commands:
                    # every one must come back a TYPED EpochFenced
                    # reject — and the refusal must be counted.
                    for k in range(int(partition_probes)):
                        probe = [1 + (k % 30)] * (6 + k)
                        ha["probes_attempted"] += 1
                        try:
                            fleet.submit(probe, 4)
                        except EpochFenced:
                            ha["probes_refused"] += 1
                        except Exception:  # noqa: BLE001 - any other
                            pass   # outcome is NOT a fencing refusal
                    ha["counted"] = int(
                        fleet.metrics.fenced_commands_refused)
                    # The workload rides over: reborn handles replace
                    # the deposed router's, matched by unique prompt
                    # (finished streams keep their settled handles).
                    reborn_by_prompt = {
                        tuple(int(t) for t in fh.request.prompt): fh
                        for fh in reborn.values()}
                    handles = [(ptup, n, reborn_by_prompt.get(ptup, fh))
                               for ptup, n, fh in handles]
                    fleet = new_fleet
                elif action.kind == "router_crash":
                    crashed = True
                    if self.storage_plan is not None:
                        # Recovery re-opens the journal against the
                        # disk: a still-raging storm would fail that
                        # open, so the crash ends the storm (it proved
                        # what it could).
                        self.storage_plan._rates = (0.0, 0.0, 0.0, 0.0)
                        storm_baseline = None
                    _fold_injected(chaos, injected_acc)
                    fleet, recovery_s, revived_handles, chaos = \
                        self._crash_and_recover(fleet, chaos, violations)
                    by_id = {c.replica_id: c for c in chaos}
                    for ptup, n, fh in handles:
                        if fh.done and fh.state.value == "finished":
                            finished_pre_crash.append(
                                (ptup, list(fh.tokens)))
            fleet.step()
            if ha is not None:
                if not ha["partitioned"]:
                    ha["keeper"].step()   # primary keeps its lease
                elif ha["promoted"]:
                    ha["standby"].step()  # promoted standby renews
            step_idx += 1
            live = (revived_handles.values() if crashed
                    else [fh for _, _, fh in handles])
            if not pending and all(fh.done for fh in live):
                break
            if pending and pace_s > 0.0:
                time.sleep(pace_s)
        wall_s = time.monotonic() - t0
        report = self._referee(fleet, handles, expect, crashed,
                               finished_pre_crash, revived_handles,
                               recovery_s, violations, skipped, planes,
                               ha)
        report.actions = schedule
        report.steps = step_idx
        report.wall_s = wall_s
        report.failover_s = failover_s
        _fold_injected(chaos, injected_acc)
        if self.storage_plan is not None:
            injected_acc["storage"] = int(
                self.storage_plan.total_injected)
        report.injected = injected_acc
        fleet.close()
        return report

    # ------------------------------------------------------- crash/recover
    def _crash_and_recover(self, fleet, chaos, violations):
        """The router-SIGKILL plane: abandon the live router un-closed
        (exactly what a SIGKILL leaves — buffered, un-fsynced tail
        lost), reap its replicas, verify the WAL fold is bit-stable,
        then rebuild over FRESH replicas via :meth:`FleetRouter.
        recover` and time the cycle until every revived stream made
        forward progress."""
        for c in chaos:
            if c.kill_fn is not None:
                try:
                    c.kill_fn()
                except Exception:  # noqa: BLE001 - already-dead victim
                    pass
        fp1 = _state_fingerprint(self.journal_dir)
        fp2 = _state_fingerprint(self.journal_dir)
        if fp1 != fp2:
            violations.append("read_state not bit-stable across reads")
        t0 = time.monotonic()
        reps = self._make_replicas()
        recovered, revived = FleetRouter.recover(
            self.journal_dir, reps, journal=self._build_journal(),
            **self._router_kw)
        mirrored = {rid: len(fh.tokens) for rid, fh in revived.items()}
        deadline = time.monotonic() + self.recovery_bound_s
        while time.monotonic() < deadline:
            recovered.step()
            if all(fh.done or len(fh.tokens) > mirrored[rid]
                   for rid, fh in revived.items()):
                break
        recovery_s = time.monotonic() - t0
        new_chaos = self._make_chaos(recovered)
        return recovered, recovery_s, revived, new_chaos

    # -------------------------------------------------------------- referee
    def _referee(self, fleet, handles, expect, crashed,
                 finished_pre_crash, revived_handles, recovery_s,
                 violations, skipped, planes,
                 ha=None) -> CampaignReport:
        invariants: Dict[str, bool] = {}
        live = (list(revived_handles.values()) if crashed
                else [fh for _, _, fh in handles])
        invariants["acked_terminal"] = all(fh.done for fh in live)
        if not invariants["acked_terminal"]:
            violations.append(
                f"{sum(not fh.done for fh in live)} acked stream(s) "
                f"not terminal")
        exact = True
        checked = 0
        if crashed:
            pairs = list(finished_pre_crash) + [
                (tuple(int(t) for t in fh.request.prompt),
                 list(fh.tokens))
                for fh in revived_handles.values()
                if fh.done and fh.state.value == "finished"]
        else:
            pairs = [(ptup, list(fh.tokens)) for ptup, _, fh in handles
                     if fh.done and fh.state.value == "finished"]
        for ptup, toks in pairs:
            checked += 1
            if toks != expect[ptup]:
                exact = False
                violations.append(
                    f"stream {ptup[:4]}...: tokens diverged from "
                    f"oracle ({toks[:6]} vs {expect[ptup][:6]})")
        if checked == 0:
            exact = False
            violations.append("no finished stream to verify")
        invariants["token_exact"] = exact
        counts = fleet.compile_counts()
        invariants["zero_recompiles"] = bool(counts) and all(
            v == 1 for v in counts.values())
        if not invariants["zero_recompiles"]:
            violations.append(f"recompiles: {counts}")
        balanced, bad = _pins_balanced(fleet)
        invariants["pins_balanced"] = balanced
        violations.extend(bad)
        if self.journal_dir is not None:
            fp1 = _state_fingerprint(self.journal_dir)
            fp2 = _state_fingerprint(self.journal_dir)
            invariants["recover_idempotent"] = (
                fp1 == fp2
                and not any("bit-stable" in v for v in violations))
        else:
            invariants["recover_idempotent"] = True
            skipped.append("recover_idempotent (no journal)")
        if "router" in planes and self.journal_dir is not None:
            invariants["recovery_bounded"] = (
                recovery_s is not None
                and recovery_s <= self.recovery_bound_s)
            if not invariants["recovery_bounded"]:
                violations.append(f"recovery took {recovery_s}s "
                                  f"(bound {self.recovery_bound_s}s)")
        else:
            invariants["recovery_bounded"] = True
            skipped.append("recovery_bounded (no router crash)")
        try:
            from pddl_tpu.obs.export import (fleet_exposition,
                                             parse_prometheus_text)
            parse_prometheus_text(fleet_exposition(fleet))
            invariants["exposition_round_trip"] = True
        except Exception as e:  # noqa: BLE001 - the referee reports
            invariants["exposition_round_trip"] = False
            violations.append(f"exposition: {e}")
        if ha is not None and ha.get("promoted"):
            attempted = int(ha["probes_attempted"])
            refused = int(ha["probes_refused"])
            counted = int(ha["counted"])
            invariants["single_writer"] = (
                attempted > 0 and refused == attempted
                and counted >= attempted)
            if not invariants["single_writer"]:
                violations.append(
                    f"single_writer: {refused}/{attempted} deposed "
                    f"commands refused ({counted} counted)")
        else:
            invariants["single_writer"] = True
            skipped.append("single_writer (partition plane not fired)")
        collector = getattr(fleet, "dtrace", None)
        if collector is None:
            invariants["trace_complete"] = True
            skipped.append("trace_complete (tracing not armed)")
        else:
            # A few extra pump rounds first: span batches for the very
            # last finishes may still sit in worker pipes.
            for _ in range(3):
                try:
                    fleet.step()
                except Exception:  # noqa: BLE001 - settled fleet only
                    break
            from pddl_tpu.obs.assemble import stitch
            gappy: List[str] = []
            for tid, trace in stitch(collector.records()).items():
                for gap in trace.gaps():
                    gappy.append(f"trace {tid}: {gap}")
            invariants["trace_complete"] = not gappy
            violations.extend(gappy[:5])
        return CampaignReport(
            seed=self.seed, planes=tuple(planes), actions=[], steps=0,
            wall_s=0.0, recovery_s=recovery_s, injected={},
            invariants=invariants, violations=violations,
            skipped=skipped)
