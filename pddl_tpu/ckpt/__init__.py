"""Checkpoint / restore / pretrained-weight import.

The reference's entire persistence surface is a final
``model.save('ImageNet-<name>-reuse.h5')`` (``/root/reference/
imagenet-resnet50.py:69-72``; rank-0-gated and str+int-broken in the Horovod
script, ``imagenet-resnet50-hvd.py:125-129``) plus pretrained-weight loading
via ``weights='imagenet'`` (``imagenet-pretrained-resnet50.py:56``). This
package provides that and the mid-training story the reference lacks
(SURVEY.md §5 "Checkpoint / resume"):

- :class:`Checkpointer` — Orbax-backed sharded, optionally async
  save/restore of the full :class:`~pddl_tpu.train.state.TrainState`
  (params, BN stats, optimizer state, step) with epoch metadata; restore
  places shards directly on the mesh.
- :class:`ModelCheckpoint` / :class:`BackupAndRestore` — Keras-style
  callbacks for periodic saving and crash-resume.
- :func:`load_keras_resnet50_h5` — imports ``tf.keras.applications``
  ResNet-50 ``.h5`` weights into the Flax model for the pretrained mode.
- :func:`fetch_keras_resnet50_weights` — resolves (and, on explicit
  opt-in, downloads) the official keras-applications weight file with MD5
  verification, making ``weights='imagenet'`` runnable end to end.
"""

from pddl_tpu.ckpt.checkpoint import (
    BackupAndRestore,
    CheckpointCorruptError,
    CheckpointEveryN,
    Checkpointer,
    ModelCheckpoint,
    latest_epoch,
    tree_checksums,
)
from pddl_tpu.ckpt.fetch import fetch_keras_resnet50_weights
from pddl_tpu.ckpt.hf_import import load_hf_gpt2
from pddl_tpu.ckpt.keras_import import load_keras_resnet50_h5

__all__ = [
    "Checkpointer",
    "CheckpointCorruptError",
    "CheckpointEveryN",
    "ModelCheckpoint",
    "BackupAndRestore",
    "latest_epoch",
    "tree_checksums",
    "fetch_keras_resnet50_weights",
    "load_hf_gpt2",
    "load_keras_resnet50_h5",
]
