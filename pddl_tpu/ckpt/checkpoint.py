"""Orbax-backed checkpointing: sharded, async-capable, resume-aware.

TPU-native upgrade over the reference's final-save-only persistence
(``/root/reference/imagenet-resnet50.py:69-72``): every host writes its own
param/optimizer shards in parallel (no gather to host 0 — the reference's
``model.save`` funnels everything through one process), restore places
shards directly onto the mesh via the state's ``NamedSharding``s, and saves
can overlap the next training step (``async_save``).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from pddl_tpu.train.callbacks import Callback

PyTree = Any
log = logging.getLogger(__name__)


def _ocp():
    import orbax.checkpoint as ocp  # noqa: PLC0415

    return ocp


class Checkpointer:
    """Save/restore the full TrainState with step-numbered retention.

    >>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(state, epoch=4)
    >>> state = ckpt.restore(trainer.state)   # shard-aware in-place layout
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = 5,
                 async_save: bool = True, read_only: bool = False):
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        if read_only:
            # Readers must never mutate a (possibly live) directory: no tmp
            # cleanup, no retention GC, no writes. A second writing manager
            # on the same directory races the real one's in-flight saves.
            options = ocp.CheckpointManagerOptions(read_only=True)
        else:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                # A crash/SIGKILL mid-save leaves
                # '<step>.orbax-checkpoint-tmp' behind; a resumed run
                # re-saves the SAME step (it restores the epoch the crash
                # interrupted), and writing into the stale tmp dir races to
                # FileNotFoundError. Clean leftovers at init (primary-gated,
                # awaited before the first save). Caught by the
                # multi-process kill/resume test.
                cleanup_tmp_directories=True,
            )
        self._mngr = ocp.CheckpointManager(self.directory, options=options)

    # ---------------------------------------------------------------- save
    def save(self, state: PyTree, epoch: Optional[int] = None,
             metrics: Optional[Dict[str, float]] = None, force: bool = False) -> int:
        """Save at the state's step; records epoch/metrics as metadata."""
        ocp = _ocp()
        step = int(jax.device_get(state.step))
        meta = {"epoch": epoch, "metrics": metrics or {}}
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
            force=force,
        )
        return step

    def wait(self) -> None:
        """Block until any in-flight async save completes."""
        self._mngr.wait_until_finished()

    # ------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, target: PyTree, step: Optional[int] = None) -> PyTree:
        """Restore into the layout of ``target`` (a live, correctly-sharded
        TrainState — e.g. ``trainer.state`` right after ``init_state``).

        Each leaf is restored with the sharding ``target``'s leaf carries, so
        PS/ZeRO-sharded states come back sharded without a replicated
        staging copy.
        """
        ocp = _ocp()
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            target,
        )
        try:
            out = self._mngr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract)),
            )
        except (ValueError, KeyError) as e:
            # Migration: checkpoints written before TrainState grew
            # ema_batch_stats lack that subtree, so a template containing
            # it fails the structure match. Retry without it and seed the
            # shadow from the restored live stats — exactly its value at
            # init time. Guarded on the error actually naming the subtree
            # so an unrelated restore failure (wrong model shapes, say)
            # surfaces as itself, not as a bogus migration message.
            ema_bs = getattr(abstract, "ema_batch_stats", None)
            if (ema_bs is None or not jax.tree.leaves(ema_bs)
                    or "ema_batch_stats" not in str(e)):
                raise
            out = self._mngr.restore(
                step,
                args=ocp.args.Composite(state=ocp.args.StandardRestore(
                    abstract.replace(ema_batch_stats=None))),
            )
            log.warning(
                "restore: checkpoint predates ema_batch_stats; seeded the "
                "EMA stats shadow from the restored batch_stats",
            )
            restored = out["state"]
            # No copy needed: jax arrays are immutable, and init seeds the
            # shadow from the same live tree (train/loop.py).
            return restored.replace(ema_batch_stats=restored.batch_stats)
        return out["state"]

    def metadata(self, step: Optional[int] = None) -> Dict[str, Any]:
        ocp = _ocp()
        step = step if step is not None else self.latest_step()
        if step is None:
            return {}
        out = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return out["meta"] or {}

    def close(self) -> None:
        self._mngr.close()


def latest_epoch(directory: str) -> Optional[int]:
    """Epoch recorded in the newest checkpoint under ``directory`` (for
    computing ``initial_epoch`` on resume), or None if no checkpoint."""
    if not os.path.isdir(directory):
        return None
    ckpt = Checkpointer(directory, async_save=False, read_only=True)
    try:
        if ckpt.latest_step() is None:
            return None
        return ckpt.metadata().get("epoch")
    finally:
        ckpt.close()


class ModelCheckpoint(Callback):
    """Periodic epoch-end checkpointing (the Keras ``ModelCheckpoint``
    capability the reference never used; its only save is train-end).

    ``save_best_only`` monitors a metric like the reference's callbacks
    monitor ``val_loss`` (``imagenet-resnet50.py:64-65``).
    """

    def __init__(self, directory: str, monitor: str = "val_loss",
                 save_best_only: bool = False, mode: str = "min",
                 every_n_epochs: int = 1, max_to_keep: Optional[int] = 5,
                 async_save: bool = True):
        self.ckpt = Checkpointer(directory, max_to_keep=max_to_keep,
                                 async_save=async_save)
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.mode = mode
        self.every_n_epochs = every_n_epochs
        self.best = float("inf") if mode == "min" else -float("inf")

    def _improved(self, current: float) -> bool:
        return current < self.best if self.mode == "min" else current > self.best

    def on_epoch_end(self, epoch, state, logs):
        if (epoch + 1) % self.every_n_epochs:
            return None
        if self.save_best_only:
            current = logs.get(self.monitor)
            if current is None or not self._improved(current):
                return None
            self.best = current
        self.ckpt.save(state, epoch=epoch, metrics=logs)
        return None

    def on_train_end(self, state, logs):
        self.ckpt.wait()
        return None


class BackupAndRestore(Callback):
    """Crash-resume: restore the newest checkpoint at train start and keep
    a rolling backup every epoch — fault tolerance the reference almost
    entirely lacks (SURVEY.md §5 "Failure detection": its only crumbs are
    ``GRPC_FAIL_FAST`` and the Horovod re-broadcast comment,
    ``imagenet-resnet50-hvd.py:108-111``).

    Use with ``initial_epoch=latest_epoch(dir) + 1`` (or the CLI runner's
    ``--resume``, which wires both ends).
    """

    def __init__(self, directory: str, async_save: bool = True):
        self.ckpt = Checkpointer(directory, max_to_keep=1, async_save=async_save)

    def on_train_begin(self, state):
        if self.ckpt.latest_step() is None:
            return None
        return self.ckpt.restore(state)

    def on_epoch_end(self, epoch, state, logs):
        self.ckpt.save(state, epoch=epoch, metrics=logs)
        return None

    def on_train_end(self, state, logs):
        self.ckpt.wait()
        return None


def save_params_npz(path: str, params: PyTree) -> None:
    """Small, dependency-light final export (the ``model.save('...h5')``
    moment, ``imagenet-resnet50.py:69-72``): flat ``{path: array}`` npz,
    coordinator-only under multi-host."""
    from pddl_tpu.core import dist

    if not dist.is_coordinator():
        return
    flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(str(getattr(k, "key", k)) for k in keypath)
        flat[name] = np.asarray(jax.device_get(leaf))
    # Write through a file object: np.savez(path) silently appends ".npz"
    # to extensionless paths, landing the file somewhere the caller's
    # save_path doesn't point.
    with open(path, "wb") as f:
        np.savez(f, **flat)
