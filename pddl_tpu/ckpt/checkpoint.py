"""Orbax-backed checkpointing: sharded, async-capable, resume-aware,
VERIFIED.

TPU-native upgrade over the reference's final-save-only persistence
(``/root/reference/imagenet-resnet50.py:69-72``): every host writes its own
param/optimizer shards in parallel (no gather to host 0 — the reference's
``model.save`` funnels everything through one process), restore places
shards directly onto the mesh via the state's ``NamedSharding``s, and saves
can overlap the next training step (``async_save``).

Crash-resilience discipline (CheckFreq, FAST '21; Gemini, SOSP '23):

- **Integrity metadata**: every save embeds per-leaf CRC32 checksums in
  its (atomically finalized) Orbax metadata — the checksums double as
  the finalize marker, since a torn save has no restorable metadata.
- **Verify-on-restore**: ``restore()`` recomputes the checksums of what
  came off storage and compares; a torn or bit-rotted latest save is
  SKIPPED (with a loud warning) in favor of the newest step that
  restores AND verifies — which is why every writer here keeps
  ``max_to_keep >= 2``.
- **Step granularity**: :class:`CheckpointEveryN` saves every N
  optimizer steps with the Trainer's loader position (epoch, step
  offset, batches consumed) in the metadata, so
  ``Trainer.fit(resume=...)`` resumes a killed run MID-EPOCH,
  bit-exactly — and the Trainer's in-process fault recovery
  (`train/loop.py`) restores from the same saves and replays forward.
"""

from __future__ import annotations

import logging
import os
import time
import zlib
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from pddl_tpu.train.callbacks import Callback

PyTree = Any
log = logging.getLogger(__name__)


def _ocp():
    import orbax.checkpoint as ocp  # noqa: PLC0415

    return ocp


class CheckpointCorruptError(RuntimeError):
    """A checkpoint restored structurally but failed checksum
    verification (torn write past finalize, bit rot, partial copy)."""


def _rehome(state: PyTree) -> PyTree:
    """Copy restored leaves into jax-owned buffers (shardings kept).

    Orbax hands back arrays whose buffers tensorstore allocated.
    Donating those straight into the jitted train step corrupts the
    heap on this container's jaxlib (glibc `corrupted double-linked
    list` aborts when the persistent compile cache and a multi-device
    host platform are both active) — the donated deallocation goes
    through the wrong allocator. One on-device copy per restore makes
    every downstream consumer (donated fit steps, in-process recovery
    replay, elastic resume) hold buffers jax itself allocated; the cost
    is one device-to-device pass over the state, noise against the
    restore's storage I/O.
    """
    import jax.numpy as jnp  # noqa: PLC0415

    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, state)


def tree_checksums(state: PyTree) -> Optional[Dict[str, str]]:
    """Per-leaf CRC32 (hex) over the host bytes of every leaf, keyed by
    tree path — the integrity metadata a save embeds and a restore
    re-derives. Returns ``None`` when any leaf is not fully addressable
    (multi-host sharded state: no process holds the global bytes, so a
    global checksum would need a gather — verification is skipped, not
    wrong)."""
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    for _, leaf in flat:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return None
    # ONE batched fetch: per-leaf device_get would serialize a
    # device-to-host round-trip per parameter on the hot save path.
    host = jax.device_get([leaf for _, leaf in flat])
    out: Dict[str, str] = {}
    for (path, _), arr in zip(flat, host):
        # crc32 reads the numpy buffer directly — no tobytes() copy of
        # a possibly-multi-GB state on the hot save path.
        arr = np.ascontiguousarray(np.asarray(arr))
        out[jax.tree_util.keystr(path)] = f"{zlib.crc32(arr):08x}"
    return out


class Checkpointer:
    """Save/restore the full TrainState with step-numbered retention.

    >>> ckpt = Checkpointer("/tmp/run1", max_to_keep=3)
    >>> ckpt.save(state, epoch=4)
    >>> state = ckpt.restore(trainer.state)   # shard-aware in-place layout
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = 5,
                 async_save: bool = True, read_only: bool = False):
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        if read_only:
            # Readers must never mutate a (possibly live) directory: no tmp
            # cleanup, no retention GC, no writes. A second writing manager
            # on the same directory races the real one's in-flight saves.
            options = ocp.CheckpointManagerOptions(read_only=True)
        else:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
                # A crash/SIGKILL mid-save leaves
                # '<step>.orbax-checkpoint-tmp' behind; a resumed run
                # re-saves the SAME step (it restores the epoch the crash
                # interrupted), and writing into the stale tmp dir races to
                # FileNotFoundError. Clean leftovers at init (primary-gated,
                # awaited before the first save). Caught by the
                # multi-process kill/resume test.
                cleanup_tmp_directories=True,
            )
        self._mngr = ocp.CheckpointManager(self.directory, options=options)

    # ---------------------------------------------------------------- save
    def save(self, state: PyTree, epoch: Optional[int] = None,
             metrics: Optional[Dict[str, float]] = None, force: bool = False,
             loader: Optional[Dict[str, Any]] = None,
             checksum: bool = True) -> int:
        """Save at the state's step; records epoch/metrics — and, for
        the crash-resume path, the data-loader position (``loader``)
        and per-leaf checksums (``checksum=True``) — as metadata.

        The checksums are computed from the in-memory state BEFORE the
        (possibly async) write dispatches, so they describe exactly
        what was handed to Orbax; the one cost is a host fetch of the
        state (measured: ``benchmarks/gpt_train_bench.py
        --checkpoint-overhead``)."""
        ocp = _ocp()
        step = int(jax.device_get(state.step))
        meta: Dict[str, Any] = {"epoch": epoch, "metrics": metrics or {}}
        if loader is not None:
            meta["loader"] = dict(loader)
        if checksum:
            sums = tree_checksums(state)
            if sums is None:
                log.warning(
                    "save(step=%d): state has non-addressable leaves "
                    "(multi-host); skipping checksum metadata", step)
            else:
                meta["checksums"] = sums
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta),
            ),
            force=force,
        )
        return step

    def wait(self) -> None:
        """Block until any in-flight async save completes."""
        self._mngr.wait_until_finished()

    # ------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        """Every step Orbax finalized, ascending."""
        return sorted(self._mngr.all_steps())

    def restore(self, target: PyTree, step: Optional[int] = None,
                verify: bool = True) -> PyTree:
        """Restore into the layout of ``target`` (a live, correctly-sharded
        TrainState — e.g. ``trainer.state`` right after ``init_state``).

        Each leaf is restored with the sharding ``target``'s leaf carries, so
        PS/ZeRO-sharded states come back sharded without a replicated
        staging copy.

        With no explicit ``step``, candidates are tried NEWEST FIRST: a
        save that fails to restore (torn write — finalize marker or
        array files missing/truncated) or restores but fails its
        checksum verification is skipped with a warning and the next
        older step is tried — crash-resume must not be wedged by the
        very crash it is recovering from. An explicit ``step`` raises
        instead (:class:`CheckpointCorruptError` on checksum mismatch):
        the caller asked for THAT save, silently substituting another
        would lie. Saves without checksum metadata (pre-r10, or
        multi-host) restore unverified, as before.
        """
        if step is not None:
            return self._restore_verified(target, step, verify=verify)
        candidates = self.all_steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        last_err: Optional[BaseException] = None
        for s in reversed(candidates):
            try:
                return self._restore_verified(target, s, verify=verify)
            except Exception as e:  # noqa: BLE001 - torn/corrupt saves
                # fall back; the LAST candidate's error re-raises below,
                # so a structural bug (wrong model shapes on every step)
                # still surfaces as itself.
                last_err = e
                log.warning(
                    "restore: step %d is torn or corrupt (%s); falling "
                    "back to the previous verified save", s, e)
        raise CheckpointCorruptError(
            f"no restorable checkpoint under {self.directory}: newest "
            f"failure: {last_err}") from last_err

    def verify(self, state: PyTree, step: int) -> bool:
        """Does ``state`` match the checksums recorded for ``step``?
        ``True`` when the save carries no checksums (nothing to refute
        — pre-r10 saves, multi-host saves)."""
        expected = self.metadata(step).get("checksums")
        if not expected:
            return True
        actual = tree_checksums(state)
        if actual is None:
            # Multi-host restore of a single-host-checksummed save: no
            # process holds the global bytes, so verification is
            # impossible here — proceed unverified, loudly.
            log.warning(
                "verify(step=%d): restored state is not fully "
                "addressable; checksum verification skipped", step)
            return True
        # Subset semantics: every leaf the SAVE recorded must match.
        # Extra leaves in `actual` are migration-seeded subtrees (e.g.
        # the ema_batch_stats shadow) that were never written — they
        # carry no stored bytes to verify.
        return all(actual.get(k) == v for k, v in expected.items())

    def _restore_verified(self, target: PyTree, step: int,
                          verify: bool = True) -> PyTree:
        out = self._restore_step(target, step)
        if verify and not self.verify(out, step):
            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.directory} failed "
                "checksum verification (torn or corrupted save)")
        return out

    def _restore_step(self, target: PyTree, step: int) -> PyTree:
        ocp = _ocp()
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array) else x,
            target,
        )
        try:
            out = self._mngr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract)),
            )
        except (ValueError, KeyError) as e:
            # Migration: checkpoints written before TrainState grew
            # ema_batch_stats lack that subtree, so a template containing
            # it fails the structure match. Retry without it and seed the
            # shadow from the restored live stats — exactly its value at
            # init time. Guarded on the error actually naming the subtree
            # so an unrelated restore failure (wrong model shapes, say)
            # surfaces as itself, not as a bogus migration message.
            ema_bs = getattr(abstract, "ema_batch_stats", None)
            if (ema_bs is None or not jax.tree.leaves(ema_bs)
                    or "ema_batch_stats" not in str(e)):
                raise
            out = self._mngr.restore(
                step,
                args=ocp.args.Composite(state=ocp.args.StandardRestore(
                    abstract.replace(ema_batch_stats=None))),
            )
            log.warning(
                "restore: checkpoint predates ema_batch_stats; seeded the "
                "EMA stats shadow from the restored batch_stats",
            )
            restored = out["state"]
            # The shadow seeds from the same (rehomed) live tree — jax
            # arrays are immutable, so sharing it is fine.
            restored = _rehome(restored)
            return restored.replace(ema_batch_stats=restored.batch_stats)
        return _rehome(out["state"])

    def metadata(self, step: Optional[int] = None) -> Dict[str, Any]:
        ocp = _ocp()
        step = step if step is not None else self.latest_step()
        if step is None:
            return {}
        out = self._mngr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )
        return out["meta"] or {}

    def newest_metadata(self) -> Dict[str, Any]:
        """Metadata of the newest step whose metadata restores — a torn
        latest save (crash mid-write) falls back to the previous one,
        mirroring :meth:`restore`'s no-explicit-step discipline."""
        for s in reversed(self.all_steps()):
            try:
                return self.metadata(s)
            except Exception as e:  # noqa: BLE001 - torn save
                log.warning("metadata: step %d unreadable (%s); trying "
                            "the previous save", s, e)
        return {}

    def close(self) -> None:
        self._mngr.close()


def latest_epoch(directory: str) -> Optional[int]:
    """Epoch recorded in the newest readable checkpoint under
    ``directory`` (for computing ``initial_epoch`` on resume), or None
    if no checkpoint."""
    if not os.path.isdir(directory):
        return None
    ckpt = Checkpointer(directory, async_save=False, read_only=True)
    try:
        if ckpt.latest_step() is None:
            return None
        return ckpt.newest_metadata().get("epoch")
    finally:
        ckpt.close()


def _grace_save(ckpt: Checkpointer, trainer, state, logs=None,
                checksum: bool = True) -> int:
    """Idempotent step-granular save with loader metadata — the shared
    core of every delegated grace-save path. A save landing on a step
    the manager already holds (a SIGTERM on a save-cadence batch, or
    right after an epoch-end save) returns without writing instead of
    colliding with the existing step."""
    step = int(jax.device_get(state.step))
    if ckpt.latest_step() == step:
        return step
    loader = trainer.loader_state() if trainer is not None else None
    epoch = (loader["epoch"] - 1) if loader else None
    # Step logs are device scalars; metadata is JSON.
    metrics = {k: float(v) for k, v in logs.items()} if logs else None
    return ckpt.save(state, epoch=epoch, metrics=metrics, force=True,
                     loader=loader, checksum=checksum)


class ModelCheckpoint(Callback):
    """Periodic epoch-end checkpointing (the Keras ``ModelCheckpoint``
    capability the reference never used; its only save is train-end).

    ``save_best_only`` monitors a metric like the reference's callbacks
    monitor ``val_loss`` (``imagenet-resnet50.py:64-65``).
    """

    def __init__(self, directory: str, monitor: str = "val_loss",
                 save_best_only: bool = False, mode: str = "min",
                 every_n_epochs: int = 1, max_to_keep: Optional[int] = 5,
                 async_save: bool = True):
        self.ckpt = Checkpointer(directory, max_to_keep=max_to_keep,
                                 async_save=async_save)
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.mode = mode
        self.every_n_epochs = every_n_epochs
        self.best = float("inf") if mode == "min" else -float("inf")

    def _improved(self, current: float) -> bool:
        return current < self.best if self.mode == "min" else current > self.best

    def _loader(self):
        return (self.trainer.loader_state()
                if self.trainer is not None else None)

    def save_now(self, state, logs=None) -> int:
        """Grace-save entry point (``PreemptionCheckpoint(delegate=...)``)
        through THIS manager — one writer per directory. Idempotent per
        step, like :meth:`CheckpointEveryN.save_now`."""
        return _grace_save(self.ckpt, self.trainer, state, logs)

    def on_epoch_end(self, epoch, state, logs):
        if (epoch + 1) % self.every_n_epochs:
            return None
        if self.save_best_only:
            current = logs.get(self.monitor)
            if current is None or not self._improved(current):
                return None
            self.best = current
        self.ckpt.save(state, epoch=epoch, metrics=logs,
                       loader=self._loader())
        return None

    def on_train_end(self, state, logs):
        self.ckpt.wait()
        return None


class BackupAndRestore(Callback):
    """Crash-resume: restore the newest checkpoint at train start and keep
    a rolling backup every epoch — fault tolerance the reference almost
    entirely lacks (SURVEY.md §5 "Failure detection": its only crumbs are
    ``GRPC_FAIL_FAST`` and the Horovod re-broadcast comment,
    ``imagenet-resnet50-hvd.py:108-111``).

    Use with ``initial_epoch=latest_epoch(dir) + 1`` (or the CLI runner's
    ``--resume``, which wires both ends).
    """

    def __init__(self, directory: str, async_save: bool = True,
                 max_to_keep: int = 2):
        # >= 2 saves retained: the torn-latest fallback in restore()
        # needs a previous verified step to fall back TO.
        self.ckpt = Checkpointer(directory, max_to_keep=max(max_to_keep, 2),
                                 async_save=async_save)

    def on_train_begin(self, state):
        if self.ckpt.latest_step() is None:
            return None
        return self.ckpt.restore(state)

    def on_epoch_end(self, epoch, state, logs):
        self.ckpt.save(state, epoch=epoch, metrics=logs,
                       loader=(self.trainer.loader_state()
                               if self.trainer is not None else None))
        return None

    def on_train_end(self, state, logs):
        self.ckpt.wait()
        return None


class CheckpointEveryN(Callback):
    """Step-granular verified checkpointing — the CheckFreq cadence.

    Every ``every_n_steps`` optimizer steps the full TrainState is
    saved (async by default, overlapping the next steps) with per-leaf
    checksums and the Trainer's loader position in the metadata, and at
    least the last two saves are retained. This one callback powers
    BOTH recovery paths:

    - **process restart**: ``Trainer.fit(resume=directory)`` restores
      the newest VERIFIED save (a torn/corrupt latest is skipped) and
      repositions the data pipeline, so the restarted run is bit-exact
      with an uninterrupted one (``tests/test_train_faults.py``);
    - **in-process**: registering with the Trainer (automatic via
      ``set_trainer``) makes it the restore source for the guarded
      device-call boundary — exhausted retries restore the last good
      save and replay forward from the Trainer's batch replay buffer,
      whose depth is sized to ``every_n_steps`` (`train/loop.py`).

    The save cadence is counted in PYTHON (seeded from one
    ``state.step`` fetch at train start), so the hot loop never syncs
    on the device step counter.
    """

    def __init__(self, directory: str, every_n_steps: int = 50,
                 max_to_keep: int = 3, async_save: bool = True,
                 checksum: bool = True):
        if every_n_steps < 1:
            raise ValueError(
                f"every_n_steps must be >= 1, got {every_n_steps}")
        if max_to_keep is not None and max_to_keep < 2:
            raise ValueError(
                "max_to_keep must be >= 2: torn-latest fallback needs a "
                "previous verified save to fall back to")
        self.directory = directory
        self.every_n_steps = int(every_n_steps)
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.checksum = checksum
        self.ckpt: Optional[Checkpointer] = None
        self.saves = 0
        self.last_save_wall_s = 0.0
        self._step = 0

    def set_trainer(self, trainer) -> None:
        super().set_trainer(trainer)
        # The Trainer's in-process recovery restores from these saves
        # and sizes its batch replay buffer to the save interval.
        if hasattr(trainer, "attach_recovery"):
            trainer.attach_recovery(self)

    def on_train_begin(self, state):
        if self.ckpt is None:
            self.ckpt = Checkpointer(self.directory,
                                     max_to_keep=self.max_to_keep,
                                     async_save=self.async_save)
        self._step = int(jax.device_get(state.step))
        return None

    def on_train_batch_end(self, step, state, logs):
        self._step += 1
        if self._step % self.every_n_steps:
            return None
        self.save_now(state, logs=logs)
        return None

    def save_now(self, state, logs=None) -> int:
        """One verified save at the state's current step (also the
        grace-window entry point for preemption handling). Idempotent
        per step: a grace save landing on a batch the cadence already
        saved (PreemptionCheckpoint delegating here) is a no-op instead
        of a same-step manager collision."""
        before = self.ckpt.latest_step()
        t0 = time.perf_counter()
        step = _grace_save(self.ckpt, self.trainer, state, logs,
                           checksum=self.checksum)
        if step == before:
            return step  # idempotent no-op, nothing written
        self.last_save_wall_s = time.perf_counter() - t0
        self.saves += 1
        if self.trainer is not None:
            self.trainer.on_checkpoint_saved(step, self.last_save_wall_s)
        return step

    def on_train_end(self, state, logs):
        if self.ckpt is not None:
            self.ckpt.wait()
        return None


def save_params_npz(path: str, params: PyTree) -> None:
    """Small, dependency-light final export (the ``model.save('...h5')``
    moment, ``imagenet-resnet50.py:69-72``): flat ``{path: array}`` npz,
    coordinator-only under multi-host."""
    from pddl_tpu.core import dist

    if not dist.is_coordinator():
        return
    flat = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(str(getattr(k, "key", k)) for k in keypath)
        flat[name] = np.asarray(jax.device_get(leaf))
    # Write through a file object: np.savez(path) silently appends ".npz"
    # to extensionless paths, landing the file somewhere the caller's
    # save_path doesn't point.
    with open(path, "wb") as f:
        np.savez(f, **flat)
