"""Serving export: serialized StableHLO inference artifacts.

The reference's only deployable artifact is a Keras ``.h5``
(``/root/reference/imagenet-resnet50.py:69-72``), which needs the whole
Python/TF stack to serve. The TPU-native artifact is the compiled program
itself: ``jax.export`` serializes the jitted forward pass (weights baked
in or passed at call time) as portable StableHLO bytes that any XLA
runtime — including a C++ server with no Python — can load and execute,
with shapes, dtypes, and shardings recorded in the artifact.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export

PyTree = Any


def export_inference_fn(
    model,
    params: PyTree,
    input_shape: Sequence[int],
    *,
    input_dtype: Any = jnp.float32,
    batch_stats: Optional[PyTree] = None,
    apply_kwargs: Optional[dict] = None,
    platforms: Optional[Sequence[str]] = None,
) -> bytes:
    """Serialize ``model.apply`` (inference mode, weights baked in).

    Returns StableHLO bytes: the traced forward pass closed over
    ``params`` (weights become constants in the artifact, so a serving
    runtime needs nothing else). ``input_shape`` includes the batch
    dimension. The artifact records its target platforms and loaders
    enforce a match — by default only the platform exporting it; pass
    ``platforms=("tpu", "cpu")`` to lower for several and serve the same
    bytes anywhere among them.
    """
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    kwargs = dict(train=False)
    kwargs.update(apply_kwargs or {})

    def forward(x):
        return model.apply(variables, x, **kwargs)

    spec = jax.ShapeDtypeStruct(tuple(input_shape), input_dtype)
    kw = {"platforms": tuple(platforms)} if platforms else {}
    exported = jax_export.export(jax.jit(forward), **kw)(spec)
    return exported.serialize()


def save_inference_artifact(path: str, *args, **kwargs) -> str:
    """:func:`export_inference_fn` straight to a file; returns ``path``."""
    data = export_inference_fn(*args, **kwargs)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def load_inference_artifact(path_or_bytes) -> Tuple[Any, Any]:
    """Deserialize an artifact; returns ``(call, exported)``.

    ``call(x)`` runs the compiled forward on this process's devices (the
    pure-Python counterpart of a C++ XLA server loading the same bytes).
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    exported = jax_export.deserialize(data)
    return exported.call, exported
