"""Serving export: serialized StableHLO inference artifacts.

The reference's only deployable artifact is a Keras ``.h5``
(``/root/reference/imagenet-resnet50.py:69-72``), which needs the whole
Python/TF stack to serve. The TPU-native artifact is the compiled program
itself: ``jax.export`` serializes the jitted forward pass (weights baked
in or passed at call time) as portable StableHLO bytes that any XLA
runtime — including a C++ server with no Python — can load and execute,
with shapes, dtypes, and shardings recorded in the artifact.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export

PyTree = Any


def export_inference_fn(
    model,
    params: PyTree,
    input_shape: Sequence[int],
    *,
    input_dtype: Any = jnp.float32,
    batch_stats: Optional[PyTree] = None,
    apply_kwargs: Optional[dict] = None,
    platforms: Optional[Sequence[str]] = None,
) -> bytes:
    """Serialize ``model.apply`` (inference mode, weights baked in).

    Returns StableHLO bytes: the traced forward pass closed over
    ``params`` (weights become constants in the artifact, so a serving
    runtime needs nothing else). ``input_shape`` includes the batch
    dimension. The artifact records its target platforms and loaders
    enforce a match — by default only the platform exporting it; pass
    ``platforms=("tpu", "cpu")`` to lower for several and serve the same
    bytes anywhere among them.
    """
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    kwargs = dict(train=False)
    kwargs.update(apply_kwargs or {})

    def forward(x):
        return model.apply(variables, x, **kwargs)

    spec = jax.ShapeDtypeStruct(tuple(input_shape), input_dtype)
    kw = {"platforms": tuple(platforms)} if platforms else {}
    exported = jax_export.export(jax.jit(forward), **kw)(spec)
    return exported.serialize()


def save_inference_artifact(path: str, *args, **kwargs) -> str:
    """:func:`export_inference_fn` straight to a file; returns ``path``."""
    data = export_inference_fn(*args, **kwargs)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def load_inference_artifact(path_or_bytes) -> Tuple[Any, Any]:
    """Deserialize an artifact; returns ``(call, exported)``.

    ``call(x)`` runs the compiled forward on this process's devices (the
    pure-Python counterpart of a C++ XLA server loading the same bytes).
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    exported = jax_export.deserialize(data)
    return exported.call, exported


# --------------------------------------------------------------- decode
def export_decode_programs(
    model,
    params: PyTree,
    *,
    batch: int,
    prompt_len: int,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    platforms: Optional[Sequence[str]] = None,
    param_transform=None,
) -> dict:
    """Serialize the full GENERATION pipeline as two StableHLO programs.

    ``model.save``-then-serve is the endpoint of every reference script
    (``/root/reference/imagenet-resnet50.py:72``); for the LM families
    the serving artifact is generation, not a single forward. This
    exports the same two programs :func:`pddl_tpu.models.gpt.generate`
    dispatches (models/gpt.py `_decode_programs`) — nothing here is a
    re-implementation of decoding:

    - ``prefill``: ``(params, prompt i32[B,P]) -> (cache, logits)`` —
      builds the zero cache internally and runs the batched prompt pass;
    - ``decode``: ``(params, cache, logits, key_data u32[2]) ->
      tokens i32[B,T]`` — the ENTIRE ``max_new_tokens`` loop as the one
      on-device ``lax.scan`` dispatch, sampling included.

    Parameters are call ARGUMENTS (new checkpoints of the same shape
    reuse the artifact; weights never bloat the program). With
    ``param_transform`` (int8 serving: pass the QUANTIZED tree as
    ``params`` and :func:`pddl_tpu.ops.quant.dequantize` here) the
    artifact's parameter arguments are the int8+scale leaves and the
    dequant compiles INTO the programs — the serving runtime ships and
    holds half the bytes. The RNG enters
    as raw ``uint32[2]`` key data (``jax.random.key_data``) so the
    serving boundary carries no JAX-extended dtypes. The KV-cache tree
    flows between the two calls opaquely — a server treats it as a
    buffer list. The whole decode path is pure jnp/lax
    (``ops/attention.py decode_attention`` — chunked sweep, no custom
    calls), so the artifact round-trips through any XLA runtime on the
    recorded platforms.

    Returns ``{"prefill": bytes, "decode": bytes, "manifest": dict}``.
    """
    import numpy as np

    from pddl_tpu.models.gpt import _decode_cache_shapes, _decode_fns

    dec = model.clone(decode=True)
    step_fn, decode_all = _decode_fns(dec, temperature, top_k, top_p,
                                      max_new_tokens, param_transform)
    cache_shapes = _decode_cache_shapes(dec, batch)

    def prefill(p, prompt):
        cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                             cache_shapes)
        return step_fn(p, cache, prompt)

    def decode(p, cache, logits, key_data):
        return decode_all(p, cache, logits,
                          jax.random.wrap_key_data(key_data))

    p_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), params)
    prompt_spec = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
    cache_spec = jax.tree.map(
        lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype), cache_shapes)
    # The decode program's logits input is EXACTLY the prefill program's
    # logits output (shape and dtype) — jax_export enforces dtypes
    # strictly at call time, so derive both from the same trace.
    logits_spec = jax.eval_shape(prefill, p_spec, prompt_spec)[1]
    key_spec = jax.ShapeDtypeStruct((2,), jnp.uint32)

    kw = {"platforms": tuple(platforms)} if platforms else {}
    pre = jax_export.export(jax.jit(prefill), **kw)(p_spec, prompt_spec)
    run = jax_export.export(jax.jit(decode), **kw)(
        p_spec, cache_spec, logits_spec, key_spec)
    manifest = {
        "batch": batch, "prompt_len": prompt_len,
        "max_new_tokens": max_new_tokens, "temperature": temperature,
        "top_k": top_k, "top_p": top_p,
        "platforms": list(pre.platforms),
        "quantized_params": param_transform is not None,
    }
    return {"prefill": pre.serialize(), "decode": run.serialize(),
            "manifest": manifest}


def save_decode_artifact(path: str, *args, **kwargs) -> str:
    """:func:`export_decode_programs` into ONE file (a zip with
    ``prefill.stablehlo``, ``decode.stablehlo``, ``manifest.json``);
    returns ``path``."""
    import json
    import zipfile

    arts = export_decode_programs(*args, **kwargs)
    tmp = f"{path}.tmp.{os.getpid()}"
    with zipfile.ZipFile(tmp, "w") as z:
        z.writestr("prefill.stablehlo", arts["prefill"])
        z.writestr("decode.stablehlo", arts["decode"])
        z.writestr("manifest.json", json.dumps(arts["manifest"]))
    os.replace(tmp, path)
    return path


def load_decode_artifact(path: str):
    """Deserialize a :func:`save_decode_artifact` file.

    Returns ``(prefill, decode, manifest)`` where
    ``prefill(params, prompt) -> (cache, logits)`` and
    ``decode(params, cache, logits, key_data) -> tokens`` run the
    compiled programs on this process's devices.
    """
    import json
    import zipfile

    with zipfile.ZipFile(path) as z:
        pre = jax_export.deserialize(z.read("prefill.stablehlo"))
        run = jax_export.deserialize(z.read("decode.stablehlo"))
        manifest = json.loads(z.read("manifest.json"))
    return pre.call, run.call, manifest
