"""Pretrained-weight acquisition: the ``weights='imagenet'`` analogue.

The reference downloads Keras ResNet-50 ImageNet weights implicitly inside
``tf.keras.applications.ResNet50(weights='imagenet')``
(``/root/reference/imagenet-pretrained-resnet50.py:56``). TPU pod hosts
frequently have no egress, so this framework makes acquisition explicit:

- :func:`fetch_keras_resnet50_weights` resolves the official
  keras-applications weight file from a local cache, optionally downloading
  it (explicit opt-in) and always verifying the published MD5.
- When the file is missing and downloading is off, the error message IS the
  offline procedure: the one ``curl`` command (any machine with egress) plus
  where to drop the file.

URLs and hashes are the ones keras-applications itself publishes
(``tf_keras/src/applications/resnet.py`` ``BASE_WEIGHTS_PATH`` /
``WEIGHTS_HASHES``; Keras's ``get_file`` verifies the same MD5 values).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

BASE_WEIGHTS_URL = (
    "https://storage.googleapis.com/tensorflow/keras-applications/resnet/"
)

# model -> variant -> (file name, MD5 as published by keras-applications).
KERAS_RESNET_WEIGHTS: dict[str, dict[str, tuple[str, str]]] = {
    "resnet50": {
        "top": ("resnet50_weights_tf_dim_ordering_tf_kernels.h5",
                "2cb95161c43110f7111970584f804107"),
        "notop": ("resnet50_weights_tf_dim_ordering_tf_kernels_notop.h5",
                  "4d473c1dd8becc155b73f8504c6f6626"),
    },
    "resnet101": {
        "top": ("resnet101_weights_tf_dim_ordering_tf_kernels.h5",
                "f1aeb4b969a6efcfb50fad2f0c20cfc5"),
        "notop": ("resnet101_weights_tf_dim_ordering_tf_kernels_notop.h5",
                  "88cf7a10940856eca736dc7b7e228a21"),
    },
    "resnet152": {
        "top": ("resnet152_weights_tf_dim_ordering_tf_kernels.h5",
                "100835be76be38e30d865e96f2aaae62"),
        "notop": ("resnet152_weights_tf_dim_ordering_tf_kernels_notop.h5",
                  "ee4c566cf9a93f14d82f913c2dc6dd0c"),
    },
}


def default_cache_dir() -> str:
    """``$PDDL_TPU_CACHE`` or ``~/.cache/pddl_tpu/keras``."""
    root = os.environ.get(
        "PDDL_TPU_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "pddl_tpu"),
    )
    return os.path.join(root, "keras")


def _md5(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        while block := f.read(chunk):
            h.update(block)
    return h.hexdigest()


def fetch_keras_resnet50_weights(
    variant: str = "notop",
    *,
    model: str = "resnet50",
    cache_dir: Optional[str] = None,
    download: bool = False,
    verify: bool = True,
) -> str:
    """Return the local path of the official Keras ResNet weight file.

    Args:
      variant: ``"notop"`` (backbone only — what the reference's
        ``include_top=False`` uses, ``imagenet-pretrained-resnet50.py:56``)
        or ``"top"`` (with the original 1000-way classifier).
      model: ``resnet50`` (default) / ``resnet101`` / ``resnet152``.
      cache_dir: where the file lives; default :func:`default_cache_dir`.
      download: explicit opt-in to fetch over the network. Off by default —
        TPU pod hosts often have no egress, and implicit downloads from N
        hosts at once are a thundering herd; run the printed command once
        instead.
      verify: check the keras-published MD5 of the file (cached or fresh).

    Returns the path to a verified ``.h5``, usable as ``--pretrained-h5``.
    Raises ``FileNotFoundError`` (with the exact acquisition command) when
    the file is absent and ``download=False``, and ``ValueError`` on hash
    mismatch.
    """
    try:
        file_name, md5 = KERAS_RESNET_WEIGHTS[model][variant]
    except KeyError:
        raise ValueError(
            f"unknown weights {model!r}/{variant!r}; known models "
            f"{sorted(KERAS_RESNET_WEIGHTS)}, variants ('top', 'notop')"
        ) from None
    cache_dir = cache_dir or default_cache_dir()
    path = os.path.join(cache_dir, file_name)
    url = BASE_WEIGHTS_URL + file_name

    if not os.path.exists(path):
        if not download:
            raise FileNotFoundError(
                f"pretrained weights not found at {path}.\n"
                f"Acquire them once (any machine with network access):\n"
                f"  curl -fL --create-dirs -o {path} {url}\n"
                f"or re-run with download enabled "
                f"(--download-weights / download=True). "
                f"Expected MD5: {md5}"
            )
        os.makedirs(cache_dir, exist_ok=True)
        # Per-process temp name: N hosts sharing one cache (NFS home) must
        # not clobber each other's in-flight downloads; the atomic replace
        # means last-writer-wins on identical content.
        import tempfile
        from urllib.request import urlretrieve

        fd, tmp = tempfile.mkstemp(
            prefix=file_name + ".", suffix=".part", dir=cache_dir
        )
        os.close(fd)
        try:
            urlretrieve(url, tmp)  # noqa: S310 - https URL constant above
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    if verify:
        got = _md5(path)
        if got != md5:
            raise ValueError(
                f"MD5 mismatch for {path}: got {got}, expected {md5} "
                f"(the keras-applications published hash). Delete the file "
                f"and re-download from {url}."
            )
    return path
