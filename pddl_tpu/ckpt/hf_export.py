"""Export Llama-family weights to a HuggingFace ``transformers`` state dict.

The inverse of :func:`pddl_tpu.ckpt.hf_import.load_hf_llama` — train or
fine-tune on TPU here, serve anywhere transformers runs. The export is
exact for the whole Llama/Mistral/Qwen2/Mixtral lineage because the
architectures correspond one-to-one (untied embed/head, bias-free except
Qwen2's q/k/v; Mixtral layers round-trip through
``block_sparse_moe.{gate,experts.*}``). The GPT-2 family is deliberately
NOT exported: HF GPT-2
ties ``lm_head`` to ``wte``, and a trained untied head has no faithful
representation in that format.

Keys follow ``LlamaForCausalLM`` (``model.*`` + ``lm_head.weight``);
every kernel transposes back to torch ``nn.Linear``'s ``[out, in]``.
Values are numpy arrays — wrap in ``torch.from_numpy`` for
``load_state_dict`` (see ``tests/test_llama.py`` roundtrips).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

PyTree = Any


def export_hf_llama(variables: PyTree, *, model=None) -> Dict[str, np.ndarray]:
    """Map a :class:`~pddl_tpu.models.llama.Llama` variables tree onto HF
    Llama state-dict keys.

    Args:
      variables: ``{"params": ...}`` (trained or fresh).
      model: the Llama the variables belong to, if available — used to
        slice ``vocab_multiple`` padding back off the embedding and head
        (padding rows/columns never influenced training: the head slices
        them away, so dropping them is exact).

    Returns a ``{key: np.ndarray}`` state dict (f32).
    """
    params = variables["params"]
    vocab = getattr(model, "vocab_size", None)
    sd: Dict[str, np.ndarray] = {}

    def put(key: str, value) -> None:
        sd[key] = np.asarray(value, np.float32)

    emb = np.asarray(params["embed"]["embedding"])
    head = np.asarray(params["lm_head"]["kernel"])       # [E, V(+pad)]
    if vocab is not None:
        emb = emb[:vocab]
        head = head[:, :vocab]
    put("model.embed_tokens.weight", emb)                # [V, E]
    put("lm_head.weight", head.T)                        # [V, E]
    put("model.norm.weight", params["ln_final"]["scale"])

    n_blocks = sum(1 for k in params if k.startswith("block"))
    for i in range(n_blocks):
        blk = params[f"block{i}"]
        hf = f"model.layers.{i}."
        put(hf + "input_layernorm.weight", blk["ln1"]["scale"])
        put(hf + "post_attention_layernorm.weight", blk["ln2"]["scale"])

        attn = blk["attn"]
        e = attn["query"]["kernel"].shape[0]  # shape read, no host copy
        for name, proj in (("query", "q_proj"), ("key", "k_proj"),
                           ("value", "v_proj")):
            kern = np.asarray(attn[name]["kernel"])      # [E, Hx, D]
            put(hf + f"self_attn.{proj}.weight",
                kern.reshape(e, -1).T)                   # [Hx*D, E]
            if "bias" in attn[name]:                     # Qwen2 lineage
                put(hf + f"self_attn.{proj}.bias",
                    np.asarray(attn[name]["bias"]).reshape(-1))
        put(hf + "self_attn.o_proj.weight",
            np.asarray(attn["out"]["kernel"]).T)         # [E, H*D]

        if "moe" in blk:
            # Mixtral layer: router + expert-major SwiGLU stacks back to
            # per-expert Linear weights (keys follow MixtralForCausalLM).
            moe = blk["moe"]
            put(hf + "block_sparse_moe.gate.weight",
                np.asarray(moe["router"]["kernel"]).T)   # [N, E]
            for ours in ("w1", "w3", "w2"):
                stack = np.asarray(moe[ours])            # [N, in, out]
                for x in range(stack.shape[0]):
                    put(hf + f"block_sparse_moe.experts.{x}.{ours}.weight",
                        stack[x].T)
        else:
            put(hf + "mlp.gate_proj.weight",
                np.asarray(blk["mlp_gate"]["kernel"]).T)  # [I, E]
            put(hf + "mlp.up_proj.weight",
                np.asarray(blk["mlp_up"]["kernel"]).T)
            put(hf + "mlp.down_proj.weight",
                np.asarray(blk["mlp_down"]["kernel"]).T)  # [E, I]
    return sd
