"""HuggingFace LM weight import (GPT-2 + Llama) — the LM families'
``weights='imagenet'``.

The reference's pretrained mode loads published backbone weights into the
vision model (``/root/reference/imagenet-pretrained-resnet50.py:56``);
this is the same capability for the causal-LM family: map a
``transformers`` GPT-2 checkpoint (``GPT2LMHeadModel``) onto
:class:`pddl_tpu.models.gpt.GPT`'s parameter tree. The architectures
correspond exactly — pre-LN blocks, tanh-approximate GELU (HF
``gelu_new`` == flax ``nn.gelu(approximate=True)``), learned positional
embeddings, weight-tied LM head — so imported logits match the torch
model (``tests/test_hf_import.py``).

Name map (HF ``transformer.*`` → ours)::

    wte.weight            token_embed.embedding            [V, E]
    wpe.weight            pos_embed                        [1, P, E]
    h.<i>.ln_1.*          block<i>.ln1.{scale,bias}
    h.<i>.attn.c_attn.*   block<i>.attn.{query,key,value}  (split 3x, [E,H,D])
    h.<i>.attn.c_proj.*   block<i>.attn.out                [H*D, E]
    h.<i>.ln_2.*          block<i>.ln2.{scale,bias}
    h.<i>.mlp.c_fc.*      block<i>.mlp1                    [E, 4E]
    h.<i>.mlp.c_proj.*    block<i>.mlp2                    [4E, E]
    ln_f.*                ln_final.{scale,bias}
    (tied wte)            lm_head.kernel = wteᵀ, bias = 0

HF's ``Conv1D`` stores kernels as ``[in, out]`` already — no transposes
beyond the head split. A ``vocab_multiple``-padded model accepts a
smaller HF vocab: the real rows fill, padding rows keep their init (they
are unreachable — the head slices them away, ``models/gpt.py``).
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np

from pddl_tpu.ckpt.keras_import import _as_mutable

PyTree = Any


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)


def _tree_put(params: PyTree, path: str, value: np.ndarray, *,
              allow_vocab_pad: bool = False,
              what: str = "hf import") -> None:
    """Write ``value`` at ``a/b/c``-style ``path`` in a mutable numpy tree.

    Shared by both importers. ``allow_vocab_pad``: a smaller HF vocab
    fills the real slice of a ``vocab_multiple``-padded leaf (rows of a
    ``[V, E]`` embedding, columns of an ``[E, V]`` head, a ``[V]`` bias);
    padding entries keep their init — they are unreachable, the head
    slices them away.
    """
    node = params
    *parents, name = path.split("/")
    for p in parents:
        node = node[p]
    old = node[name]
    if allow_vocab_pad and value.shape != old.shape:
        # Merge only when the checkpoint FITS inside the padded leaf; a
        # checkpoint vocab LARGER than the model's (wrong vocab_size)
        # falls through to the descriptive shape error below instead of
        # an opaque numpy broadcast failure (ADVICE r3).
        fits = (value.ndim == old.ndim
                and all(vs <= os
                        for vs, os in zip(value.shape, old.shape))
                and sum(vs != os
                        for vs, os in zip(value.shape, old.shape)) == 1)
        if fits:
            merged = np.array(old)
            if value.ndim == 1:
                merged[: value.shape[0]] = value
            elif value.shape[0] != old.shape[0]:       # [V, E] rows
                merged[: value.shape[0], ...] = value
            else:                                      # [E, V] columns
                merged[:, : value.shape[1]] = value
            value = merged
    if value.shape != old.shape:
        raise ValueError(
            f"{what} {path}: shape {value.shape} != model's "
            f"{old.shape} (wrong depth/width/heads?)"
        )
    node[name] = value.astype(old.dtype)


def load_hf_gpt2(model_or_dir, variables: PyTree, *,
                 model=None, expected_ln_eps: float | None = None) -> PyTree:
    """Load a HF GPT-2 checkpoint into a GPT variables tree.

    Args:
      model_or_dir: a ``transformers.GPT2LMHeadModel`` (or any object with
        its ``state_dict()``), or a local checkpoint directory/name to
        pass to ``GPT2LMHeadModel.from_pretrained`` (no implicit network
        access beyond what transformers itself does for a local path).
      variables: ``{"params": ...}`` from ``GPT.init``; returned updated,
        input untouched.
      model: the :class:`~pddl_tpu.models.gpt.GPT` the variables were
        built for, if available. LayerNorm epsilon is a module attribute,
        invisible in ``variables`` — without it an import into a model
        left at the default ``ln_eps=1e-6`` succeeds but drifts from the
        torch logits (HF GPT-2 uses 1e-5). Pass the model (or
        ``expected_ln_eps``) so the mismatch raises instead.
      expected_ln_eps: the ``ln_eps`` the target model was built with;
        overrides ``model.ln_eps`` if both are given.
    """
    if isinstance(model_or_dir, str):
        from transformers import GPT2LMHeadModel  # noqa: PLC0415

        model_or_dir = GPT2LMHeadModel.from_pretrained(model_or_dir)
    if expected_ln_eps is None and model is not None:
        expected_ln_eps = getattr(model, "ln_eps", None)
    if expected_ln_eps is not None:
        cfg = getattr(model_or_dir, "config", None)
        hf_eps = getattr(cfg, "layer_norm_epsilon", 1e-5)
        if not np.isclose(expected_ln_eps, hf_eps, rtol=1e-3):
            raise ValueError(
                f"hf import: model was built with ln_eps={expected_ln_eps} "
                f"but the checkpoint uses layer_norm_epsilon={hf_eps} — "
                f"build the GPT with ln_eps={hf_eps} (epsilon is baked "
                "into the module, not the weights, so the import would "
                "silently produce wrong logits)"
            )
    sd = {k: _np(v) for k, v in model_or_dir.state_dict().items()}
    # Tolerate both "transformer.wte..." (LMHead model) and bare keys.
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) \
        else ""

    # Fresh mutable numpy tree (tree.map builds new containers;
    # _as_mutable unfreezes FrozenDict levels like keras_import does).
    params = jax.tree.map(np.asarray, _as_mutable(variables["params"]))

    def put(path: str, value: np.ndarray, allow_vocab_pad: bool = False):
        _tree_put(params, path, value, allow_vocab_pad=allow_vocab_pad)

    wte = sd[f"{prefix}wte.weight"]
    put("token_embed/embedding", wte, allow_vocab_pad=True)
    wpe = sd[f"{prefix}wpe.weight"]
    pos_old = params["pos_embed"]
    if wpe.shape[0] != pos_old.shape[1]:
        raise ValueError(
            f"hf import: positions {wpe.shape[0]} != model max_len "
            f"{pos_old.shape[1]}"
        )
    params["pos_embed"] = wpe[None].astype(pos_old.dtype)

    n_blocks = sum(1 for k in params if k.startswith("block"))
    n_hf = 1 + max(
        (int(m.group(1)) for m in
         (re.match(rf"{re.escape(prefix)}h\.(\d+)\.", k) for k in sd) if m),
        default=-1,
    )
    if n_hf != n_blocks:
        raise ValueError(
            f"hf import: checkpoint has {n_hf} transformer layers but the "
            f"model has {n_blocks} — depths must match (a deeper checkpoint "
            "would silently drop layers)"
        )
    e = wte.shape[1]
    for i in range(n_blocks):
        hf = f"{prefix}h.{i}."
        put(f"block{i}/ln1/scale", sd[hf + "ln_1.weight"])
        put(f"block{i}/ln1/bias", sd[hf + "ln_1.bias"])
        put(f"block{i}/ln2/scale", sd[hf + "ln_2.weight"])
        put(f"block{i}/ln2/bias", sd[hf + "ln_2.bias"])

        qkv_k = sd[hf + "attn.c_attn.weight"]  # [E, 3E] (Conv1D: [in, out])
        qkv_b = sd[hf + "attn.c_attn.bias"]    # [3E]
        h = params[f"block{i}"]["attn"]["query"]["kernel"].shape[1]
        d = e // h
        for j, name in enumerate(("query", "key", "value")):
            put(f"block{i}/attn/{name}/kernel",
                qkv_k[:, j * e:(j + 1) * e].reshape(e, h, d))
            put(f"block{i}/attn/{name}/bias",
                qkv_b[j * e:(j + 1) * e].reshape(h, d))
        put(f"block{i}/attn/out/kernel", sd[hf + "attn.c_proj.weight"])
        put(f"block{i}/attn/out/bias", sd[hf + "attn.c_proj.bias"])

        put(f"block{i}/mlp1/kernel", sd[hf + "mlp.c_fc.weight"])
        put(f"block{i}/mlp1/bias", sd[hf + "mlp.c_fc.bias"])
        put(f"block{i}/mlp2/kernel", sd[hf + "mlp.c_proj.weight"])
        put(f"block{i}/mlp2/bias", sd[hf + "mlp.c_proj.bias"])

    put("ln_final/scale", sd[f"{prefix}ln_f.weight"])
    put("ln_final/bias", sd[f"{prefix}ln_f.bias"])
    # GPT-2 ties the LM head to wte; ours is an explicit Dense [E, V(+pad)].
    put("lm_head/kernel", wte.T, allow_vocab_pad=True)
    lm_bias = params["lm_head"]["bias"]
    lm_bias = np.array(lm_bias)
    lm_bias[: wte.shape[0]] = 0.0
    params["lm_head"]["bias"] = lm_bias

    out = dict(variables)
    out["params"] = params
    return out


def load_hf_llama(model_or_dir, variables: PyTree, *,
                  model=None, expected_rms_eps: float | None = None,
                  expected_rope_theta: float | None = None) -> PyTree:
    """Load a HF Llama checkpoint into a :class:`~pddl_tpu.models.llama.
    Llama` variables tree.

    Name map (HF ``model.*`` → ours; torch ``nn.Linear`` stores
    ``[out, in]``, so every kernel transposes)::

        embed_tokens.weight                      embed.embedding   [V, E]
        layers.<i>.input_layernorm.weight        block<i>.ln1.scale
        layers.<i>.self_attn.{q,k,v}_proj.weight block<i>.attn.{query,key,value}
                                                 ([E, H(or H_kv), D])
        layers.<i>.self_attn.o_proj.weight       block<i>.attn.out [H*D, E]
        layers.<i>.post_attention_layernorm.*    block<i>.ln2.scale
        layers.<i>.mlp.{gate,up}_proj.weight     block<i>.mlp_{gate,up} [E, I]
        layers.<i>.mlp.down_proj.weight          block<i>.mlp_down [I, E]
        norm.weight                              ln_final.scale
        lm_head.weight (or tied embed)           lm_head.kernel    [E, V]

    Like the GPT-2 importer, module attributes invisible in the weights
    are validated when the ``model`` (or the ``expected_*`` values) is
    given: ``rms_eps`` against ``config.rms_norm_eps`` and ``rope_theta``
    against ``config.rope_theta`` — either mismatch silently skews logits.
    A ``vocab_multiple``-padded model accepts the smaller HF vocab.

    Mistral checkpoints are Llama-layout state dicts and import through
    this same function: build the Llama with
    ``sliding_window=config.sliding_window`` and the logits match
    transformers' windowed attention
    (``tests/test_llama.py::test_hf_mistral_checkpoint_loads_with_sliding_window``).
    Qwen2 checkpoints likewise: build with ``qkv_bias=True`` (their one
    structural delta — q/k/v projection biases, imported when present in
    the state dict).
    """
    if isinstance(model_or_dir, str):
        from transformers import LlamaForCausalLM  # noqa: PLC0415

        model_or_dir = LlamaForCausalLM.from_pretrained(model_or_dir)
    cfg = getattr(model_or_dir, "config", None)
    if expected_rms_eps is None and model is not None:
        expected_rms_eps = getattr(model, "rms_eps", None)
    if expected_rope_theta is None and model is not None:
        expected_rope_theta = getattr(model, "rope_theta", None)
    if cfg is not None:
        # Only validate against a real config — a bare state_dict holder
        # (supported, like the GPT-2 importer) has nothing to check
        # against, and inventing defaults would spuriously reject e.g. a
        # Llama-3-style rope_theta=500000 model.
        for name, want, have in (
            ("rms_eps", expected_rms_eps,
             getattr(cfg, "rms_norm_eps", None)),
            ("rope_theta", expected_rope_theta,
             getattr(cfg, "rope_theta", None)),
        ):
            if want is not None and have is not None \
                    and not np.isclose(want, have, rtol=1e-3):
                raise ValueError(
                    f"hf llama import: model was built with {name}={want} "
                    f"but the checkpoint uses {have} — rebuild the Llama "
                    f"with {name}={have} (the value is baked into the "
                    "module, not the weights, so the import would "
                    "silently skew logits)"
                )
        if model is not None:
            # sliding_window distinguishes a Mistral checkpoint; unlike
            # eps/theta, None-vs-set is the dangerous mismatch (a Llama
            # left at the default silently ignores the checkpoint's SWA
            # for every sequence longer than the window).
            want_sw = getattr(model, "sliding_window", None)
            have_sw = getattr(cfg, "sliding_window", None)
            layer_types = getattr(cfg, "layer_types", None)
            if layer_types is not None:
                # Modern transformers resolves the use_sliding_window /
                # max_window_layers combination into per-layer types; our
                # single global sliding_window can represent all-full or
                # all-sliding, nothing mixed.
                kinds = set(layer_types)
                if kinds == {"full_attention"}:
                    have_sw = None
                elif kinds != {"sliding_attention"}:
                    raise ValueError(
                        "hf llama import: checkpoint mixes per-layer "
                        f"attention types {sorted(kinds)} (e.g. Qwen2 "
                        "max_window_layers) — not representable by the "
                        "global sliding_window attribute"
                    )
            elif getattr(cfg, "use_sliding_window", True) is False:
                # Qwen2-style gate without resolved layer_types.
                have_sw = None
            elif have_sw is not None:
                # No layer_types (older transformers): apply Qwen2's
                # max_window_layers semantics by hand — layers below it
                # run full attention, so a mixed split is unrepresentable.
                mwl = getattr(cfg, "max_window_layers", None)
                nhl = getattr(cfg, "num_hidden_layers", None)
                if mwl is not None and nhl is not None:
                    if mwl >= nhl:
                        have_sw = None         # every layer full
                    elif mwl > 0:
                        raise ValueError(
                            "hf llama import: checkpoint windows only "
                            f"layers >= max_window_layers={mwl} of {nhl} "
                            "— not representable by the global "
                            "sliding_window attribute"
                        )
            if want_sw != have_sw:
                raise ValueError(
                    f"hf llama import: model sliding_window={want_sw} but "
                    f"the checkpoint config uses {have_sw} — rebuild with "
                    f"sliding_window={have_sw}"
                )
            # Mixtral: routing width is config, not weights — a top-k
            # mismatch imports cleanly and silently skews every logit.
            have_tk = getattr(cfg, "num_experts_per_tok", None)
            n_local = getattr(cfg, "num_local_experts", None)
            if n_local and have_tk:
                want_tk = getattr(model, "moe_top_k", None)
                if want_tk is not None and want_tk != have_tk:
                    raise ValueError(
                        f"hf llama import: model moe_top_k={want_tk} but "
                        f"the checkpoint uses num_experts_per_tok="
                        f"{have_tk} — rebuild with moe_top_k={have_tk}"
                    )
                # Mixtral routing is DROPLESS; the dense dispatch drops
                # overflow beyond capacity_factor*k*S/n tokens per expert
                # in TRAINING. Eval/serving (train=False) is dropless by
                # construction when moe_eval_dropless is on (capacity == S
                # covers the all-tokens-to-one-expert worst case,
                # ops/moe.py) — so inference parity needs no
                # capacity_factor condition. Only a model that turned
                # dropless eval OFF must carry a worst-case
                # capacity_factor >= n/k, or an imbalanced prompt
                # silently diverges from transformers' logits.
                if not getattr(model, "moe_eval_dropless", False):
                    want_cf = getattr(model, "moe_capacity_factor", None)
                    if want_cf is not None and want_cf < n_local / have_tk:
                        raise ValueError(
                            f"hf llama import: moe_eval_dropless=False "
                            f"with moe_capacity_factor={want_cf} can drop "
                            f"routed tokens at inference (dropless "
                            f"Mixtral needs >= num_local_experts/"
                            f"num_experts_per_tok = {n_local / have_tk:g})"
                            " — re-enable moe_eval_dropless or raise "
                            "moe_capacity_factor for serving parity"
                        )
    sd = {k: _np(v) for k, v in model_or_dir.state_dict().items()}
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""

    params = jax.tree.map(np.asarray, _as_mutable(variables["params"]))

    def put(path: str, value: np.ndarray, allow_vocab_pad: bool = False):
        _tree_put(params, path, value, allow_vocab_pad=allow_vocab_pad,
                  what="hf llama import")

    ckpt_has_bias = f"{prefix}layers.0.self_attn.q_proj.bias" in sd
    model_has_bias = "bias" in params.get("block0", {}).get(
        "attn", {}).get("query", {})
    if ckpt_has_bias and not model_has_bias:
        raise ValueError(
            "hf llama import: checkpoint carries q/k/v projection biases "
            "(Qwen2-style) but the model has none — rebuild the Llama "
            "with qkv_bias=True"
        )
    if model_has_bias and not ckpt_has_bias:
        # The loop below would overwrite every weight but silently keep
        # the target tree's existing bias values — raise instead.
        raise ValueError(
            "hf llama import: model was built with qkv_bias=True but the "
            "checkpoint has no q/k/v projection biases — rebuild with "
            "qkv_bias=False"
        )

    wte = sd[f"{prefix}embed_tokens.weight"]
    put("embed/embedding", wte, allow_vocab_pad=True)

    n_blocks = sum(1 for k in params if k.startswith("block"))
    n_hf = 1 + max(
        (int(m.group(1)) for m in
         (re.match(rf"{re.escape(prefix)}layers\.(\d+)\.", k) for k in sd)
         if m),
        default=-1,
    )
    if n_hf != n_blocks:
        raise ValueError(
            f"hf llama import: checkpoint has {n_hf} layers but the model "
            f"has {n_blocks} — depths must match"
        )
    e = wte.shape[1]
    for i in range(n_blocks):
        hf = f"{prefix}layers.{i}."
        put(f"block{i}/ln1/scale", sd[hf + "input_layernorm.weight"])
        put(f"block{i}/ln2/scale", sd[hf + "post_attention_layernorm.weight"])

        attn = params[f"block{i}"]["attn"]
        h = attn["query"]["kernel"].shape[1]
        d = e // h
        for name, proj in (("query", "q_proj"), ("key", "k_proj"),
                           ("value", "v_proj")):
            w = sd[hf + f"self_attn.{proj}.weight"]  # [Hx*D, E]
            hx = attn[name]["kernel"].shape[1]       # H or H_kv
            put(f"block{i}/attn/{name}/kernel", w.T.reshape(e, hx, d))
            if hf + f"self_attn.{proj}.bias" in sd:  # Qwen2: qkv biases
                put(f"block{i}/attn/{name}/bias",
                    sd[hf + f"self_attn.{proj}.bias"].reshape(hx, d))
        put(f"block{i}/attn/out/kernel",
            sd[hf + "self_attn.o_proj.weight"].T)    # [E, H*D] -> [H*D, E]

        if hf + "block_sparse_moe.gate.weight" in sd:
            # Mixtral layer: router + expert-major SwiGLU experts. Ours
            # keeps the HF per-expert names (w1 gate / w3 up / w2 down)
            # stacked on a leading expert dim; torch Linear stores
            # [out, in] so every matrix transposes.
            if "moe" not in params[f"block{i}"]:
                raise ValueError(
                    "hf llama import: checkpoint is a Mixtral (routed "
                    f"experts in layer {i}) but the model block has no "
                    "MoE — rebuild the Llama with moe_experts="
                    "config.num_local_experts"
                )
            n_exp = params[f"block{i}"]["moe"]["w1"].shape[0]
            ck_exp = sum(
                1 for k in sd
                if k.startswith(hf + "block_sparse_moe.experts.")
                and k.endswith(".w1.weight"))
            if n_exp != ck_exp:
                raise ValueError(
                    f"hf llama import: layer {i} has {ck_exp} experts in "
                    f"the checkpoint but the model was built with "
                    f"moe_experts={n_exp}"
                )
            put(f"block{i}/moe/router/kernel",
                sd[hf + "block_sparse_moe.gate.weight"].T)
            for ours, theirs in (("w1", "w1"), ("w3", "w3"), ("w2", "w2")):
                put(f"block{i}/moe/{ours}", np.stack([
                    sd[hf + f"block_sparse_moe.experts.{x}.{theirs}.weight"].T
                    for x in range(n_exp)]))
        else:
            put(f"block{i}/mlp_gate/kernel",
                sd[hf + "mlp.gate_proj.weight"].T)
            put(f"block{i}/mlp_up/kernel", sd[hf + "mlp.up_proj.weight"].T)
            put(f"block{i}/mlp_down/kernel",
                sd[hf + "mlp.down_proj.weight"].T)

    put("ln_final/scale", sd[f"{prefix}norm.weight"])
    head = sd.get("lm_head.weight", wte)  # tied when absent
    put("lm_head/kernel", head.T, allow_vocab_pad=True)

    out = dict(variables)
    out["params"] = params
    return out


def load_hf_mixtral(model_or_dir, variables: PyTree, *, model=None,
                    **kwargs) -> PyTree:
    """Load a HF Mixtral checkpoint into a Llama variables tree.

    A Mixtral checkpoint is the Llama layout with each layer's MLP
    replaced by ``block_sparse_moe`` (router ``gate`` + per-expert
    SwiGLU ``w1``/``w3``/``w2``); :func:`load_hf_llama` detects and maps
    those layers, so this wrapper only resolves string inputs through
    ``MixtralForCausalLM``. Build the target model with
    ``moe_experts=config.num_local_experts`` and
    ``moe_top_k=config.num_experts_per_tok`` (validated when ``model``
    is passed; use a generous ``moe_capacity_factor`` for parity —
    Mixtral routing is dropless).
    """
    if isinstance(model_or_dir, str):
        from transformers import MixtralForCausalLM  # noqa: PLC0415

        model_or_dir = MixtralForCausalLM.from_pretrained(model_or_dir)
    return load_hf_llama(model_or_dir, variables, model=model, **kwargs)
