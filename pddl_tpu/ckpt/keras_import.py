"""Import ``tf.keras.applications`` ResNet-50 ``.h5`` weights into Flax.

Pretrained-mode parity: the reference's fine-tune scripts build the backbone
with ``weights='imagenet'`` (``/root/reference/imagenet-pretrained-resnet50.py
:56``), i.e. Keras downloads a ``.h5`` weight file and loads it by layer
name. This module performs the same load against a *local* ``.h5`` into the
:class:`pddl_tpu.models.resnet.ResNet` variable tree (which mirrors the
Keras v1 architecture exactly so every tensor maps 1:1 — see
``models/resnet.py``).

Name mapping (Keras → pddl_tpu), derived from the keras.applications
``resnet`` layer-naming scheme:

==============================  =================================
``conv1_conv`` / ``conv1_bn``   ``stem_conv`` / ``stem_bn``
``conv{s}_block{b}_0_conv/bn``  ``stage{s-1}_block{b}/shortcut_conv|bn``
``conv{s}_block{b}_{i}_conv``   ``stage{s-1}_block{b}/conv{i}``
``conv{s}_block{b}_{i}_bn``     ``stage{s-1}_block{b}/bn{i}``
``predictions`` (``probs``)     ``head``
==============================  =================================

BN weight translation: ``gamma→scale``, ``beta→bias`` (params);
``moving_mean→mean``, ``moving_variance→var`` (batch_stats). Conv/Dense
kernels share the (kh, kw, in, out) / (in, out) layouts between Keras and
Flax, so no transposition is needed.

Both weight-file flavors are handled: weights-only ``.h5`` (layer groups at
root) and full ``model.save`` archives (layers under ``model_weights``) —
the latter is what the reference's own final save produces
(``imagenet-resnet50.py:69-72``), so weights round-trip with Keras.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

PyTree = Any

# Keras BN weight name → (collection, our leaf name)
_BN_WEIGHTS = {
    "gamma": ("params", "scale"),
    "beta": ("params", "bias"),
    "moving_mean": ("batch_stats", "mean"),
    "moving_variance": ("batch_stats", "var"),
}


def keras_layer_map(
    stage_sizes: Sequence[int] = (3, 4, 6, 3),
) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
    """Keras layer name → (kind, module path) for a ResNet-v1 topology."""
    m: Dict[str, Tuple[str, Tuple[str, ...]]] = {
        "conv1_conv": ("conv", ("stem_conv",)),
        "conv1_bn": ("bn", ("stem_bn",)),
    }
    for s, n_blocks in enumerate(stage_sizes):
        for b in range(n_blocks):
            keras_pre = f"conv{s + 2}_block{b + 1}"
            ours = f"stage{s + 1}_block{b + 1}"
            if b == 0:
                m[f"{keras_pre}_0_conv"] = ("conv", (ours, "shortcut_conv"))
                m[f"{keras_pre}_0_bn"] = ("bn", (ours, "shortcut_bn"))
            for i in (1, 2, 3):
                m[f"{keras_pre}_{i}_conv"] = ("conv", (ours, f"conv{i}"))
                m[f"{keras_pre}_{i}_bn"] = ("bn", (ours, f"bn{i}"))
    # include_top head: named `predictions` in keras.applications, `probs`
    # in some exported variants.
    m["predictions"] = ("dense", ("head",))
    m["probs"] = ("dense", ("head",))
    return m


def _collect_datasets(group) -> Dict[str, np.ndarray]:
    """All datasets under an h5 group, keyed by base name ('kernel',...)."""
    out: Dict[str, np.ndarray] = {}

    def visit(name, obj):
        import h5py  # noqa: PLC0415

        if isinstance(obj, h5py.Dataset):
            base = name.split("/")[-1].split(":")[0]
            out[base] = np.asarray(obj)

    group.visititems(visit)
    return out


def _set_leaf(tree: dict, path: Tuple[str, ...], leaf_name: str,
              value: np.ndarray, source: str) -> None:
    node = tree
    for key in path:
        if key not in node:
            raise KeyError(
                f"importing {source}: module path {'/'.join(path)} not in "
                f"model tree (have: {sorted(node)})"
            )
        node = node[key]
    if leaf_name not in node:
        raise KeyError(
            f"importing {source}: weight {leaf_name!r} not in "
            f"{'/'.join(path)} (have: {sorted(node)})"
        )
    old = node[leaf_name]
    if tuple(old.shape) != tuple(value.shape):
        if (leaf_name == "kernel" and tuple(value.shape[:2]) == (7, 7)
                and tuple(old.shape) == (4, 4, 4 * value.shape[2],
                                         value.shape[3])):
            # Space-to-depth stem variant: the 7x7 Keras stem kernel maps
            # EXACTLY onto the 4x4x(4C) kernel (same function — see
            # models/resnet.py s2d_stem_kernel).
            from pddl_tpu.models.resnet import s2d_stem_kernel

            value = np.asarray(s2d_stem_kernel(value))
        elif (leaf_name == "kernel" and tuple(value.shape[:2]) == (4, 4)
                and tuple(old.shape) == (7, 7, value.shape[2] // 4,
                                         value.shape[3])):
            # The reverse direction: an .h5 exported from an s2d-stem
            # model loads back into the Keras-shaped stem (exact for
            # transformed kernels; trained s2d kernels lose the taps
            # outside the 7x7 window — see s2d_stem_kernel_inverse).
            from pddl_tpu.models.resnet import s2d_stem_kernel_inverse

            value = np.asarray(s2d_stem_kernel_inverse(value))
        else:
            raise ValueError(
                f"importing {source} -> {'/'.join(path)}/{leaf_name}: shape "
                f"{tuple(value.shape)} != model's {tuple(old.shape)} — "
                "architecture mismatch (wrong depth/width or not a v1 "
                "ResNet?)"
            )
    node[leaf_name] = value.astype(np.asarray(old).dtype)


def load_keras_resnet50_h5(
    path: str,
    variables: PyTree,
    stage_sizes: Sequence[int] = (3, 4, 6, 3),
    require_head: Optional[bool] = None,
) -> PyTree:
    """Load Keras ResNet ``.h5`` weights into a model variable tree.

    Args:
      path: ``.h5`` file — either a keras.applications weight file (with or
        without top) or a full Keras ``model.save`` archive.
      variables: the tree from ``model.init`` (``{"params", "batch_stats"}``);
        returned updated, input untouched.
      stage_sizes: block counts, default ResNet-50. Use the model family's
        sizes for 101/152 imports.
      require_head: True → fail if the file has no classifier head; None →
        import it when present (``include_top`` behavior), skip otherwise
        (the reference uses ``include_top=False`` + its own head,
        ``imagenet-resnet50.py:56-60``).

    Returns a new variables tree with every matched tensor replaced.
    """
    try:
        import h5py  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover
        raise ImportError("load_keras_resnet50_h5 requires h5py") from e

    new_vars = {
        "params": copy.deepcopy(_as_mutable(variables["params"])),
        "batch_stats": copy.deepcopy(_as_mutable(variables.get("batch_stats", {}))),
    }
    layer_map = keras_layer_map(stage_sizes)
    imported, saw_head = [], False

    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        for layer_name in root:
            if layer_name not in layer_map:
                continue
            kind, module_path = layer_map[layer_name]
            weights = _collect_datasets(root[layer_name])
            if not weights:
                continue
            if kind in ("conv", "dense"):
                _set_leaf(new_vars["params"], module_path, "kernel",
                          weights["kernel"], layer_name)
                if "bias" in weights:
                    _set_leaf(new_vars["params"], module_path, "bias",
                              weights["bias"], layer_name)
                saw_head |= kind == "dense"
            else:  # bn
                for keras_name, (coll, ours) in _BN_WEIGHTS.items():
                    if keras_name in weights:
                        _set_leaf(new_vars[coll], module_path, ours,
                                  weights[keras_name], layer_name)
            imported.append(layer_name)

    expected = len(keras_layer_map(stage_sizes)) - 2  # head counts once
    if len(imported) < expected - (0 if saw_head else 1):
        missing = sorted(set(layer_map) - set(imported) - {"predictions", "probs"})
        raise ValueError(
            f"{path}: only {len(imported)} of ~{expected} layers matched; "
            f"first missing: {missing[:5]} — is this a v1 ResNet-{sum(s * 3 for s in stage_sizes) + 2} "
            "weight file?"
        )
    if require_head and not saw_head:
        raise ValueError(f"{path} has no classifier head (notop weights)")

    out = dict(variables)
    out["params"] = new_vars["params"]
    if new_vars["batch_stats"]:
        out["batch_stats"] = new_vars["batch_stats"]
    return out


def _as_mutable(tree):
    """FrozenDict (older flax) → plain nested dict; dicts pass through."""
    if hasattr(tree, "unfreeze"):
        return tree.unfreeze()
    return {k: _as_mutable(v) if isinstance(v, dict) or hasattr(v, "unfreeze")
            else v for k, v in dict(tree).items()}


def export_keras_style_h5(path: str, variables: PyTree,
                          stage_sizes: Sequence[int] = (3, 4, 6, 3)) -> None:
    """Write the model tree as a Keras legacy ``.h5`` weight file — the
    final-save counterpart of the reference's ``model.save('...-reuse.h5')``
    (``imagenet-resnet50.py:69-72``).

    Emits the genuine legacy format (root ``layer_names`` attr, per-layer
    ``weight_names`` attrs), so the file loads back both via
    :func:`load_keras_resnet50_h5` AND via
    ``tf.keras.Model.load_weights(path, by_name=True)`` — verified against
    keras.applications.ResNet50 in ``tests/test_keras_parity.py``.
    """
    import h5py  # noqa: PLC0415

    params = _as_mutable(variables["params"])
    stats = _as_mutable(variables.get("batch_stats", {}))

    def get(tree, pth):
        node = tree
        for k in pth:
            node = node[k]
        return node

    layer_names = []
    with h5py.File(path, "w") as f:
        for layer_name, (kind, module_path) in keras_layer_map(stage_sizes).items():
            if layer_name == "probs":  # alias of predictions
                continue
            try:
                node = get(params, module_path)
            except KeyError:
                continue
            top = f.create_group(layer_name)
            g = top.create_group(layer_name)
            if kind in ("conv", "dense"):
                weights = {"kernel:0": np.asarray(node["kernel"])}
                if "bias" in node:
                    weights["bias:0"] = np.asarray(node["bias"])
            else:
                stat = get(stats, module_path)
                weights = {
                    "gamma:0": np.asarray(node["scale"]),
                    "beta:0": np.asarray(node["bias"]),
                    "moving_mean:0": np.asarray(stat["mean"]),
                    "moving_variance:0": np.asarray(stat["var"]),
                }
            for wname, value in weights.items():
                g.create_dataset(wname, data=value)
            top.attrs["weight_names"] = np.array(
                [f"{layer_name}/{w}".encode() for w in weights]
            )
            layer_names.append(layer_name)
        f.attrs["layer_names"] = np.array([n.encode() for n in layer_names])
        f.attrs["backend"] = b"tensorflow"
