"""API-compat shims for the reference's third-party distribution APIs.

Currently: :mod:`pddl_tpu.compat.hvd`, a Horovod-surface shim
(``import pddl_tpu.compat.hvd as hvd``) covering everything
``/root/reference/imagenet-resnet50-hvd.py`` uses, with XLA collectives
instead of MPI/NCCL.
"""
