"""Horovod API shim: ``import pddl_tpu.compat.hvd as hvd``.

Covers, symbol for symbol, the Horovod surface the reference's script uses
(``/root/reference/imagenet-resnet50-hvd.py``) with TPU-native semantics —
no MPI, no NCCL, no process-per-GPU:

================================  ============================================
reference call                    shim behavior
================================  ============================================
``hvd.init()`` (``:16``)          ``jax.distributed`` bootstrap + global mesh
``hvd.size()`` (``:99``)          replica count = devices on the data axis
``hvd.rank()`` (``:28,96,117``)   global index of this host's first replica
``hvd.local_rank()`` (``:41``)    0 — one process drives all local replicas
``hvd.DistributedOptimizer``      gradient ``pmean`` (explicit in shard_map
(``:101``)                        regime; already-global under the Trainer's
                                  jit-with-shardings regime)
``BroadcastGlobalVariables-``     host-0 value broadcast via
``Callback(0)`` (``:111``)        ``multihost_utils`` (replicated-init no-op
                                  single-host; real sync after restore)
``MetricAverageCallback``         cross-process metric mean (``:112-113``)
``LearningRateWarmupCallback``    linear warmup (``:114-115``)
================================  ============================================

Semantic mapping (documented, not hidden): Horovod runs one *process* per
accelerator; under SPMD one process drives every local device. The
"Horovod world" here is the set of data-parallel **replicas** (devices), so
``size()`` counts devices — which keeps the script-observable arithmetic
(LR scaling ``0.1*size`` ``:99``, effective global batch ``32*size``)
identical to Horovod's on the same chip count. Rank-gated side effects
(logging, saving ``:117-129``) key off ``rank()==0`` ⇔ coordinator host.
Data sharding is per *process*: use ``num_data_shards()``/``data_shard_index()``
(the ``.shard(hvd.size(), hvd.rank())`` moment, ``:77-81``, maps to
per-host, not per-device, sharding — one pipeline feeds all local replicas).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np
import optax

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.train.callbacks import Callback, LearningRateWarmup

PyTree = Any

_mesh = None


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None) -> None:
    """``hvd.init()``: multi-host rendezvous + global data mesh."""
    global _mesh
    dist.initialize(coordinator_address, num_processes, process_id)
    _mesh = build_mesh(MeshConfig())


def _require_init():
    if _mesh is None:
        raise RuntimeError("call hvd.init() first (imagenet-resnet50-hvd.py:16)")
    return _mesh


def is_initialized() -> bool:
    return _mesh is not None


def mesh():
    return _require_init()


def size() -> int:
    """World size = data-parallel replica count (LR/batch arithmetic parity)."""
    return int(np.prod(list(_require_init().shape.values())))


def rank() -> int:
    """Global rank of this process's first replica; 0 ⇔ coordinator."""
    return jax.process_index() * local_size()


def local_rank() -> int:
    """Always 0: one SPMD process drives all local devices (the reference
    uses this only to pin one GPU per process, ``:36-41`` — moot on TPU)."""
    return 0


def local_size() -> int:
    m = _require_init()
    return len([d for d in m.devices.flat if d.process_index == jax.process_index()])


def num_data_shards() -> int:
    """Shard count for input pipelines: per host (process), not per replica."""
    _require_init()
    return jax.process_count()


def data_shard_index() -> int:
    _require_init()
    return jax.process_index()


# ------------------------------------------------------------------ comms
def allreduce(value, average: bool = True):
    """Cross-process all-reduce of a host value (numpy/scalar/pytree).

    Jit-free utility — the gradient path never calls this (gradients are
    averaged inside the compiled step); it exists for host-side sums like
    sample counts or custom metrics.
    """
    _require_init()
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils  # noqa: PLC0415

    def _one(x):
        gathered = multihost_utils.process_allgather(np.asarray(x))
        return gathered.mean(axis=0) if average else gathered.sum(axis=0)

    return jax.tree.map(_one, value)


def allgather(value):
    """Concatenate each process's value along axis 0 (``hvd.allgather``).

    Host-side utility like :func:`allreduce`; per-process arrays must
    share their trailing dimensions. Scalars gather to a ``[size]``
    vector.
    """
    _require_init()
    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x: np.asarray(x)[None] if np.ndim(x) == 0 else np.asarray(x),
            value,
        )
    from jax.experimental import multihost_utils  # noqa: PLC0415

    def _one(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return multihost_utils.process_allgather(x)  # [n_procs]
        # hvd.allgather concatenates RAGGED per-process arrays along axis
        # 0 (its primary use: variable-length per-rank results). The
        # underlying gather needs uniform shapes, so: exchange lengths,
        # pad to the max, gather, then slice each block back.
        lengths = multihost_utils.process_allgather(np.asarray(len(x)))
        max_len = int(lengths.max())
        padded = np.zeros((max_len,) + x.shape[1:], x.dtype)
        padded[: len(x)] = x
        gathered = multihost_utils.process_allgather(padded)  # [P, max, ...]
        return np.concatenate(
            [gathered[p, : int(lengths[p])] for p in range(len(lengths))]
        )

    return jax.tree.map(_one, value)


def broadcast(value, root_rank: int = 0):
    """Broadcast host-``root_rank``'s value to every process.

    The reference only ever broadcasts from 0
    (``imagenet-resnet50-hvd.py:111``); any root is supported anyway —
    ``is_source`` selects whose value the one-to-all collective carries.
    """
    _require_init()
    if not 0 <= root_rank < jax.process_count():
        raise ValueError(
            f"root_rank={root_rank} out of range for "
            f"{jax.process_count()} processes"
        )
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils  # noqa: PLC0415

    return jax.tree.map(
        lambda x: multihost_utils.broadcast_one_to_all(
            np.asarray(x), is_source=jax.process_index() == root_rank),
        value,
    )


def DistributedOptimizer(optimizer: str | optax.GradientTransformation,
                         learning_rate: Optional[float] = None,
                         axis_name: Optional[str] = None,
                         **kwargs) -> optax.GradientTransformation:
    """``hvd.DistributedOptimizer`` (``:101``): optimizer whose updates are
    computed from *globally averaged* gradients.

    - Default (Trainer regime): gradients of a loss over the globally
      data-sharded batch are already the global average — XLA inserts the
      all-reduce; the optimizer is returned as-is (plus LR wiring).
    - ``axis_name=...`` (explicit per-replica regime, e.g. inside
      ``jax.shard_map``): prepends a gradient ``pmean`` over that axis, the
      literal ring-allreduce moment.
    """
    from pddl_tpu.train.state import make_optimizer  # noqa: PLC0415

    tx = make_optimizer(optimizer, learning_rate if learning_rate is not None
                        else 1e-3, **kwargs)
    if axis_name is None:
        return tx

    def _pmean_grads(updates, state, params=None):
        del state, params
        return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), updates), ()

    pmean_stage = optax.GradientTransformation(lambda params: (), _pmean_grads)
    return optax.chain(pmean_stage, tx)


# -------------------------------------------------------------- callbacks
class BroadcastGlobalVariablesCallback(Callback):
    """``hvd.callbacks.BroadcastGlobalVariablesCallback(0)`` (``:108-111``).

    Forces bitwise-identical start weights. Under the Trainer's SPMD init,
    parameters are created identically on every host (same seed, replicated
    sharding), so normally a no-op; after a per-host restore it performs a
    real host-0 broadcast — the "consistent initialization ... when training
    is restored" case the Horovod docs (quoted by the reference) describe.
    """

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        # Validate even single-process (a typo'd root should fail the dev
        # run, not explode later on the cluster) — directly, so the
        # single-process path keeps working without hvd.init().
        if not 0 <= self.root_rank < jax.process_count():
            raise ValueError(
                f"root_rank={self.root_rank} out of range for "
                f"{jax.process_count()} processes"
            )
        if jax.process_count() == 1:
            return None
        return broadcast(state, self.root_rank)


class MetricAverageCallback(Callback):
    """``hvd.callbacks.MetricAverageCallback`` (``:112-113``).

    The reference needs it because each rank evaluates a different
    validation shard. Under the Trainer, step metrics are computed inside
    the compiled step over the *global* batch, so epoch logs are already
    world-averages; per-host extras (if any) are averaged here.
    """

    def on_epoch_end(self, epoch, state, logs: Dict[str, float]):
        if jax.process_count() == 1:
            return None
        averaged = allreduce({k: float(v) for k, v in logs.items()})
        logs.update(averaged)
        return None


# Same class, Horovod's name (``:114-115``).
LearningRateWarmupCallback = LearningRateWarmup


class callbacks:  # namespace mirror of horovod.tensorflow.keras.callbacks
    BroadcastGlobalVariablesCallback = BroadcastGlobalVariablesCallback
    MetricAverageCallback = MetricAverageCallback
    LearningRateWarmupCallback = LearningRateWarmupCallback
