"""Experiment configuration + the 8 reference-script presets.

The reference has essentially no config system (SURVEY.md §5 "Config / flag
system"): hard-coded paths and batch sizes, one commented-out argparse
(``/root/reference/imagenet-resnet50-hvd.py:17-23``) and one with broken
flag names ``' -- ps'``/``' -- worker'`` (``imagenet-resnet50-ps.py:21-27``).
This module replaces all of that with one dataclass and a preset per
reference script, so every experiment the reference expresses as a separate
file is here a named configuration over the same library.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

# Reference batch arithmetic, cited per script (SURVEY.md §6):
#   single/mirrored: 32/replica (imagenet-resnet50.py:46, -mirror.py:54)
#   multiworker scratch: 128/replica train, 256 val (-multiworkers.py:70-72)
#   multiworker pretrained: 32/replica both (-pretrained-...-multiworkers.py:63-65)
#   hvd: 32/replica, post-batch shard (-hvd.py:77-81)
#   ps: 32 global, repeat + fixed steps (-ps.py:118-119,142-143)


@dataclasses.dataclass
class ExperimentConfig:
    """Everything a reference script hard-codes, as data."""

    name: str = "experiment"
    # model
    model: str = "resnet50"
    num_classes: int = 1000
    pretrained_h5: Optional[str] = None  # weights='imagenet' analogue: local .h5
    # The reference's weights='imagenet' itself
    # (imagenet-pretrained-resnet50.py:56): when set and pretrained_h5 is
    # not, the official keras-applications file is resolved from the local
    # cache (ckpt/fetch.py), downloading only with download_weights=True —
    # TPU hosts can't be assumed to have egress.
    weights: Optional[str] = None
    download_weights: bool = False  # explicit opt-in (--download-weights)
    bn_mode: str = "train"  # "frozen" reproduces the reference's training=False
    # ResNet stem: "keras" (exact keras.applications shape) or
    # "space_to_depth" (MLPerf-style throughput variant, same function —
    # models/resnet.py; pretrained .h5 stems import via the exact kernel
    # transform either way).
    stem: str = "keras"
    compute_dtype: str = "bfloat16"
    # Parameter (and thus optimizer-moment) STORAGE dtype. "bfloat16"
    # halves weight+optimizer HBM — how the 1B llama fits one chip —
    # but bf16 Adam moments are a convergence hazard; see
    # docs/CONVERGENCE.md's f32-vs-bf16 comparison before using it for
    # quality-critical training.
    param_dtype: str = "float32"
    # Update rule for bf16 param storage: "plain" (round-to-nearest,
    # measured +2.4% val loss at 304M), "stochastic_round" (unbiased,
    # same memory — the default recipe fix), or "f32_master" (exact
    # master copy). Ignored for float32 params.
    # See train/mixed_precision.py and docs/CONVERGENCE.md.
    param_update: str = "plain"
    # transformer families only: activation rematerialization policy
    # ("none" | "dots" | "full" — models/vit.py REMAT_POLICIES)
    remat: Optional[str] = None
    # data
    data_dir: Optional[str] = None  # None → synthetic
    image_size: int = 224
    # synthetic image task only: class-mean separation in noise-std units
    # (data/synthetic.py signal_strength). The default 1.0 is a WEAK
    # per-pixel signal — a finite replayed epoch lets a big model
    # memorize instead of generalize and val sits at chance; raise it
    # (e.g. 4.0) when a run needs the val metrics to really track
    # learning, as real ImageNet's do (examples/workflow_rehearsal.py).
    synthetic_signal: float = 1.0
    # ResNet family: BatchNorm moving-average momentum override. None →
    # the Keras-parity 0.99. Lower (e.g. 0.9) for short synthetic runs so
    # inference-mode val metrics converge within the run — see
    # models/resnet.py bn_momentum.
    bn_momentum: Optional[float] = None
    per_replica_batch: int = 32
    val_per_replica_batch: Optional[int] = None
    data_shard: str = "data"  # "data" | "batch" | "none"
    # language-model runs (models named gpt_*): sequence length of the
    # synthetic next-token task; vocab comes from num_classes.
    seq_len: int = 64
    # pad the LM embed/head vocab dim to a multiple (Megatron convention;
    # needed for vocab-parallel TP on real vocab sizes)
    vocab_multiple: int = 1
    # strategy
    strategy: str = "single"  # single|mirrored|multiworker|ps|
    #                           tensor_parallel|expert_parallel|pipeline
    strategy_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # optimizer / schedule
    optimizer: str = "adam"
    learning_rate: float = 1e-3  # Keras Adam default (compile at :62)
    scale_lr: bool = False  # Horovod's 0.1*size rule (-hvd.py:99)
    # compiled step->LR schedule (train/state.py make_schedule); None keeps
    # the reference's callback-driven LR control
    lr_schedule: Optional[str] = None
    lr_schedule_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ema_decay: Optional[float] = None  # EMA of params; eval uses the shadow
    # average gradients over k micro-batches per optimizer update (large
    # effective batch without the HBM)
    gradient_accumulation_steps: Optional[int] = None
    epochs: int = 50  # reference (imagenet-resnet50.py:67)
    steps_per_epoch: Optional[int] = None
    warmup_epochs: int = 0  # hvd preset: 3 (-hvd.py:114)
    # reference callbacks (imagenet-resnet50.py:64-65)
    reduce_lr_on_plateau: bool = True
    early_stopping: bool = True
    # augmentation (model-graph layers :53-55; crop 160 in hvd :89)
    crop: Optional[int] = None  # None → image_size
    flip: bool = True
    # persistence
    checkpoint_dir: Optional[str] = None
    resume: bool = False
    # Step-granular verified checkpointing cadence (CheckpointEveryN):
    # None keeps epoch-granular saves only. With --resume, a killed run
    # restarts MID-epoch from the newest verified save.
    checkpoint_every_steps: Optional[int] = None
    save_path: Optional[str] = None  # final export (model.save analogue :69-72)
    # observability
    profile_dir: Optional[str] = None  # jax.profiler traces (utils/profiling)
    # misc
    seed: int = 0
    verbose: int = 2  # reference verbose=2 (:67)

    def replace(self, **kw) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)


# One preset per reference script. The pretrained variants carry
# weights="imagenet" like the reference; the file resolves from the local
# cache (or --pretrained-h5 / --download-weights — TPU hosts can't download
# Keras weights implicitly).
PRESETS: Dict[str, ExperimentConfig] = {
    # imagenet-resnet50.py — single device, from scratch
    "single": ExperimentConfig(
        name="ResNet50_ImageNet", strategy="single", per_replica_batch=32,
    ),
    # imagenet-pretrained-resnet50.py — single device, frozen-BN fine-tune
    "single-pretrained": ExperimentConfig(
        name="ResNet50_ImageNet_pretrained", weights="imagenet", strategy="single",
        per_replica_batch=32, bn_mode="frozen",
    ),
    # imagenet-resnet50-mirror.py — single-host sync DP, 32×replicas
    "mirrored": ExperimentConfig(
        name="ResNet50_ImageNet_mirror", strategy="mirrored",
        per_replica_batch=32,
    ),
    # imagenet-pretrained-resnet50-mirror.py
    "mirrored-pretrained": ExperimentConfig(
        name="ResNet50_ImageNet_mirror_pretrained", weights="imagenet", strategy="mirrored",
        per_replica_batch=32, bn_mode="frozen",
    ),
    # imagenet-resnet50-multiworkers.py — multi-host DP, 128×n train/256×n val
    "multiworker": ExperimentConfig(
        name="ResNet50_ImageNet_multiworker", strategy="multiworker",
        per_replica_batch=128, val_per_replica_batch=256, data_shard="data",
    ),
    # imagenet-pretrained-resnet50-multiworkers.py — 32×n both, frozen BN
    "multiworker-pretrained": ExperimentConfig(
        name="ResNet50_ImageNet_multiworker_pretrained", weights="imagenet", strategy="multiworker",
        per_replica_batch=32, bn_mode="frozen",
    ),
    # imagenet-resnet50-hvd.py — DP with hvd semantics: LR 0.1×size,
    # 3-epoch warmup, post-batch sharding, crop 160 (:89,99,114,77-81).
    # ReduceLROnPlateau + EarlyStopping run alongside the warmup callbacks
    # exactly as in the reference's callback list (:106-107); warmup owns
    # the LR for epochs 0-2 (it re-sets it every batch), plateau reductions
    # stick only once warmup releases — see
    # tests/test_callbacks.py::test_warmup_and_plateau_compose.
    "hvd": ExperimentConfig(
        name="ResNet50_ImageNet_hvd", strategy="multiworker",
        per_replica_batch=32, data_shard="batch", learning_rate=0.1,
        scale_lr=True, warmup_epochs=3, crop=160,
    ),
    # imagenet-resnet50-ps.py — sharded-state PS analogue, repeated stream
    # with fixed steps/epoch (:118-119,142-143 — we default to data-derived
    # steps rather than the reference's wrong 312500). The reference PS
    # script keeps both val_loss callbacks too (:139-140).
    "ps": ExperimentConfig(
        name="ResNet50_ImageNet_ps", strategy="ps", per_replica_batch=32,
    ),
}


def get_preset(name: str, **overrides) -> ExperimentConfig:
    try:
        cfg = PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown preset {name!r}; known: {sorted(PRESETS)}"
        ) from None
    return cfg.replace(**overrides) if overrides else cfg
