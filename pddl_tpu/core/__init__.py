"""Core substrate: device meshes, multi-host bootstrap, collectives, sharding.

This layer is the TPU-native replacement for everything the reference pulls in
as external native machinery (SURVEY.md §2b): NCCL rings become XLA
collectives compiled over ICI, ``SlurmClusterResolver`` / ``hvd.init()`` /
in-process gRPC clusters become ``jax.distributed.initialize`` + one
``jax.sharding.Mesh``, and parameter-server variable placement becomes
``NamedSharding`` with a min-size partitioner.
"""
