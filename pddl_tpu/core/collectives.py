"""Named-axis collectives: the NCCL/MPI/Horovod-core replacement.

Every cross-device primitive the reference obtains from native libraries —
NCCL all-reduce inside ``MirroredStrategy`` / ``MultiWorkerMirroredStrategy``
(``/root/reference/imagenet-resnet50-mirror.py:21``,
``imagenet-resnet50-multiworkers.py:19-21``), Horovod's ring all-reduce and
broadcast (``imagenet-resnet50-hvd.py:101,111``) — maps here to an XLA
collective compiled over ICI/DCN. These helpers are usable in two regimes:

1. **inside ``jax.shard_map``** (per-shard view): the functions below call
   ``lax.psum`` etc. with a mesh axis name.
2. **implicit, under ``jit`` with shardings** (global view): you usually do
   not need explicit collectives at all — a mean over a ``data``-sharded
   batch dimension *is* the gradient all-reduce; XLA inserts the transfer.
   The trainer (``pddl_tpu.train.loop``) uses this regime.

Regime 2 is the idiomatic TPU path; regime 1 exists for the Horovod-compat
shim, ring attention, and anywhere explicit per-replica code is clearer.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def psum(tree: PyTree, axis_name: str | Sequence[str]) -> PyTree:
    """All-reduce-sum a pytree over a named mesh axis (NCCL allreduce)."""
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def pmean(tree: PyTree, axis_name: str | Sequence[str]) -> PyTree:
    """All-reduce-mean — gradient averaging (``hvd.DistributedOptimizer``,
    ``/root/reference/imagenet-resnet50-hvd.py:101``) and metric averaging
    (``MetricAverageCallback``, ``:112-113``)."""
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def pmax(tree: PyTree, axis_name: str | Sequence[str]) -> PyTree:
    return jax.tree.map(lambda x: lax.pmax(x, axis_name), tree)


def pmin(tree: PyTree, axis_name: str | Sequence[str]) -> PyTree:
    return jax.tree.map(lambda x: lax.pmin(x, axis_name), tree)


def broadcast(tree: PyTree, axis_name: str, root: int = 0) -> PyTree:
    """Broadcast ``root``'s values to every member of the axis.

    The ``hvd.callbacks.BroadcastGlobalVariablesCallback(0)`` analogue
    (``/root/reference/imagenet-resnet50-hvd.py:111``): used to force
    bitwise-identical initial weights across replicas. Under SPMD with
    replicated params this is a no-op by construction; the helper exists for
    per-replica (shard_map) code paths and for restoring from per-host state.
    """

    def _bcast(x: jnp.ndarray) -> jnp.ndarray:
        # Select root's shard on every member: gather along the axis, index.
        gathered = lax.all_gather(x, axis_name)
        return gathered[root]

    return jax.tree.map(_bcast, tree)


def all_gather(tree: PyTree, axis_name: str, *, axis: int = 0, tiled: bool = False) -> PyTree:
    """Gather per-replica values along a new (or tiled) leading axis."""
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree
    )


def reduce_scatter(tree: PyTree, axis_name: str, *, scatter_axis: int = 0) -> PyTree:
    """Sum-reduce across the axis, scattering shards — ZeRO-style gradient
    sharding; rides ICI at half the cost of allreduce when state is sharded."""
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True),
        tree,
    )


def ppermute_ring(x: jnp.ndarray, axis_name: str, *, shift: int = 1) -> jnp.ndarray:
    """Rotate shards around the ring: member i sends to (i+shift) % n.

    The building block for ring attention (:mod:`pddl_tpu.ops.ring_attention`)
    — neighbor exchange rides ICI at full bisection bandwidth.
    """
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str) -> jnp.ndarray:
    """This member's coordinate along the axis (``hvd.rank()`` analogue in
    traced code)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    """Static size of a named axis (``hvd.size()`` analogue in traced code).

    Version-gated like :func:`pddl_tpu.core.mesh.shard_map`: newer jax
    spells it ``lax.axis_size``; older releases expose the frame via
    ``jax.core.axis_frame`` (which, depending on the release, returns
    either the size itself or a frame object carrying ``.size``)."""
    sz = getattr(lax, "axis_size", None)
    if sz is not None:
        return sz(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def pcast_varying(x: jnp.ndarray, axis_name) -> jnp.ndarray:
    """Mark ``x`` device-varying along ``axis_name`` for the
    varying-manual-axes checker (``lax.pcast(..., to="varying")``).

    A no-op on pre-vma jax: the compat
    :func:`pddl_tpu.core.mesh.shard_map` disables the legacy replication
    checker there, so there is no vma state to update and the values
    are already per-shard."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_name, to="varying")
