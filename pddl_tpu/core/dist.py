"""Multi-host bootstrap: the TPU-native cluster-resolution layer.

Replaces, capability-for-capability, the reference's three bootstrap
mechanisms (SURVEY.md §1 "Cluster bootstrap / resolution"):

- ``tf.distribute.cluster_resolver.SlurmClusterResolver``
  (``/root/reference/imagenet-resnet50-multiworkers.py:16``): cluster spec
  derived from ``SLURM_*`` env vars.
- ``hvd.init()`` MPI rendezvous (``/root/reference/imagenet-resnet50-hvd.py:16``).
- the in-process gRPC server cluster of the PS script
  (``/root/reference/imagenet-resnet50-ps.py:31-65``).

On TPU none of those exist: every host calls
``jax.distributed.initialize(coordinator, num_processes, process_id)`` once,
after which ``jax.devices()`` is the global pod slice and XLA compiles
collectives over ICI/DCN directly — there is no user-visible transport.

Discovery order for coordinator/process info:

1. explicit arguments,
2. ``PDDL_COORDINATOR`` / ``PDDL_NUM_PROCESSES`` / ``PDDL_PROCESS_ID`` env,
3. Slurm env (``SLURM_STEP_NODELIST``/``SLURM_NTASKS``/``SLURM_PROCID``),
   mirroring the reference's use of ``SLURM_NTASKS``
   (``imagenet-resnet50-multiworkers.py:29``),
4. Cloud TPU pod metadata: when none of the above are present but the env
   advertises a multi-host TPU slice (``TPU_WORKER_HOSTNAMES`` with more
   than one host), :func:`initialize` defers to the argument-less
   ``jax.distributed.initialize()``, which self-resolves from TPU metadata.

Single-process runs skip initialization entirely, so the same training
script works from a laptop CPU to a pod slice unchanged.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import Optional

import jax

log = logging.getLogger(__name__)

_DEFAULT_PORT = 8476
_initialized = False


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """Resolved multi-host process layout (the ``ClusterSpec`` analogue)."""

    coordinator_address: Optional[str]
    num_processes: int
    process_id: int

    @property
    def is_multiprocess(self) -> bool:
        return self.num_processes > 1


def _first_slurm_host(nodelist: str) -> str:
    """Expand the first host of a Slurm nodelist like ``nid[001-004]``.

    Mirrors what ``SlurmClusterResolver`` does internally to pick worker 0
    as chief.
    """
    m = re.match(r"([^\[,]+)(?:\[(\d+)[-,\d]*\])?", nodelist.strip())
    if not m:
        return nodelist.split(",")[0]
    base, first = m.group(1), m.group(2)
    return f"{base}{first}" if first else base


def resolve_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> ClusterSpec:
    """Resolve the process layout from args > PDDL_* env > Slurm env."""
    env = os.environ
    coord = coordinator_address or env.get("PDDL_COORDINATOR")
    nproc = num_processes if num_processes is not None else _int_env("PDDL_NUM_PROCESSES")
    pid = process_id if process_id is not None else _int_env("PDDL_PROCESS_ID")

    if nproc is None and "SLURM_NTASKS" in env:
        nproc = int(env["SLURM_NTASKS"])
    if pid is None and "SLURM_PROCID" in env:
        pid = int(env["SLURM_PROCID"])
    if coord is None and "SLURM_STEP_NODELIST" in env:
        coord = f"{_first_slurm_host(env['SLURM_STEP_NODELIST'])}:{_DEFAULT_PORT}"
    elif coord is None and "SLURM_JOB_NODELIST" in env:
        coord = f"{_first_slurm_host(env['SLURM_JOB_NODELIST'])}:{_DEFAULT_PORT}"

    return ClusterSpec(coord, nproc or 1, pid or 0)


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def _tpu_pod_host_count() -> int:
    """Host count advertised by Cloud TPU metadata env, 1 if absent."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) or 1


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> ClusterSpec:
    """Initialize multi-host JAX if (and only if) running multi-process.

    Idempotent. The single call that replaces the reference's entire
    resolver + NCCL-options + gRPC-server bootstrap surface.
    """
    global _initialized
    spec = resolve_cluster(coordinator_address, num_processes, process_id)
    if not spec.is_multiprocess and not _initialized and _tpu_pod_host_count() > 1:
        # Cloud TPU pod with no explicit/Slurm config: jax self-resolves
        # coordinator + process ids from TPU metadata.
        log.info("jax.distributed.initialize() from TPU pod metadata")
        jax.distributed.initialize()
        _initialized = True
        return ClusterSpec(None, jax.process_count(), jax.process_index())
    if spec.is_multiprocess and not _initialized:
        log.info(
            "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
            spec.coordinator_address, spec.num_processes, spec.process_id,
        )
        jax.distributed.initialize(
            coordinator_address=spec.coordinator_address,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
        )
        _initialized = True
    return spec


def process_index() -> int:
    """This host's index (Horovod ``rank()`` / TF ``task_id`` analogue)."""
    return jax.process_index()


def process_count() -> int:
    """Number of participating hosts (Horovod ``size()`` at host granularity)."""
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the chief host — gates logging/saving the way the reference
    gates on ``hvd.rank() == 0`` (``imagenet-resnet50-hvd.py:28,96,117``)."""
    return jax.process_index() == 0
