"""Device-mesh construction.

TPU-native replacement for the reference's cluster/strategy device handling:

- ``tf.distribute.MirroredStrategy`` device enumeration
  (``/root/reference/imagenet-resnet50-mirror.py:21``) → a single-host mesh
  over ``jax.local_devices()``.
- ``SlurmClusterResolver`` + ``MultiWorkerMirroredStrategy``
  (``/root/reference/imagenet-resnet50-multiworkers.py:16-25``) → a global
  mesh over ``jax.devices()`` after ``jax.distributed.initialize`` (see
  :mod:`pddl_tpu.core.dist`).
- Horovod's rank/size world (``/root/reference/imagenet-resnet50-hvd.py:16``)
  → the same mesh; ranks are positions along the ``data`` axis.

Axis conventions (all optional except ``data``):

========  =============================================================
``data``  data parallelism (batch sharding, gradient all-reduce via ICI)
``model`` tensor parallelism (Megatron weight sharding,
          :mod:`pddl_tpu.parallel.tensor_parallel`)
``seq``   sequence/context parallelism (ring attention, long context)
``expert`` expert parallelism for MoE layers (:mod:`pddl_tpu.ops.moe`)
``stage`` pipeline parallelism (GPipe microbatch pipeline,
          :mod:`pddl_tpu.ops.pipeline`)
========  =============================================================

The mesh is the *only* place device topology appears; everything above it
(strategies, trainer, models) speaks named axes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names, in canonical order.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"  # pipeline parallelism (GPipe microbatch pipeline)
CANONICAL_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS, STAGE_AXIS)


def local_device_count() -> int:
    """Number of accelerator devices attached to this process."""
    return jax.local_device_count()


def global_device_count() -> int:
    """Number of devices across all processes (the "world size" analogue)."""
    return jax.device_count()


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape.

    Any axis may be ``-1`` meaning "all remaining devices". Axes of size 1
    are kept in the mesh (they cost nothing and keep sharding rules uniform
    across strategies).

    Example::

        MeshConfig(data=-1)                  # pure data parallel
        MeshConfig(data=-1, model=2)         # DP x TP
        MeshConfig(data=2, seq=4)            # DP x sequence parallel
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1
    # Restrict to this process's local devices (mirrored strategy) instead of
    # the global device set (multi-worker).
    local_only: bool = False

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = {
            DATA_AXIS: self.data,
            MODEL_AXIS: self.model,
            SEQ_AXIS: self.seq,
            EXPERT_AXIS: self.expert,
            STAGE_AXIS: self.stage,
        }
        for name, s in sizes.items():
            if s == 0 or s < -1:
                raise ValueError(f"mesh axis {name!r} size must be >= 1 or -1, got {s}")
        wildcard = [name for name, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh shape {sizes} needs {fixed} devices, have {n_devices}"
            )
        return sizes


def build_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` from a :class:`MeshConfig`.

    ``build_mesh()`` with no arguments gives the canonical data-parallel mesh
    over all devices — the TPU-native analogue of constructing a
    ``MirroredStrategy``/``MultiWorkerMirroredStrategy`` in the reference.

    Axis sizes can also be passed directly: ``build_mesh(data=4, model=2)``.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")

    if devices is None:
        devices = jax.local_devices() if config.local_only else jax.devices()
    devices = list(devices)
    sizes = config.axis_sizes(len(devices))
    shape = tuple(sizes[a] for a in CANONICAL_AXES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_AXES)


def mesh_num_replicas(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """Replica count along a mesh axis — the ``strategy.num_replicas_in_sync``
    analogue (reference scales batch by it: ``imagenet-resnet50-mirror.py:54``).
    """
    return mesh.shape[axis]


def validate_divisible(batch_size: int, mesh: Mesh, axis: str = DATA_AXIS) -> None:
    n = mesh_num_replicas(mesh, axis)
    if batch_size % n != 0:
        raise ValueError(
            f"global batch {batch_size} not divisible by {axis}-axis size {n}"
        )


def describe(mesh: Mesh) -> str:
    """Human-readable one-liner for logs."""
    axes = ", ".join(f"{a}={s}" for a, s in mesh.shape.items() if s > 1) or "1 device"
    plat = mesh.devices.flat[0].platform
    return f"Mesh({axes}) on {mesh.devices.size} {plat} device(s)"
