"""Device-mesh construction.

TPU-native replacement for the reference's cluster/strategy device handling:

- ``tf.distribute.MirroredStrategy`` device enumeration
  (``/root/reference/imagenet-resnet50-mirror.py:21``) → a single-host mesh
  over ``jax.local_devices()``.
- ``SlurmClusterResolver`` + ``MultiWorkerMirroredStrategy``
  (``/root/reference/imagenet-resnet50-multiworkers.py:16-25``) → a global
  mesh over ``jax.devices()`` after ``jax.distributed.initialize`` (see
  :mod:`pddl_tpu.core.dist`).
- Horovod's rank/size world (``/root/reference/imagenet-resnet50-hvd.py:16``)
  → the same mesh; ranks are positions along the ``data`` axis.

Axis conventions (all optional except ``data``):

========  =============================================================
``data``  data parallelism (batch sharding, gradient all-reduce via ICI)
``model`` tensor parallelism (Megatron weight sharding,
          :mod:`pddl_tpu.parallel.tensor_parallel`)
``seq``   sequence/context parallelism (ring attention, long context)
``expert`` expert parallelism for MoE layers (:mod:`pddl_tpu.ops.moe`)
``stage`` pipeline parallelism (GPipe microbatch pipeline,
          :mod:`pddl_tpu.ops.pipeline`)
========  =============================================================

The mesh is the *only* place device topology appears; everything above it
(strategies, trainer, models) speaks named axes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def mesh_context(mesh: Mesh):
    """Ambient-mesh context manager across jax versions.

    Newer jax spells it ``jax.set_mesh`` (with ``jax.sharding.use_mesh``
    as the intermediate name); older releases use the ``Mesh`` object's
    own context manager. The shardings this repo passes to ``jit`` are
    explicit ``NamedSharding``s that carry their mesh, so the ambient
    context is belt-and-braces — but version-gating it here keeps the
    Trainer importable and RUNNABLE on every jax the container ships
    instead of failing at the first ``init_state``.
    """
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        return use_mesh(mesh)
    return mesh  # old-style: `with mesh:` sets the ambient mesh


def has_vma_checking() -> bool:
    """True when this jax has the top-level ``jax.shard_map`` with the
    varying-manual-axes checker (``check_vma``). Older releases only
    ship ``jax.experimental.shard_map`` whose ``check_rep`` checker
    predates vma propagation; tests pinning checker behaviour gate on
    this instead of erroring at collection."""
    return hasattr(jax, "shard_map")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              **kwargs):
    """Version-gated ``jax.shard_map`` (the :func:`mesh_context` trick
    applied to per-shard mapping).

    Newer jax spells it ``jax.shard_map(..., check_vma=...)``; the
    container's older release only has
    ``jax.experimental.shard_map.shard_map(..., check_rep=...)``. The
    fallback always disables the legacy replication checker: it
    predates varying-manual-axes propagation and rejects patterns
    (pallas kernels, psum-into-replicated) the modern checker accepts,
    and disabling it never affects numerics — only whether the claimed
    out_specs replication is verified. Every shard_map in this repo
    (flash/ring attention, the GPipe pipeline, the collectives tests)
    goes through here so one jax upgrade flips them all together.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kwargs)
    from jax.experimental.shard_map import shard_map as sm_exp  # noqa: PLC0415

    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False, **kwargs)

# Canonical axis names, in canonical order.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"  # pipeline parallelism (GPipe microbatch pipeline)
CANONICAL_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS, STAGE_AXIS)


def local_device_count() -> int:
    """Number of accelerator devices attached to this process."""
    return jax.local_device_count()


def global_device_count() -> int:
    """Number of devices across all processes (the "world size" analogue)."""
    return jax.device_count()


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape.

    Any axis may be ``-1`` meaning "all remaining devices". Axes of size 1
    are kept in the mesh (they cost nothing and keep sharding rules uniform
    across strategies).

    Example::

        MeshConfig(data=-1)                  # pure data parallel
        MeshConfig(data=-1, model=2)         # DP x TP
        MeshConfig(data=2, seq=4)            # DP x sequence parallel
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1
    # Restrict to this process's local devices (mirrored strategy) instead of
    # the global device set (multi-worker).
    local_only: bool = False

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = {
            DATA_AXIS: self.data,
            MODEL_AXIS: self.model,
            SEQ_AXIS: self.seq,
            EXPERT_AXIS: self.expert,
            STAGE_AXIS: self.stage,
        }
        for name, s in sizes.items():
            if s == 0 or s < -1:
                raise ValueError(f"mesh axis {name!r} size must be >= 1 or -1, got {s}")
        wildcard = [name for name, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh shape {sizes} needs {fixed} devices, have {n_devices}"
            )
        return sizes


def build_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` from a :class:`MeshConfig`.

    ``build_mesh()`` with no arguments gives the canonical data-parallel mesh
    over all devices — the TPU-native analogue of constructing a
    ``MirroredStrategy``/``MultiWorkerMirroredStrategy`` in the reference.

    Axis sizes can also be passed directly: ``build_mesh(data=4, model=2)``.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")

    if devices is None:
        devices = jax.local_devices() if config.local_only else jax.devices()
    devices = list(devices)
    sizes = config.axis_sizes(len(devices))
    shape = tuple(sizes[a] for a in CANONICAL_AXES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_AXES)


def slice_groups(
    devices: Sequence[jax.Device],
    num_slices: int | None = None,
) -> list[list[jax.Device]]:
    """Group devices by TPU slice (the ICI domain).

    Slice membership comes from ``device.slice_index`` (multi-slice TPU
    jobs); if absent, from ``process_index`` (multi-host CPU/GPU jobs);
    if neither distinguishes anything, ``num_slices`` splits the device
    list evenly (how tests fake a multi-slice topology on one host).
    """
    devices = list(devices)
    keys = {getattr(d, "slice_index", None) for d in devices}
    if keys != {None}:
        # Heterogeneous sets can expose slice_index on only some devices
        # (int and None mixed); -1 keeps the group keys sortable instead
        # of sorted() raising TypeError on None < int.
        def key(d):
            si = getattr(d, "slice_index", None)
            return -1 if si is None else si
    elif len({d.process_index for d in devices}) > 1:
        key = lambda d: d.process_index  # noqa: E731
    else:
        if not num_slices:
            return [devices]
        if len(devices) % num_slices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into {num_slices} slices"
            )
        per = len(devices) // num_slices
        return [devices[i * per:(i + 1) * per] for i in range(num_slices)]
    groups: dict = {}
    for d in devices:
        groups.setdefault(key(d), []).append(d)
    out = [groups[k] for k in sorted(groups)]
    if num_slices and len(out) != num_slices:
        raise ValueError(
            f"detected {len(out)} slices but num_slices={num_slices}"
        )
    if len({len(g) for g in out}) != 1:
        raise ValueError(
            f"uneven slices: {[len(g) for g in out]} devices per slice"
        )
    return out


def build_hybrid_mesh(
    config: MeshConfig | None = None,
    *,
    dcn_axis: str = DATA_AXIS,
    devices: Sequence[jax.Device] | None = None,
    num_slices: int | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a multi-slice mesh: one axis spans slices over DCN, the rest
    stay inside a slice on ICI.

    The returned object is an ordinary :class:`Mesh` — only the device
    *placement* differs from :func:`build_mesh`: positions along
    ``dcn_axis`` are slice-major (all of slice 0, then slice 1, …), and
    every other axis is laid out within a single slice, so its
    collectives (tensor-parallel all-reduces, ring-attention ppermutes,
    pipeline hops) never cross the slow DCN link. The ``dcn_axis``
    gradient all-reduce lowers to the standard hierarchical pattern:
    reduce over ICI inside each slice, then once over DCN between
    slices. This is the TPU analogue of the reference's NCCL
    intra-node ring + cross-host collective split
    (``imagenet-resnet50-multiworkers.py:19-25``).

    ``num_slices`` is only needed when the devices carry no slice/process
    identity (e.g. the fake CPU mesh in tests).
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")
    if dcn_axis not in CANONICAL_AXES:
        raise ValueError(f"unknown dcn_axis {dcn_axis!r}")

    if devices is None:
        devices = jax.devices()
    groups = slice_groups(devices, num_slices)
    n_slices = len(groups)
    if n_slices == 1:
        return build_mesh(config, devices=devices)

    sizes = config.axis_sizes(len(list(devices)))
    if sizes[dcn_axis] % n_slices != 0:
        raise ValueError(
            f"{dcn_axis}-axis size {sizes[dcn_axis]} not divisible by "
            f"{n_slices} slices"
        )
    per_slice = dict(sizes)
    per_slice[dcn_axis] = sizes[dcn_axis] // n_slices
    per_slice_devices = math.prod(per_slice.values())
    if per_slice_devices != len(groups[0]):
        raise ValueError(
            f"per-slice mesh {per_slice} needs {per_slice_devices} devices "
            f"but each slice has {len(groups[0])} — non-DCN axes must fit "
            "inside one slice"
        )

    # Each slice reshapes to the canonical order with its share of the DCN
    # axis; stacking slice-major along that axis makes position//per_slice
    # the slice id.
    shape = tuple(per_slice[a] for a in CANONICAL_AXES)
    dcn_pos = CANONICAL_AXES.index(dcn_axis)
    blocks = [np.asarray(g).reshape(shape) for g in groups]
    dev_array = np.concatenate(blocks, axis=dcn_pos)
    return Mesh(dev_array, CANONICAL_AXES)


def mesh_num_replicas(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """Replica count along a mesh axis — the ``strategy.num_replicas_in_sync``
    analogue (reference scales batch by it: ``imagenet-resnet50-mirror.py:54``).
    """
    return mesh.shape[axis]


def validate_divisible(batch_size: int, mesh: Mesh, axis: str = DATA_AXIS) -> None:
    n = mesh_num_replicas(mesh, axis)
    if batch_size % n != 0:
        raise ValueError(
            f"global batch {batch_size} not divisible by {axis}-axis size {n}"
        )


def describe(mesh: Mesh) -> str:
    """Human-readable one-liner for logs."""
    axes = ", ".join(f"{a}={s}" for a, s in mesh.shape.items() if s > 1) or "1 device"
    plat = mesh.devices.flat[0].platform
    return f"Mesh({axes}) on {mesh.devices.size} {plat} device(s)"
