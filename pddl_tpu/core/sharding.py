"""Sharding rules: NamedSharding helpers + the parameter-server partitioner.

The reference's parameter-server mode shards variables across PS hosts with
``tf.distribute.experimental.partitioners.MinSizePartitioner(min_shard_bytes=
256 << 10, max_shards=NUM_PS)`` (``/root/reference/imagenet-resnet50-ps.py:75-78``).
On TPU there is no RPC variable hosting; the capability maps to *sharded
state under SPMD*: parameters / optimizer state whose size crosses the
threshold are sharded along a mesh axis with ``NamedSharding``, everything
else is replicated. XLA then materializes gathers/scatters over ICI — the
push/pull traffic of a parameter server without a data plane to operate.
This is the honest TPU analogue (sync SPMD rather than async RPC; SURVEY.md
§7 "PS capability mapping").
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any

REPLICATED = PartitionSpec()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, REPLICATED)


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def tree_shardings(mesh: Mesh, tree: PyTree, spec_fn) -> PyTree:
    """Map ``spec_fn(path, leaf) -> PartitionSpec`` over a pytree into
    NamedShardings."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_fn(path, leaf)), tree
    )


@functools.lru_cache(maxsize=32)  # bounded: keys hold Mesh/device handles
def _factored_mesh(mesh: Mesh, axis_name: str, k: int) -> Mesh:
    """A two-axis view of ``mesh``'s devices: ``k``-way shard × replicate.

    Same devices in the same order, reshaped ``(k, n_devices // k)`` with
    axis names ``_<axis>_shard`` / ``_<axis>_repl``. A ``NamedSharding``
    over this mesh placing a tensor dimension on ``_<axis>_shard`` lowers to
    GSPMD's ``last_tile_dim_replicate`` layout — ``k``-way sharded, each
    shard replicated over a contiguous run of ``n // k`` devices — which is
    how a 2..N-way shard count rides an N-device axis. jit accepts it
    alongside shardings of the parent mesh (same device assignment).
    """
    devices = np.asarray(mesh.devices).reshape(k, -1)
    return Mesh(devices, (f"_{axis_name}_shard", f"_{axis_name}_repl"))


@dataclasses.dataclass(frozen=True)
class MinSizePartitioner:
    """Shard tensors along one dimension of a mesh axis, min-size gated.

    Capability analogue of TF's ``MinSizePartitioner``
    (``/root/reference/imagenet-resnet50-ps.py:75-78``): a variable is split
    along its largest dimension only if every resulting shard stays at least
    ``min_shard_bytes`` and the split does not exceed ``max_shards``;
    otherwise it stays whole (replicated).

    Mapping note: TF returns a free shard *count* (1..max_shards) consumed
    by the PS runtime; XLA requires uniform tiling. The mapping: the TF
    count is rounded DOWN to the largest divisor of the axis size that also
    divides one of the tensor's dimensions. A full-axis count shards over
    the named mesh axis; an intermediate count (2..N-1) shards over a
    factored sub-axis view of the same devices
    (``k``-way split, each shard replicated over ``N/k`` neighbours — see
    :func:`_factored_mesh`); a count of 1 replicates. ``num_shards``
    reports the raw TF-equivalent count for parity checks.
    """

    min_shard_bytes: int = 256 << 10  # 256 KiB, the reference's value (:77)
    max_shards: Optional[int] = None  # defaults to the mesh axis size
    axis_name: str = "data"

    def num_shards(self, shape: tuple[int, ...], dtype, axis_size: int) -> int:
        """How many shards the reference partitioner would produce."""
        if not shape:
            return 1
        nbytes = math.prod(shape) * np.dtype(dtype).itemsize
        limit = self.max_shards if self.max_shards is not None else axis_size
        limit = min(limit, axis_size)
        # At least min_shard_bytes per shard, at most `limit` shards.
        by_size = max(1, nbytes // self.min_shard_bytes)
        return int(min(by_size, limit, max(shape)))

    def feasible_shards(
        self, shape: tuple[int, ...], dtype, axis_size: int
    ) -> tuple[int, Optional[int]]:
        """``(count, dim)`` achievable under XLA's even tiling.

        The largest divisor of ``axis_size`` that is ≤ the TF shard count
        and evenly divides some tensor dimension (largest dimension
        preferred), with the dimension it splits. ``(1, None)`` when no
        such split exists.
        """
        n_tf = self.num_shards(shape, dtype, axis_size)
        if n_tf <= 1:
            return 1, None
        dims_by_size = sorted(range(len(shape)), key=lambda d: -shape[d])
        for n in range(axis_size, 1, -1):
            if axis_size % n or n > n_tf:
                continue
            for d in dims_by_size:
                if shape[d] % n == 0:
                    return n, d
        return 1, None

    @staticmethod
    def _dim_spec(d: int, axis: str) -> PartitionSpec:
        """PartitionSpec placing tensor dimension ``d`` on mesh axis ``axis``."""
        spec = [None] * (d + 1)
        spec[d] = axis
        return PartitionSpec(*spec)

    def spec(self, shape: tuple[int, ...], dtype, axis_size: int) -> PartitionSpec:
        """PartitionSpec for one tensor on the NAMED axis (full-axis only).

        ``PartitionSpec`` can only express whole-axis tiling; intermediate
        shard counts need :meth:`sharding` (which can return a factored
        sub-axis layout). Kept for callers that must stay on the parent
        mesh's axis names.
        """
        n, d = self.feasible_shards(shape, dtype, axis_size)
        if n != axis_size:
            return REPLICATED
        return self._dim_spec(d, self.axis_name)

    def sharding(self, mesh: Mesh, shape: tuple[int, ...], dtype) -> NamedSharding:
        """The tensor's placement on ``mesh`` — the real partitioner API.

        Full-axis counts tile the named axis; intermediate counts (the
        reference's 2..N-way middle ground, ``imagenet-resnet50-ps.py:78``)
        shard a factored view of the same devices; count 1 replicates.
        Sub-axis layouts require every other mesh axis to be size 1 (pure
        data-parallel mesh — the PS topology); otherwise the intermediate
        count falls back to replication.
        """
        axis_size = mesh.shape[self.axis_name]
        n, d = self.feasible_shards(tuple(shape), dtype, axis_size)
        if n == 1:
            return NamedSharding(mesh, REPLICATED)
        if n == axis_size:
            return NamedSharding(mesh, self._dim_spec(d, self.axis_name))
        if any(s > 1 for a, s in mesh.shape.items() if a != self.axis_name):
            # Factoring the whole device set would fold other parallelism
            # axes into the replica groups; stay whole instead.
            return NamedSharding(mesh, REPLICATED)
        sub = _factored_mesh(mesh, self.axis_name, n)
        return NamedSharding(sub, self._dim_spec(d, f"_{self.axis_name}_shard"))

    def tree_specs(self, tree: PyTree, axis_size: int) -> PyTree:
        """PartitionSpecs for a whole pytree (full-axis projection)."""
        return jax.tree.map(
            lambda leaf: self.spec(tuple(leaf.shape), leaf.dtype, axis_size), tree
        )

    def tree_shardings(self, mesh: Mesh, tree: PyTree) -> PyTree:
        return jax.tree.map(
            lambda leaf: self.sharding(mesh, tuple(leaf.shape), leaf.dtype),
            tree,
        )


def shard_tree(tree: PyTree, shardings: PyTree) -> PyTree:
    """Device-put a pytree according to a matching pytree of shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)


def with_sharding_constraint(tree: PyTree, mesh: Mesh, spec: PartitionSpec) -> PyTree:
    """Constrain intermediate values inside jit (layout hints to XLA)."""
    s = NamedSharding(mesh, spec)
    return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, s), tree)
