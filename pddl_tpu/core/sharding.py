"""Sharding rules: NamedSharding helpers + the parameter-server partitioner.

The reference's parameter-server mode shards variables across PS hosts with
``tf.distribute.experimental.partitioners.MinSizePartitioner(min_shard_bytes=
256 << 10, max_shards=NUM_PS)`` (``/root/reference/imagenet-resnet50-ps.py:75-78``).
On TPU there is no RPC variable hosting; the capability maps to *sharded
state under SPMD*: parameters / optimizer state whose size crosses the
threshold are sharded along a mesh axis with ``NamedSharding``, everything
else is replicated. XLA then materializes gathers/scatters over ICI — the
push/pull traffic of a parameter server without a data plane to operate.
This is the honest TPU analogue (sync SPMD rather than async RPC; SURVEY.md
§7 "PS capability mapping").
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any

REPLICATED = PartitionSpec()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, REPLICATED)


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dimension over the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def tree_shardings(mesh: Mesh, tree: PyTree, spec_fn) -> PyTree:
    """Map ``spec_fn(path, leaf) -> PartitionSpec`` over a pytree into
    NamedShardings."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_fn(path, leaf)), tree
    )


@dataclasses.dataclass(frozen=True)
class MinSizePartitioner:
    """Shard tensors along one dimension of a mesh axis, min-size gated.

    Capability analogue of TF's ``MinSizePartitioner``
    (``/root/reference/imagenet-resnet50-ps.py:75-78``): a variable is split
    along its largest dimension only if every resulting shard stays at least
    ``min_shard_bytes`` and the split does not exceed ``max_shards``;
    otherwise it stays whole (replicated).

    Mapping note: TF returns a free shard *count* (1..max_shards) consumed by
    the PS runtime; XLA's ``NamedSharding`` tiles a dimension uniformly over
    the *whole* mesh axis. So sharding here is all-or-nothing per tensor: a
    tensor is laid out split ``axis_size`` ways exactly when the TF
    partitioner would have produced ≥ ``axis_size`` shards (which guarantees
    the per-shard minimum), and is replicated otherwise. ``num_shards``
    reports the TF-equivalent count for parity checks.
    """

    min_shard_bytes: int = 256 << 10  # 256 KiB, the reference's value (:77)
    max_shards: Optional[int] = None  # defaults to the mesh axis size
    axis_name: str = "data"

    def num_shards(self, shape: tuple[int, ...], dtype, axis_size: int) -> int:
        """How many shards the reference partitioner would produce."""
        if not shape:
            return 1
        nbytes = math.prod(shape) * np.dtype(dtype).itemsize
        limit = self.max_shards if self.max_shards is not None else axis_size
        limit = min(limit, axis_size)
        # At least min_shard_bytes per shard, at most `limit` shards.
        by_size = max(1, nbytes // self.min_shard_bytes)
        return int(min(by_size, limit, max(shape)))

    def spec(self, shape: tuple[int, ...], dtype, axis_size: int) -> PartitionSpec:
        """PartitionSpec for one tensor: shard its largest dim if it pays.

        Shards only when splitting ``axis_size`` ways keeps every shard at or
        above ``min_shard_bytes`` and ``max_shards`` permits ``axis_size``
        pieces (see class docstring for the TF→XLA mapping).
        """
        if self.num_shards(shape, dtype, axis_size) < axis_size:
            return REPLICATED
        # Shard the largest dimension that tiles the axis evenly; XLA
        # requires uniform tiling for NamedSharding.
        dims_by_size = sorted(range(len(shape)), key=lambda d: -shape[d])
        for d in dims_by_size:
            if shape[d] % axis_size == 0:
                spec = [None] * (d + 1)
                spec[d] = self.axis_name
                return PartitionSpec(*spec)
        return REPLICATED

    def tree_specs(self, tree: PyTree, axis_size: int) -> PyTree:
        """PartitionSpecs for a whole pytree (params or optimizer state)."""
        return jax.tree.map(
            lambda leaf: self.spec(tuple(leaf.shape), leaf.dtype, axis_size), tree
        )

    def tree_shardings(self, mesh: Mesh, tree: PyTree) -> PyTree:
        specs = self.tree_specs(tree, mesh.shape[self.axis_name])
        return jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs)


def shard_tree(tree: PyTree, shardings: PyTree) -> PyTree:
    """Device-put a pytree according to a matching pytree of shardings."""
    return jax.tree.map(jax.device_put, tree, shardings)


def with_sharding_constraint(tree: PyTree, mesh: Mesh, spec: PartitionSpec) -> PyTree:
    """Constrain intermediate values inside jit (layout hints to XLA)."""
    s = NamedSharding(mesh, spec)
    return jax.tree.map(lambda x: jax.lax.with_sharding_constraint(x, s), tree)
