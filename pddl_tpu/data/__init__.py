"""Data pipelines.

Parity surface: the reference ingests ImageNet-2012 via TFDS + tf.data
(``/root/reference/imagenet-resnet50.py:12-49``) with 224x224 crop/pad
preprocessing, AUTOTUNE-parallel map, drop-remainder batching and prefetch.
Provided here: an equivalent tf.data pipeline (TF CPU-only, feeding JAX
arrays), a pure-NumPy synthetic generator for benches/tests, and per-host
sharding for every scheme the reference uses (auto-shard DATA, post-batch
rank sharding, none).
"""

from pddl_tpu.data.synthetic import SyntheticImageClassification

__all__ = [
    "SyntheticImageClassification",
    "ImageNetConfig",
    "ImageNetDataset",
    "load_imagenet",
]


def __getattr__(name):
    # Lazy: the ImageNet pipeline pulls in TensorFlow only when used.
    if name in ("ImageNetConfig", "ImageNetDataset", "load_imagenet"):
        from pddl_tpu.data import imagenet

        return getattr(imagenet, name)
    raise AttributeError(name)
