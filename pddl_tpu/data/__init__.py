"""Data pipelines.

Parity surface: the reference ingests ImageNet-2012 via TFDS + tf.data
(``/root/reference/imagenet-resnet50.py:12-49``) with 224x224 crop/pad
preprocessing, AUTOTUNE-parallel map, drop-remainder batching and prefetch.
Provided here: an equivalent tf.data pipeline (TF CPU-only, feeding JAX
arrays), a pure-NumPy synthetic generator for benches/tests, and per-host
sharding for every scheme the reference uses (auto-shard DATA, post-batch
rank sharding, none).
"""

from pddl_tpu.data.synthetic import (
    SyntheticImageClassification,
    SyntheticLanguageModeling,
)

__all__ = [
    "SyntheticImageClassification",
    "SyntheticLanguageModeling",
    "ImageNetConfig",
    "ImageNetDataset",
    "load_imagenet",
    "NativeLoader",
    "TFRecordReader",
    "TokenFileDataset",
    "load_token_corpus",
]


_LAZY = {
    # Lazy: tf.data pulls in TensorFlow, the native loaders build the C++
    # library — both only when actually used.
    "ImageNetConfig": "imagenet", "ImageNetDataset": "imagenet",
    "load_imagenet": "imagenet",
    "NativeLoader": "native_loader",
    "TFRecordReader": "tfrecord",
    "TokenFileDataset": "text", "load_token_corpus": "text",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f"pddl_tpu.data.{_LAZY[name]}")
        return getattr(mod, name)
    raise AttributeError(name)
