"""ImageNet-2012 ingest: a CPU tf.data pipeline feeding JAX arrays.

Parity surface (SURVEY.md §2a C7/C8): the reference loads ``imagenet2012``
via TFDS from pre-downloaded tars (``/root/reference/imagenet-resnet50.py:
12-34``), maps a cast→``resize_with_crop_or_pad(224)`` preprocess with
``num_parallel_calls=AUTOTUNE`` (``:36-45``), then ``.batch(B,
drop_remainder=True).prefetch(AUTOTUNE)`` (``:46-49``). Distribution modes
differ only in *sharding*:

- ``MultiWorkerMirroredStrategy``: ``AutoShardPolicy.DATA`` — each worker
  keeps every ``n``-th *example* (``imagenet-resnet50-multiworkers.py:66-69``).
- Horovod: ``.shard(size, rank)`` applied **after** batching — each rank
  keeps every ``n``-th *batch* (``imagenet-resnet50-hvd.py:77-81``).
- single/mirrored: no sharding.

This module reproduces all three as the ``shard`` knob (``"data"`` /
``"batch"`` / ``"none"``). TPU-first split of responsibilities: the host
pipeline only decodes/crops/batches uint8→float32 tensors; normalization and
random augmentation run **on device** inside the jitted step
(:mod:`pddl_tpu.ops.augment`), so host CPU work is minimal and the
augmentations fuse into the compiled step.

TensorFlow is used strictly as a CPU input-pipeline library (accelerators
are hidden from it); every TF import is local so the rest of the framework
works without TF installed.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

IMAGE_SIZE = 224  # the reference's fixed input (imagenet-resnet50.py:40)
NUM_CLASSES = 1000


def _tf():
    """Import TensorFlow lazily, CPU-pinned (it must never grab the TPU)."""
    try:
        import tensorflow as tf  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - env without TF
        raise ImportError(
            "pddl_tpu.data.imagenet needs TensorFlow (CPU) for the tf.data "
            "pipeline; install tensorflow-cpu or use "
            "pddl_tpu.data.SyntheticImageClassification"
        ) from e
    for kind in ("GPU", "TPU"):
        try:
            tf.config.set_visible_devices([], kind)
        except Exception:
            pass
    return tf


@dataclasses.dataclass
class ImageNetConfig:
    """Pipeline configuration (the reference's hard-coded choices, exposed).

    ``global_batch_size`` is the *global* batch; with ``shard="data"`` each
    host batches ``global/process_count`` examples, with ``shard="batch"``
    each host batches the full global size and keeps every ``n``-th batch,
    with ``shard="none"`` every host sees identical global batches.
    """

    data_dir: str = ""
    split: str = "train"
    global_batch_size: int = 32  # reference default (imagenet-resnet50.py:46)
    image_size: int = IMAGE_SIZE
    num_classes: int = NUM_CLASSES
    shard: str = "data"  # "data" | "batch" | "none"
    process_index: int = 0
    process_count: int = 1
    shuffle: bool = True
    shuffle_buffer: int = 2048
    seed: int = 0
    drop_remainder: bool = True  # reference batches drop_remainder=True (:46)
    # .repeat()ed streams à la the PS script (:118-119). Usually
    # unnecessary: under steps_per_epoch the Trainer already re-iterates a
    # finite dataset when it drains (fresh __iter__ per pass). With
    # repeat=True the tf.data stream is endless, so the trainer-level
    # re-iteration never engages and epoch-boundary reshuffling is tf's
    # reshuffle_each_iteration instead of a fresh pipeline pass.
    repeat: bool = False
    cache: bool = False
    dtype: str = "float32"

    @property
    def local_batch_size(self) -> int:
        if self.shard == "data":
            if self.global_batch_size % self.process_count:
                raise ValueError(
                    f"global batch {self.global_batch_size} not divisible by "
                    f"{self.process_count} processes"
                )
            return self.global_batch_size // self.process_count
        return self.global_batch_size


class ImageNetDataset:
    """Re-iterable dataset of ``{"image": f32[B,H,W,3], "label": i32[B]}``.

    Sources, tried in order:

    1. **TFDS** (``tfds.load('imagenet2012')``) when tensorflow_datasets is
       importable and ``data_dir`` holds a prepared TFDS tree — the
       reference's own ingest path (``imagenet-resnet50.py:20-34``).
    2. **TFRecords** matching ``<data_dir>/<split>*`` in the standard
       ImageNet TFRecord schema (``image/encoded``, ``image/class/label``).
    3. **Image folders** ``<data_dir>/<split>/<class_name>/*.JPEG`` with
       classes sorted lexicographically → label ids.

    The pipeline yields host-local numpy batches; hand the iterable to
    ``Trainer.fit`` and the strategy's ``distribute_batch`` assembles the
    global sharded ``jax.Array`` per step.
    """

    def __init__(self, config: ImageNetConfig):
        self.config = config
        self._ds = None  # built lazily; re-iterable once built

    # ------------------------------------------------------------- sources
    def _load_source(self):
        """Return an unbatched tf.data.Dataset of (encoded_or_image, label)."""
        cfg = self.config
        tf = _tf()

        # 1. TFDS tree.
        try:
            import tensorflow_datasets as tfds  # noqa: PLC0415

            if cfg.data_dir and os.path.isdir(
                os.path.join(cfg.data_dir, "imagenet2012")
            ):
                # Seeded file shuffling: every process must see the SAME
                # file order or the downstream per-example ds.shard() keeps
                # overlapping/dropped subsets across hosts.
                ds = tfds.load(
                    "imagenet2012",
                    split=cfg.split,
                    data_dir=cfg.data_dir,
                    shuffle_files=cfg.shuffle,
                    as_supervised=True,  # (image, label), reference :33
                    read_config=tfds.ReadConfig(shuffle_seed=cfg.seed),
                )
                return ds, True  # already-decoded images
        except ImportError:
            pass

        # 2. TFRecord shards.
        pattern = os.path.join(cfg.data_dir, f"{cfg.split}*")
        files = sorted(tf.io.gfile.glob(pattern)) if cfg.data_dir else []
        files = [f for f in files if not os.path.isdir(f)]
        if files:
            file_ds = tf.data.Dataset.from_tensor_slices(files)
            if cfg.shuffle:
                file_ds = file_ds.shuffle(len(files), seed=cfg.seed)
            ds = file_ds.interleave(
                tf.data.TFRecordDataset,
                cycle_length=min(16, len(files)),
                num_parallel_calls=tf.data.AUTOTUNE,
            )

            feature_spec = {
                "image/encoded": tf.io.FixedLenFeature([], tf.string),
                "image/class/label": tf.io.FixedLenFeature([], tf.int64),
            }

            def _parse(record):
                ex = tf.io.parse_single_example(record, feature_spec)
                return ex["image/encoded"], tf.cast(ex["image/class/label"], tf.int64)

            return ds.map(_parse, num_parallel_calls=tf.data.AUTOTUNE), False

        # 3. Image-folder layout.
        split_dir = os.path.join(cfg.data_dir, cfg.split)
        if os.path.isdir(split_dir):
            classes = sorted(
                d for d in os.listdir(split_dir)
                if os.path.isdir(os.path.join(split_dir, d))
            )
            paths, labels = [], []
            for idx, cls in enumerate(classes):
                for fname in sorted(os.listdir(os.path.join(split_dir, cls))):
                    paths.append(os.path.join(split_dir, cls, fname))
                    labels.append(idx)
            if paths:
                ds = tf.data.Dataset.from_tensor_slices(
                    (paths, np.asarray(labels, np.int64))
                )

                def _read(path, label):
                    return tf.io.read_file(path), label

                return ds.map(_read, num_parallel_calls=tf.data.AUTOTUNE), False

        raise FileNotFoundError(
            f"no ImageNet source found under {cfg.data_dir!r} "
            f"(tried TFDS tree, TFRecords {cfg.split}*, and image folders "
            f"{cfg.split}/<class>/); for ImageNet-free runs use "
            "pddl_tpu.data.SyntheticImageClassification"
        )

    # ------------------------------------------------------------ pipeline
    def build(self):
        """Construct the tf.data pipeline (idempotent)."""
        if self._ds is not None:
            return self._ds
        cfg = self.config
        tf = _tf()
        ds, decoded = self._load_source()

        # DATA auto-shard analogue: every process keeps its n-th example
        # (imagenet-resnet50-multiworkers.py:66-69).
        if cfg.shard == "data" and cfg.process_count > 1:
            ds = ds.shard(cfg.process_count, cfg.process_index)

        if cfg.cache:
            ds = ds.cache()
        if cfg.shuffle:
            ds = ds.shuffle(cfg.shuffle_buffer, seed=cfg.seed,
                            reshuffle_each_iteration=True)
        if cfg.repeat:
            ds = ds.repeat()

        size = cfg.image_size

        def _preprocess(image_or_bytes, label):
            # Reference map step: cast float32 + crop/pad to 224
            # (imagenet-resnet50.py:36-41). Decode first for raw sources.
            img = image_or_bytes
            if not decoded:
                img = tf.io.decode_image(
                    img, channels=3, expand_animations=False
                )
            img = tf.cast(img, tf.float32)
            img = tf.image.resize_with_crop_or_pad(img, size, size)
            img.set_shape((size, size, 3))
            return img, tf.cast(label, tf.int32)

        ds = ds.map(_preprocess, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(cfg.local_batch_size, drop_remainder=cfg.drop_remainder)

        # Horovod scheme: shard AFTER batching — each rank keeps every n-th
        # global-size batch (imagenet-resnet50-hvd.py:77-81). Global batch is
        # then B×n with per-rank step count shrunk by n, exactly the
        # reference's (quirky) arithmetic.
        if cfg.shard == "batch" and cfg.process_count > 1:
            ds = ds.shard(cfg.process_count, cfg.process_index)

        ds = ds.prefetch(tf.data.AUTOTUNE)
        self._ds = ds
        return ds

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        ds = self.build()
        for image, label in ds.as_numpy_iterator():
            yield {"image": image, "label": label}

    def cardinality(self) -> int:
        """Known batch count, or -1 (unknown/infinite)."""
        tf = _tf()
        n = int(tf.data.experimental.cardinality(self.build()).numpy())
        return n if n >= 0 else -1


def load_imagenet(
    data_dir: str,
    train_batch_size: int,
    val_batch_size: Optional[int] = None,
    shard: str = "data",
    process_index: int = 0,
    process_count: int = 1,
    image_size: int = IMAGE_SIZE,
    seed: int = 0,
    **kwargs: Any,
) -> Tuple[ImageNetDataset, ImageNetDataset]:
    """Train + validation pipelines, the reference's two splits
    (``imagenet-resnet50.py:33``). Validation never shuffles; with
    ``shard="batch"`` validation is rank-sharded too, reproducing the
    Horovod script's per-rank val metrics (``imagenet-resnet50-hvd.py:81``,
    averaged via ``MetricAverageCallback``)."""
    val_batch_size = val_batch_size or train_batch_size
    common = dict(
        data_dir=data_dir, image_size=image_size, shard=shard,
        process_index=process_index, process_count=process_count, seed=seed,
    )
    # kwargs may override any config field; validation shuffling stays off
    # regardless (reference semantics: the validation split is never
    # shuffled — only `shuffle_files` on train, imagenet-resnet50.py:28-33).
    train_cfg = {**common, "shuffle": True, **kwargs,
                 "split": "train", "global_batch_size": train_batch_size}
    val_cfg = {**common, **kwargs, "shuffle": False,
               "split": "validation", "global_batch_size": val_batch_size}
    train = ImageNetDataset(ImageNetConfig(**train_cfg))
    val = ImageNetDataset(ImageNetConfig(**val_cfg))
    return train, val
