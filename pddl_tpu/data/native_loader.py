"""Python bindings for the native C++ data-loader runtime.

The reference's input pipeline executes inside TensorFlow's C++ tf.data
runtime (SURVEY.md §2b C15); this is the framework's own native equivalent:
``native/pddl_io.cpp`` — a threaded, ring-buffered, deterministic batch
loader for a packed uint8 sample format — bound here with ctypes (no
pybind11). The loader yields the same ``{"image": f32, "label": i32}``
batches as the tf.data and synthetic pipelines, so it drops into
``Trainer.fit`` unchanged.

Workflow::

    # one-time: pack any image source (done per host shard for ImageNet)
    write_packed(path, images_uint8, labels)
    # training: native threads read + batch + prefetch, Python just consumes
    for batch in NativeLoader([path], batch_size=256, num_workers=4): ...

Performance notes: batches are assembled by C++ worker threads overlapping
the device step (the ``.prefetch(AUTOTUNE)`` analogue); ``next`` copies
straight into preallocated numpy buffers (two copies total: file→batch,
batch→numpy); uint8 stays uint8 until the float cast, which happens on
device inside the jitted step via the augment pipeline.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Dict, Iterator, Sequence

import numpy as np

_MAGIC = 0x314C4450  # "PDL1"
_HEADER = struct.Struct("<IIHHHH")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libpddl_io.so"))

_lib = None


def _stale() -> bool:
    """True when the .so is missing or older than any native source."""
    if not os.path.exists(_LIB_PATH):
        return True
    built = os.path.getmtime(_LIB_PATH)
    src_dir = os.path.dirname(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(src_dir, f)) > built
        for f in os.listdir(src_dir)
        if f.endswith((".cpp", ".h")) or f == "Makefile"
    )


def _build_error_detail(e) -> str:
    """Stringify a make failure including the captured compiler stderr."""
    detail = str(e)
    stderr = getattr(e, "stderr", None)
    if stderr:
        detail += "\n" + stderr.decode(errors="replace").strip()
    return detail


def _load_lib(build_if_missing: bool = True):
    global _lib
    if _lib is not None:
        return _lib
    if _stale() and build_if_missing:
        try:
            subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH)],
                           check=True, capture_output=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            # A stale-but-working prebuilt .so beats no loader at all
            # (deployed hosts may lack the toolchain); only a missing
            # library is fatal.
            if not os.path.exists(_LIB_PATH):
                raise RuntimeError(
                    "native loader library missing and build failed: "
                    f"{_build_error_detail(e)}; "
                    f"run `make -C {os.path.dirname(_LIB_PATH)}`"
                ) from e
            import warnings

            warnings.warn(
                f"native sources newer than {_LIB_PATH} but rebuild failed "
                f"({_build_error_detail(e)}); loading the existing library",
                RuntimeWarning,
                stacklevel=2,
            )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.pddl_loader_open.restype = ctypes.c_void_p
    lib.pddl_loader_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.pddl_loader_shape.argtypes = [ctypes.c_void_p] + [
        ctypes.POINTER(ctypes.c_int)] * 3
    lib.pddl_loader_num_samples.restype = ctypes.c_long
    lib.pddl_loader_num_samples.argtypes = [ctypes.c_void_p]
    lib.pddl_loader_batches_per_epoch.restype = ctypes.c_long
    lib.pddl_loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.pddl_loader_next.restype = ctypes.c_int
    lib.pddl_loader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pddl_loader_reset.argtypes = [ctypes.c_void_p]
    lib.pddl_loader_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def build_native() -> None:
    """Explicitly build the native library (``make -C native``)."""
    _load_lib(build_if_missing=True)


def native_available() -> bool:
    """Pure availability probe: True iff the library is built and fresh.

    Deliberately does NOT load the library: caching a stale .so into
    ``_lib`` would pin it for the whole process and defeat the
    rebuild-on-stale path in :func:`_load_lib`.
    """
    return os.path.exists(_LIB_PATH) and not _stale()


class PackedWriter:
    """Streaming PDL1 writer: append samples one by one, count patched on
    close (so converters need not know N up front)."""

    def __init__(self, path: str, height: int, width: int, channels: int):
        self.shape = (height, width, channels)
        self._f = open(path, "wb")
        self._n = 0
        self._f.write(_HEADER.pack(_MAGIC, 0, height, width, channels, 0))

    def add(self, image: np.ndarray, label: int) -> None:
        image = np.ascontiguousarray(image, np.uint8)
        if image.shape != self.shape:
            raise ValueError(f"sample shape {image.shape} != {self.shape}")
        self._f.write(struct.pack("<i", int(label)))
        self._f.write(image.tobytes())
        self._n += 1

    def close(self) -> int:
        if self._f is None:
            return self._n
        self._f.seek(4)
        self._f.write(struct.pack("<I", self._n))
        self._f.close()
        self._f = None
        return self._n

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_packed(path: str, images: np.ndarray, labels: np.ndarray) -> None:
    """Write samples in the PDL1 packed format.

    ``images``: uint8 [N, H, W, C]; ``labels``: int [N].
    """
    images = np.ascontiguousarray(images, np.uint8)
    labels = np.asarray(labels, np.int32)
    if images.ndim != 4 or len(labels) != len(images):
        raise ValueError(f"bad shapes {images.shape} / {labels.shape}")
    n, h, w, c = images.shape
    with PackedWriter(path, h, w, c) as w_:
        for i in range(n):
            w_.add(images[i], int(labels[i]))


class NativeLoader:
    """Re-iterable batch source backed by the C++ runtime.

    Yields ``{"image": [B,H,W,C], "label": int32 [B]}`` — Trainer-
    compatible. Images default to **uint8** (4x less host memory and
    host→device bandwidth than f32; models/augment cast on device); pass
    ``dtype="float32"`` for consumers that need the cast on host.
    ``shard_index/shard_count`` give per-process example sharding (the
    DATA auto-shard analogue). Constructing a loader builds the native
    library on first use if missing (see :func:`build_native`).
    """

    def __init__(self, paths: Sequence[str], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1,
                 prefetch_depth: int = 4, num_workers: int = 2,
                 drop_remainder: bool = True, dtype: str = "uint8"):
        self._lib = _load_lib()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._handle = self._lib.pddl_loader_open(
            arr, len(paths), batch_size, int(shuffle), seed, shard_index,
            shard_count, prefetch_depth, num_workers, int(drop_remainder), 0,
        )
        if not self._handle:
            raise FileNotFoundError(
                f"native loader failed to open {list(paths)} (missing files, "
                "bad magic, or heterogeneous shapes)"
            )
        h, w, c = ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
        self._lib.pddl_loader_shape(self._handle, ctypes.byref(h),
                                    ctypes.byref(w), ctypes.byref(c))
        self.image_shape = (h.value, w.value, c.value)
        self.batch_size = batch_size
        self.dtype = dtype
        self._first_epoch = True

    @property
    def num_samples(self) -> int:
        return self._lib.pddl_loader_num_samples(self._handle)

    @property
    def batches_per_epoch(self) -> int:
        return self._lib.pddl_loader_batches_per_epoch(self._handle)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self._handle is None:
            raise RuntimeError("loader is closed")
        if not self._first_epoch:
            self._lib.pddl_loader_reset(self._handle)
        self._first_epoch = False
        h, w, c = self.image_shape
        images = np.empty((self.batch_size, h, w, c), np.uint8)
        labels = np.empty((self.batch_size,), np.int32)
        img_ptr = images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        lbl_ptr = labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            if self._handle is None:  # close()d mid-iteration
                raise RuntimeError("loader is closed")
            n = self._lib.pddl_loader_next(self._handle, img_ptr, lbl_ptr)
            if n <= 0:
                return
            yield {
                "image": images[:n].astype(self.dtype),
                "label": labels[:n].copy(),
            }

    def close(self) -> None:
        if self._handle is not None:
            self._lib.pddl_loader_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
