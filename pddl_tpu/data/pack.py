"""Offline packing: ImageNet TFRecord shards → PDL1 packed files.

The reference decodes + resizes JPEGs inside tf.data on every epoch of
every run (``/root/reference/imagenet-resnet50.py:36-49``). TPU-first, that
work is one-time: stream the TFDS/ImageNet TFRecords through the native
record layer (:class:`pddl_tpu.data.tfrecord.TFRecordReader` — CRC-checked,
shardable), decode + statically resize each JPEG once on the host, and
write fixed-shape uint8 samples (:class:`pddl_tpu.data.native_loader.PackedWriter`).
Training then runs on the pure-native :class:`NativeLoader` (threaded
reads, ring-buffer prefetch, per-epoch shuffle) with zero per-epoch decode
cost — the random crop/flip augmentation stays on device inside the jitted
step (``pddl_tpu/ops/augment.py``), where the reference ran it too (Keras
preprocessing layers, ``imagenet-resnet50.py:53-55``).

Per-host usage (each host packs its own shard of the record sequence)::

    pack_imagenet_tfrecords(files, f"train-{proc}.pdl1",
                            shard_index=proc, shard_count=n_procs)

TensorFlow (CPU) is used only here, only for JPEG decode + resize.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from pddl_tpu.data.native_loader import PackedWriter
from pddl_tpu.data.tfrecord import TFRecordReader


def pack_imagenet_tfrecords(
    files: Sequence[str],
    out_path: str,
    *,
    image_size: int = 224,
    shard_index: int = 0,
    shard_count: int = 1,
    label_offset: int = 0,
    limit: Optional[int] = None,
    verify: bool = True,
) -> int:
    """Pack this process's shard of ``files`` into one PDL1 file.

    Records must carry the standard ImageNet schema (``image/encoded``
    JPEG bytes, ``image/class/label`` int64 — the TFDS layout the
    reference loads, ``imagenet-resnet50.py:20-34``). Images are resized
    with crop-or-pad to ``image_size`` (the reference's map-time
    preprocess, ``:36-41``) and stored uint8 RGB. Returns the number of
    samples written. ``label_offset`` is added to stored labels (use -1
    for 1-indexed ImageNet label sets).
    """
    import tensorflow as tf  # CPU-only decode/resize, import-heavy

    feature_spec = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    }

    reader = TFRecordReader(
        files, shard_index=shard_index, shard_count=shard_count, verify=verify
    )
    n = 0
    try:
        with PackedWriter(out_path, image_size, image_size, 3) as writer:
            for payload in reader:
                ex = tf.io.parse_single_example(payload, feature_spec)
                image = tf.io.decode_image(
                    ex["image/encoded"], channels=3, expand_animations=False
                )
                image = tf.image.resize_with_crop_or_pad(
                    image, image_size, image_size
                )
                writer.add(
                    image.numpy(),
                    int(ex["image/class/label"]) + label_offset,
                )
                n += 1
                if limit is not None and n >= limit:
                    break
    finally:
        reader.close()
    return n


def main(argv: Optional[Sequence[str]] = None) -> None:
    """CLI: ``python -m pddl_tpu.data.pack <tfrecord>... -o out.pdl1``."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("files", nargs="+", help="input TFRecord shards")
    p.add_argument("-o", "--out", required=True, help="output .pdl1 path")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--shard-index", type=int, default=0)
    p.add_argument("--shard-count", type=int, default=1)
    p.add_argument("--label-offset", type=int, default=0)
    p.add_argument("--limit", type=int, default=None)
    args = p.parse_args(argv)
    n = pack_imagenet_tfrecords(
        args.files, args.out, image_size=args.image_size,
        shard_index=args.shard_index, shard_count=args.shard_count,
        label_offset=args.label_offset, limit=args.limit,
    )
    print(f"packed {n} samples -> {os.path.abspath(args.out)}")


if __name__ == "__main__":
    main()
