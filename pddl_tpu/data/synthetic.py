"""Synthetic image-classification data: deterministic, host-cheap.

Used by tests, benches, and as the fallback when the ImageNet tars the
reference hard-codes (``/root/reference/imagenet-resnet50.py:16-17``,
``/scratch/project_2006142/``) are absent. Samples are generated with a
fixed seed per (epoch, step) so multi-host runs produce identical global
batches without coordination, and each class has a distinct mean so models
can actually fit the data (loss-decreases tests).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator

import numpy as np


@dataclasses.dataclass
class SyntheticImageClassification:
    """Infinite iterable of ``{"image": f32[B,H,W,C], "label": i32[B]}``."""

    batch_size: int = 32  # reference per-replica batch (imagenet-resnet50.py:46)
    image_size: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 0
    # Restrict to this process's share of the global batch (DATA auto-shard
    # analogue): process i of n contributes batch_size/n samples.
    process_index: int = 0
    process_count: int = 1
    signal_strength: float = 1.0  # class-mean separation; 0 = pure noise
    # Offset into the batch-index space: lets a validation split share the
    # task (same seed => same class means) while drawing disjoint samples.
    index_offset: int = 0

    def __post_init__(self):
        if self.batch_size % self.process_count:
            raise ValueError(
                f"batch {self.batch_size} not divisible by {self.process_count} processes"
            )
        self._class_means = None

    @property
    def local_batch_size(self) -> int:
        return self.batch_size // self.process_count

    def _means(self) -> np.ndarray:
        if self._class_means is None:
            rng = np.random.default_rng(self.seed)
            self._class_means = rng.normal(
                size=(self.num_classes, self.channels)
            ).astype(np.float32)
        return self._class_means

    def batch(self, index: int) -> Dict[str, np.ndarray]:
        """Deterministic global batch ``index``, sliced to this process."""
        rng = np.random.default_rng((self.seed, index + self.index_offset))
        labels = rng.integers(0, self.num_classes, size=self.batch_size)
        images = rng.normal(
            size=(self.batch_size, self.image_size, self.image_size, self.channels)
        ).astype(np.float32)
        if self.signal_strength:
            images += self.signal_strength * self._means()[labels][:, None, None, :]
        lo = self.process_index * self.local_batch_size
        hi = lo + self.local_batch_size
        return {"image": images[lo:hi], "label": labels[lo:hi].astype(np.int32)}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        index = 0
        while True:
            yield self.batch(index)
            index += 1
