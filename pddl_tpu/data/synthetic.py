"""Synthetic image-classification data: deterministic, host-cheap.

Used by tests, benches, and as the fallback when the ImageNet tars the
reference hard-codes (``/root/reference/imagenet-resnet50.py:16-17``,
``/scratch/project_2006142/``) are absent. Samples are generated with a
fixed seed per (epoch, step) so multi-host runs produce identical global
batches without coordination, and each class has a distinct mean so models
can actually fit the data (loss-decreases tests).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator

import numpy as np


@dataclasses.dataclass
class SyntheticImageClassification:
    """Infinite iterable of ``{"image": f32[B,H,W,C], "label": i32[B]}``."""

    batch_size: int = 32  # reference per-replica batch (imagenet-resnet50.py:46)
    image_size: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 0
    # Restrict to this process's share of the global batch (DATA auto-shard
    # analogue): process i of n contributes batch_size/n samples.
    process_index: int = 0
    process_count: int = 1
    signal_strength: float = 1.0  # class-mean separation; 0 = pure noise
    # Offset into the batch-index space: lets a validation split share the
    # task (same seed => same class means) while drawing disjoint samples.
    index_offset: int = 0

    def __post_init__(self):
        if self.batch_size % self.process_count:
            raise ValueError(
                f"batch {self.batch_size} not divisible by {self.process_count} processes"
            )
        self._class_means = None

    @property
    def local_batch_size(self) -> int:
        return self.batch_size // self.process_count

    def _means(self) -> np.ndarray:
        if self._class_means is None:
            rng = np.random.default_rng(self.seed)
            self._class_means = rng.normal(
                size=(self.num_classes, self.channels)
            ).astype(np.float32)
        return self._class_means

    def batch(self, index: int) -> Dict[str, np.ndarray]:
        """Deterministic global batch ``index``, sliced to this process."""
        rng = np.random.default_rng((self.seed, index + self.index_offset))
        labels = rng.integers(0, self.num_classes, size=self.batch_size)
        images = rng.normal(
            size=(self.batch_size, self.image_size, self.image_size, self.channels)
        ).astype(np.float32)
        if self.signal_strength:
            images += self.signal_strength * self._means()[labels][:, None, None, :]
        lo = self.process_index * self.local_batch_size
        hi = lo + self.local_batch_size
        return {"image": images[lo:hi], "label": labels[lo:hi].astype(np.int32)}

    def with_offset(self, n: int) -> "SyntheticImageClassification":
        """The same stream positioned ``n`` batches ahead — the
        resumable-loader protocol ``Trainer.fit(resume=...)`` uses to
        reposition the pipeline for free (any dataset exposing
        ``with_offset`` gets exact resume without host-side skipping)."""
        return dataclasses.replace(
            self, index_offset=self.index_offset + int(n))

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        index = 0
        while True:
            yield self.batch(index)
            index += 1


@dataclasses.dataclass
class SyntheticLanguageModeling:
    """Infinite iterable of ``{"tokens": i32[B,S], "targets": i32[B,S]}``.

    Deterministic next-token task: sequences follow the affine recurrence
    ``t[i+1] = (a * t[i] + b) mod vocab`` (a, b drawn from ``seed``), so a
    small causal LM can drive the loss toward zero by learning the
    per-token successor map — a convergence signal for the GPT family and
    the causal flash/ring attention paths.
    """

    batch_size: int = 32
    seq_len: int = 64
    vocab_size: int = 64
    seed: int = 0
    process_index: int = 0
    process_count: int = 1
    index_offset: int = 0

    def __post_init__(self):
        if self.batch_size % self.process_count:
            raise ValueError(
                f"batch {self.batch_size} not divisible by {self.process_count} processes"
            )
        rng = np.random.default_rng(self.seed)
        # a coprime with vocab keeps the orbit long (more pairs to learn).
        self.a = int(rng.integers(1, self.vocab_size) * 2 + 1) % self.vocab_size or 1
        self.b = int(rng.integers(0, self.vocab_size))

    @property
    def local_batch_size(self) -> int:
        return self.batch_size // self.process_count

    def batch(self, index: int) -> Dict[str, np.ndarray]:
        rng = np.random.default_rng((self.seed, index + self.index_offset))
        start = rng.integers(0, self.vocab_size, size=self.batch_size)
        seqs = np.empty((self.batch_size, self.seq_len + 1), np.int64)
        seqs[:, 0] = start
        for i in range(self.seq_len):
            seqs[:, i + 1] = (self.a * seqs[:, i] + self.b) % self.vocab_size
        lo = self.process_index * self.local_batch_size
        hi = lo + self.local_batch_size
        return {"tokens": seqs[lo:hi, :-1].astype(np.int32),
                "targets": seqs[lo:hi, 1:].astype(np.int32)}

    def with_offset(self, n: int) -> "SyntheticLanguageModeling":
        """See :meth:`SyntheticImageClassification.with_offset`."""
        return dataclasses.replace(
            self, index_offset=self.index_offset + int(n))

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        index = 0
        while True:
            yield self.batch(index)
            index += 1
