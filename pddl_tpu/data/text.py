"""Token-corpus pipeline for the language-model family.

The reference is image-only (ResNet-50/ImageNet, SURVEY.md §0); the GPT
family here (``pddl_tpu/models/gpt.py``) is beyond-parity, and this module
gives it a real data path mirroring the ImageNet design: one-time
preparation to a compact binary format, then memory-mapped, shuffled,
per-process-sharded batch iteration with zero per-epoch decode cost.

Format: a flat little-endian ``uint16`` token file (``train.bin`` /
``val.bin``) plus a ``meta.json`` sidecar recording ``vocab_size`` — the
same shape of artifact the packed image loader uses (PDL1), chosen over
raw text so epochs are pure ``memmap`` slicing.

Preparation is byte-level by default (vocab 256, no external tokenizer —
nothing to download on a TPU host); any externally tokenized uint16 file
drops in unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

META_FILE = "meta.json"


def encode_text_file(
    txt_path: str, out_path: str, *, vocab: str = "byte"
) -> Tuple[int, int]:
    """One-time corpus preparation: text → flat uint16 token file.

    ``vocab="byte"`` maps each UTF-8 byte to its value (vocab 256).
    Returns ``(n_tokens, vocab_size)`` and writes ``meta.json`` next to
    ``out_path``.
    """
    if vocab != "byte":
        raise ValueError(f"unknown vocab {vocab!r}; only 'byte' is built in")
    out_dir = os.path.dirname(out_path) or "."
    existing = read_meta(out_dir)
    if existing and existing.get("vocab") not in (None, vocab):
        # An externally tokenized corpus lives here; byte-encoding a split
        # into it would mix token spaces and clobber its sidecar.
        raise ValueError(
            f"{out_dir}/{META_FILE} records vocab={existing.get('vocab')!r} "
            f"(size {existing.get('vocab_size')}); refusing to byte-encode "
            f"{txt_path} into the same corpus"
        )
    data = np.fromfile(txt_path, dtype=np.uint8)
    # Atomic publish: every process of a multi-host job runs preparation
    # concurrently (run.py build_data); os.replace means no reader ever
    # memmaps a half-written file, and identical writers race harmlessly.
    tmp = f"{out_path}.tmp.{os.getpid()}"
    data.astype("<u2").tofile(tmp)
    os.replace(tmp, out_path)

    split = os.path.basename(out_path).rsplit(".", 1)[0]
    meta = existing or {"vocab_size": 256, "vocab": vocab, "n_tokens": {}}
    if not isinstance(meta.get("n_tokens"), dict):  # legacy scalar field
        meta["n_tokens"] = {}
    meta["n_tokens"][split] = int(data.size)
    meta_tmp = os.path.join(out_dir, f"{META_FILE}.tmp.{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(out_dir, META_FILE))
    return int(data.size), 256


def read_meta(data_dir: str) -> Optional[dict]:
    path = os.path.join(data_dir, META_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


@dataclasses.dataclass
class TokenFileDataset:
    """Re-iterable ``{"tokens": i32[B,S], "targets": i32[B,S]}`` batches.

    The file is memory-mapped; an epoch is the deterministic (seeded,
    reshuffled per epoch) order of non-overlapping ``seq_len``-token
    windows, sharded every ``process_count``-th window per process — the
    LM analogue of the image pipelines' DATA sharding. Targets are the
    next-token shift of the window.
    """

    path: str
    batch_size: int  # GLOBAL batch; each process yields its share
    seq_len: int = 64
    shuffle: bool = True
    seed: int = 0
    process_index: int = 0
    process_count: int = 1

    def __post_init__(self):
        if self.batch_size % self.process_count:
            raise ValueError(
                f"batch {self.batch_size} not divisible by "
                f"{self.process_count} processes"
            )
        self._tokens = np.memmap(self.path, dtype="<u2", mode="r")
        # +1: every window needs its successor token for the target shift.
        self._n_windows = (len(self._tokens) - 1) // self.seq_len
        if self._n_windows < 1:
            raise ValueError(
                f"{self.path}: {len(self._tokens)} tokens is shorter than "
                f"one {self.seq_len}-token window"
            )
        self._epoch = 0

    @property
    def local_batch_size(self) -> int:
        return self.batch_size // self.process_count

    @property
    def batches_per_epoch(self) -> int:
        # Floor computed GLOBALLY (smallest shard's share): every process
        # must run the same number of jitted SPMD steps per epoch or the
        # job deadlocks in a collective at epoch end.
        return (self._n_windows // self.process_count) // self.local_batch_size

    def max_token(self) -> int:
        """Largest token id in the file (vocab bound for meta-less .bins)."""
        return int(self._tokens.max()) if len(self._tokens) else 0

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        order = np.arange(self._n_windows)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self._epoch))
            rng.shuffle(order)
        self._epoch += 1
        mine = order[self.process_index::self.process_count]
        lb, S = self.local_batch_size, self.seq_len
        offsets = np.arange(S + 1)
        for i in range(self.batches_per_epoch):
            idxs = mine[i * lb:(i + 1) * lb]
            # One vectorized gather per batch (no per-row Python loop).
            chunks = self._tokens[idxs[:, None] * S + offsets].astype(np.int32)
            yield {"tokens": chunks[:, :-1], "targets": chunks[:, 1:]}


def load_token_corpus(
    data_dir: str,
    *,
    seq_len: int,
    train_batch_size: int,
    val_batch_size: int,
    seed: int = 0,
    process_index: int = 0,
    process_count: int = 1,
) -> Tuple[TokenFileDataset, TokenFileDataset]:
    """Train/val datasets from a corpus directory.

    Accepts either prepared ``train.bin``/``val.bin`` (+ ``meta.json``),
    or raw ``train.txt``/``val.txt`` which are byte-encoded once and
    cached as ``.bin``. A ``val``-less corpus reuses the train file for
    validation — the "val" metrics are then training-set metrics (all
    windows, file order); provide ``val.txt``/``val.bin`` for a real
    held-out split.
    """
    def _ensure(split: str) -> Optional[str]:
        bin_path = os.path.join(data_dir, f"{split}.bin")
        if os.path.exists(bin_path):
            return bin_path
        txt_path = os.path.join(data_dir, f"{split}.txt")
        if os.path.exists(txt_path):
            encode_text_file(txt_path, bin_path)
            return bin_path
        return None

    train_path = _ensure("train")
    if train_path is None:
        raise FileNotFoundError(
            f"no train.bin or train.txt under {data_dir!r} (LM corpora are "
            "a flat uint16 token file; see pddl_tpu.data.text)"
        )
    val_path = _ensure("val") or train_path
    common = dict(seq_len=seq_len, seed=seed, process_index=process_index,
                  process_count=process_count)
    return (
        TokenFileDataset(train_path, batch_size=train_batch_size, **common),
        TokenFileDataset(val_path, batch_size=val_batch_size, shuffle=False,
                         **{**common, "seed": seed + 1}),
    )
