"""TFRecord IO: native C++ reader bindings + pure-Python writer/fallback.

The reference's ImageNet pipeline reads TFDS-prepared TFRecord shards
through TensorFlow's C++ tf.data runtime (SURVEY.md §2b C15 —
``/root/reference/imagenet-resnet50.py:20-34``). This module is the
framework's own record layer for that format:

- :class:`TFRecordReader` — ctypes binding to ``native/pddl_tfrecord.cpp``:
  CRC-validated indexing, per-process sharding, deterministic per-epoch
  shuffling, and a prefetching reader thread. Yields raw record payloads
  (``bytes``); decode (tf.Example, JPEG) happens above, exactly as
  ``tf.data.TFRecordDataset`` is decode-agnostic.
- :func:`write_tfrecord` / :func:`read_tfrecord` — dependency-free Python
  implementations of the framing (u64 length | u32 masked-crc32c(length) |
  payload | u32 masked-crc32c(payload)), used for packing, tests, and as a
  no-native fallback. Byte-compatible with TF's writer/reader.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Iterable, Iterator, List, Optional, Sequence

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) + TFRecord masking, pure Python.

_CRC_TABLE: List[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (0x82F63B78 ^ (_c >> 1)) if (_c & 1) else (_c >> 1)
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord's rotated+offset CRC mask."""
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Pure-Python framing.


def write_tfrecord(path: str, records: Iterable[bytes]) -> int:
    """Write ``records`` in TFRecord framing; returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", masked_crc32c(length)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))
            n += 1
    return n


def read_tfrecord(path: str, verify: bool = True) -> Iterator[bytes]:
    """Sequentially yield record payloads (Python fallback reader)."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            if len(hdr) != 12:
                raise IOError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", hdr[:8])
            (length_crc,) = struct.unpack("<I", hdr[8:])
            if verify and masked_crc32c(hdr[:8]) != length_crc:
                raise IOError(f"{path}: corrupt record length CRC")
            payload = f.read(length)
            footer = f.read(4)
            if len(payload) != length or len(footer) != 4:
                raise IOError(f"{path}: truncated record payload")
            if verify and masked_crc32c(payload) != struct.unpack("<I", footer)[0]:
                raise IOError(f"{path}: payload CRC mismatch")
            yield payload


def count_records(path: str) -> int:
    """Record count by hopping frame lengths (no payload reads/CRCs)."""
    n = 0
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return n
            if len(hdr) != 12:
                raise IOError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", hdr[:8])
            f.seek(length + 4, 1)
            n += 1


# ---------------------------------------------------------------------------
# Native reader binding.

_proto_ready = False


def _tfr_lib():
    """The shared native library with pddl_tfr_* prototypes registered."""
    from pddl_tpu.data.native_loader import _load_lib

    lib = _load_lib()
    global _proto_ready
    if not _proto_ready:
        if not hasattr(lib, "pddl_tfr_open"):
            # A prebuilt library from before the TFRecord layer existed,
            # loaded via the warn-on-rebuild-failure path.
            raise RuntimeError(
                "native library is too old (no pddl_tfr_* symbols); "
                "rebuild with `make -C native`"
            )
        lib.pddl_tfr_open.restype = ctypes.c_void_p
        lib.pddl_tfr_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        for fn in (lib.pddl_tfr_count, lib.pddl_tfr_total_count,
                   lib.pddl_tfr_max_length):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_void_p]
        lib.pddl_tfr_next.restype = ctypes.c_long
        lib.pddl_tfr_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
        ]
        lib.pddl_tfr_reset.argtypes = [ctypes.c_void_p]
        lib.pddl_tfr_close.argtypes = [ctypes.c_void_p]
        lib.pddl_crc32c.restype = ctypes.c_uint32
        lib.pddl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_long]
        lib.pddl_masked_crc32c.restype = ctypes.c_uint32
        lib.pddl_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_long]
        _proto_ready = True
    return lib


def native_crc32c(data: bytes) -> int:
    """CRC32C computed by the native library (for parity tests)."""
    return _tfr_lib().pddl_crc32c(data, len(data))


def native_masked_crc32c(data: bytes) -> int:
    return _tfr_lib().pddl_masked_crc32c(data, len(data))


class TFRecordReader:
    """Re-iterable raw-record source backed by the C++ runtime.

    Each ``iter()`` yields one epoch of payload ``bytes`` in this shard's
    (optionally shuffled) order; shuffling reseeds deterministically per
    epoch. ``shard_index/shard_count`` shard the *global* record sequence
    across processes, every ``shard_count``-th record (the DATA auto-shard
    analogue, ``imagenet-resnet50-multiworkers.py:66-69``).

    Opening validates the framing of every record's length field; payload
    CRCs are checked on read while ``verify=True``. Corrupt files fail at
    construction or raise mid-iteration — never yield garbage.
    """

    def __init__(self, paths: Sequence[str], *, shuffle: bool = False,
                 seed: int = 0, shard_index: int = 0, shard_count: int = 1,
                 verify: bool = True, prefetch_depth: int = 16):
        self._lib = _tfr_lib()
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._handle = self._lib.pddl_tfr_open(
            arr, len(paths), int(shuffle), seed, shard_index, shard_count,
            int(verify), prefetch_depth,
        )
        if not self._handle:
            raise FileNotFoundError(
                f"TFRecordReader failed to open {list(paths)} (missing file, "
                "corrupt framing, or empty shard)"
            )
        self._paths = list(paths)
        self._first_epoch = True

    @property
    def num_records(self) -> int:
        """Records in THIS shard."""
        return self._lib.pddl_tfr_count(self._handle)

    @property
    def total_records(self) -> int:
        """Records across all shards (the full file set)."""
        return self._lib.pddl_tfr_total_count(self._handle)

    def __len__(self) -> int:
        return self.num_records

    def __iter__(self) -> Iterator[bytes]:
        if self._handle is None:
            raise RuntimeError("reader is closed")
        if not self._first_epoch:
            self._lib.pddl_tfr_reset(self._handle)
        self._first_epoch = False
        cap = max(1, self._lib.pddl_tfr_max_length(self._handle))
        buf = (ctypes.c_uint8 * cap)()
        while True:
            if self._handle is None:  # close()d mid-iteration
                raise RuntimeError("reader is closed")
            n = self._lib.pddl_tfr_next(self._handle, buf, cap)
            if n == -4:  # end of epoch (0 is a legal empty record)
                return
            if n < 0:
                if n == -1:
                    raise RuntimeError("reader closed during iteration")
                raise IOError(
                    f"TFRecord read error ({'short buffer' if n == -2 else 'payload CRC/read failure'}) "
                    f"in {self._paths}"
                )
            yield ctypes.string_at(buf, n)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.pddl_tfr_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def open_tfrecords(paths: Sequence[str], *, native: Optional[bool] = None,
                   **kwargs):
    """Best reader available: native if built (or buildable), else Python.

    With ``native=None`` (auto) the library is built on first use when a
    toolchain is present (like :class:`NativeLoader`), falling back to the
    sequential Python reader only when it is genuinely unbuildable; the
    fallback supports just the no-shuffle single-shard case. Forcing
    ``native=True`` raises if the library can't be built.
    """
    if native is None:
        try:
            # _tfr_lib builds on first use AND validates the pddl_tfr_*
            # symbols, so a stale pre-TFRecord .so also falls back.
            _tfr_lib()
            native = True
        except (RuntimeError, OSError):
            native = False
    if native:
        return TFRecordReader(paths, **kwargs)
    if kwargs.get("shuffle") or kwargs.get("shard_count", 1) != 1:
        raise RuntimeError(
            "python TFRecord fallback is sequential/unsharded; build the "
            "native library (make -C native) for shuffle/sharding"
        )

    class _PyReader:
        """Sequential fallback with the TFRecordReader surface."""

        def __iter__(self):
            for p in paths:
                yield from read_tfrecord(p, verify=kwargs.get("verify", True))

        _count = None

        @property
        def num_records(self):
            # Counted once, by frame-length seeks only — len() must not
            # cost a full verified dataset scan.
            if self._count is None:
                self._count = sum(count_records(p) for p in paths)
            return self._count

        total_records = num_records

        def __len__(self):
            return self.num_records

        def close(self):
            pass

    return _PyReader()
