"""Model zoo.

Parity target: the reference's single model, ``tf.keras.applications.ResNet50``
with a 1000-way softmax head (``/root/reference/imagenet-resnet50.py:51-61``).
Provided TPU-native: the full Flax ResNet family (18/34/50/101/152) with exact
Keras architecture parity for pretrained-weight import, plus a Transformer
family exercising the long-context / sequence-parallel ops.
"""

from pddl_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from pddl_tpu.models.vit import ViT, ViT_S16, ViT_B16, ViT_L16
from pddl_tpu.models.llama import (Llama, Llama_1B, Llama_Small,
                                    GPipeLlama, tiny_llama)
from pddl_tpu.models.registry import get_model, register_model, list_models

__all__ = [
    "GPipeLlama",
    "Llama",
    "Llama_1B",
    "Llama_Small",
    "tiny_llama",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "ViT",
    "ViT_S16",
    "ViT_B16",
    "ViT_L16",
    "get_model",
    "register_model",
    "list_models",
]
