"""Generic GPipe model wrapper: embed → staged body → head.

Factors the pipeline-parallel model pattern out of the ViT family so any
embed/stage/head triple pipelines the same way
(:class:`pddl_tpu.models.vit.GPipeViT` for vision,
:class:`pddl_tpu.models.gpt.GPipeGPT` for causal LMs):

- ``embed``/``head`` are ordinary flax modules with replicated params,
  running under plain GSPMD outside the pipeline;
- ``stage`` is one flax module whose params are initialized ``n_stages``
  times and stacked on a leading dim — sharded one-stage-per-position over
  the ``stage`` mesh axis by :class:`pddl_tpu.parallel.pipeline.PipelineStrategy`;
- the schedule is :func:`pddl_tpu.ops.pipeline.gpipe_apply` (scan ticks +
  ppermute hops, AD-derived backward pipeline).

Duck-types the flax ``init``/``apply`` surface the Trainer uses. Stages
run deterministically (no dropout inside the pipeline).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class GPipeModel:
    """Pipeline-parallel model = embed + ``n_stages`` x stage + head."""

    def __init__(self, *, embed, stage, head, n_stages: int,
                 n_microbatches: int, mesh, remat_stages: bool = False):
        from pddl_tpu.core.mesh import STAGE_AXIS

        if mesh.shape[STAGE_AXIS] != n_stages:
            raise ValueError(
                f"n_stages={n_stages} but the mesh's '{STAGE_AXIS}' axis has "
                f"size {mesh.shape[STAGE_AXIS]} — they must match (one "
                "pipeline stage per mesh position)"
            )
        self.embed = embed
        self.stage = stage
        self.head = head
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.mesh = mesh
        self.remat_stages = remat_stages

    # -- flax-like surface --------------------------------------------------
    def init(self, rng, x, train: bool = False):
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        embed_params = self.embed.init(r_embed, x)["params"]
        h = self.embed.apply({"params": embed_params}, x)
        stage_params = [
            self.stage.init(jax.random.fold_in(r_stage, i), h)["params"]
            for i in range(self.n_stages)
        ]
        stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)
        head_params = self.head.init(r_head, h)["params"]
        return {"params": {"embed": embed_params, "stages": stacked,
                           "head": head_params}}

    def _stage_fn(self, params_slice, h):
        return self.stage.apply({"params": params_slice}, h)

    def apply(self, variables, x, *, train: bool = True, mutable=False,
              rngs=None):
        from pddl_tpu.ops.pipeline import gpipe_apply

        p = variables["params"]
        h = self.embed.apply({"params": p["embed"]}, x)
        # Flash stages under pallas interpret mode (non-TPU test backends)
        # can't declare varying axes on their outputs; relax the vma check
        # there only (Mosaic on TPU declares them fine).
        check_vma = not (getattr(self.stage, "attention", None) == "flash"
                         and jax.default_backend() != "tpu")
        h = gpipe_apply(
            p["stages"], h, mesh=self.mesh, stage_fn=self._stage_fn,
            n_microbatches=self.n_microbatches, check_vma=check_vma,
            remat_stages=self.remat_stages,
        )
        out = self.head.apply({"params": p["head"]}, h)
        if mutable:
            return out, {}
        return out

    def apply_sequential(self, variables, x):
        """Reference path: the same stacked params applied stage by stage
        with no pipeline — the numerics oracle for tests."""
        p = variables["params"]
        h = self.embed.apply({"params": p["embed"]}, x)
        for i in range(self.n_stages):
            h = self._stage_fn(
                jax.tree.map(lambda leaf: leaf[i], p["stages"]), h)
        return self.head.apply({"params": p["head"]}, h)
