"""GPT family: decoder-only causal transformer (the long-context workload).

The reference is a vision-only repo (fixed 224x224 CNN,
``/root/reference/imagenet-resnet50.py:52`` — SURVEY.md §5 "Long-context:
absent"); this family exists because long-context training is first-class
in the TPU build. It is the model line that exercises *causal* flash
attention (:mod:`pddl_tpu.ops.attention`) and causal ring attention
(:mod:`pddl_tpu.ops.ring_attention`) on the training path, and it reuses
:class:`pddl_tpu.models.vit.TransformerBlock` — so Megatron TP
(``/attn/``-path rules), Switch-MoE and every distribution strategy apply
unchanged.

Batches are ``{"tokens": int32 [B, S], "targets": int32 [B, S]}`` (the
Trainer's ``input_key``/``target_key``); loss/metrics are the standard
sparse CE / accuracy, which broadcast over the sequence dim as-is.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from pddl_tpu.models.gpipe import GPipeModel
from pddl_tpu.models.vit import TransformerBlock


class GPT(nn.Module):
    """Decoder-only transformer LM: tokens ``[B, S]`` → logits ``[B, S, V]``."""

    vocab_size: int
    max_len: int = 1024
    embed_dim: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_ratio: int = 4
    attention: str = "flash"  # "flash" | "reference" | "ring"
    mesh: Optional[Any] = None  # required for "ring"
    dropout: float = 0.0
    moe_experts: int = 0
    moe_every: int = 2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, *, train: bool = True):
        # Stem shared with GPipeGPT; share_scope keeps the param names
        # (token_embed/pos_embed) at this module's top level.
        embed = _GPTEmbed(vocab_size=self.vocab_size, max_len=self.max_len,
                          embed_dim=self.embed_dim, dtype=self.dtype,
                          param_dtype=self.param_dtype)
        nn.share_scope(self, embed)
        x = embed(tokens)

        for i in range(self.depth):
            moe = (self.moe_experts
                   if (self.depth - 1 - i) % self.moe_every == 0 else 0)
            x = TransformerBlock(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, mesh=self.mesh, causal=True,
                dropout=self.dropout, moe_experts=moe, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train=train)

        # Head shared with GPipeGPT (ln_final/lm_head names preserved).
        head = _GPTHead(vocab_size=self.vocab_size, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        nn.share_scope(self, head)
        return head(x)


class _GPTEmbed(nn.Module):
    """Token + positional embedding (the pre-pipeline LM stem)."""

    vocab_size: int
    max_len: int
    embed_dim: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        b, s = tokens.shape
        if s > self.max_len:
            raise ValueError(f"sequence {s} exceeds max_len {self.max_len}")
        x = nn.Embed(self.vocab_size, self.embed_dim,
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="token_embed")(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.embed_dim), self.param_dtype)
        return x + pos[:, :s].astype(self.dtype)


class _GPTStage(nn.Module):
    """One pipeline stage: a run of causal transformer blocks."""

    num_heads: int
    blocks: int
    mlp_ratio: int = 4
    attention: str = "reference"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.blocks):
            x = TransformerBlock(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, causal=True, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train=False)
        return x


class _GPTHead(nn.Module):
    """Final LN + LM head (the post-pipeline projection to vocab)."""

    vocab_size: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype,
                         name="ln_final")(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


class GPipeGPT(GPipeModel):
    """Pipeline-parallel causal LM: PP x long-context — token/pos embed
    (replicated) → ``n_stages`` stacked causal-transformer stages through
    the GPipe schedule → LM head (replicated). See
    :class:`pddl_tpu.models.gpipe.GPipeModel`."""

    def __init__(self, *, vocab_size: int, n_stages: int,
                 blocks_per_stage: int, n_microbatches: int, mesh,
                 max_len: int = 1024, embed_dim: int = 256,
                 num_heads: int = 4, mlp_ratio: int = 4,
                 attention: str = "reference",
                 dtype: Any = jnp.float32, param_dtype: Any = jnp.float32):
        super().__init__(
            embed=_GPTEmbed(vocab_size=vocab_size, max_len=max_len,
                            embed_dim=embed_dim, dtype=dtype,
                            param_dtype=param_dtype),
            stage=_GPTStage(num_heads=num_heads, blocks=blocks_per_stage,
                            mlp_ratio=mlp_ratio, attention=attention,
                            dtype=dtype, param_dtype=param_dtype),
            head=_GPTHead(vocab_size=vocab_size, dtype=dtype,
                          param_dtype=param_dtype),
            n_stages=n_stages, n_microbatches=n_microbatches, mesh=mesh,
        )


GPT_Small = functools.partial(GPT, embed_dim=768, depth=12, num_heads=12)


def tiny_gpt(vocab_size: int = 64, **kwargs) -> GPT:
    """Miniature GPT for tests/dry-runs."""
    kwargs.setdefault("max_len", 128)
    kwargs.setdefault("embed_dim", 32)
    kwargs.setdefault("depth", 2)
    kwargs.setdefault("num_heads", 4)
    kwargs.setdefault("attention", "reference")
    return GPT(vocab_size=vocab_size, **kwargs)
