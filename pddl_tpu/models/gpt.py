"""GPT family: decoder-only causal transformer (the long-context workload).

The reference is a vision-only repo (fixed 224x224 CNN,
``/root/reference/imagenet-resnet50.py:52`` — SURVEY.md §5 "Long-context:
absent"); this family exists because long-context training is first-class
in the TPU build. It is the model line that exercises *causal* flash
attention (:mod:`pddl_tpu.ops.attention`) and causal ring attention
(:mod:`pddl_tpu.ops.ring_attention`) on the training path, and it reuses
:class:`pddl_tpu.models.vit.TransformerBlock` — so Megatron TP
(``/attn/``-path rules), Switch-MoE and every distribution strategy apply
unchanged.

Batches are ``{"tokens": int32 [B, S], "targets": int32 [B, S]}`` (the
Trainer's ``input_key``/``target_key``); loss/metrics are the standard
sparse CE / accuracy, which broadcast over the sequence dim as-is.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from pddl_tpu.models.gpipe import GPipeModel
from pddl_tpu.models.vit import TransformerBlock, remat_block
from pddl_tpu.ops.large_vocab import chunked_cross_entropy


class GPT(nn.Module):
    """Decoder-only transformer LM: tokens ``[B, S]`` → logits ``[B, S, V]``."""

    vocab_size: int
    max_len: int = 1024
    embed_dim: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_ratio: int = 4
    attention: str = "flash"  # "flash" | "reference" | "ring" | "ring_flash"
    mesh: Optional[Any] = None  # required for "ring"/"ring_flash"
    dropout: float = 0.0
    moe_experts: int = 0
    moe_top_k: int = 1  # experts per token (1=Switch, 2=GShard/Mixtral)
    moe_every: int = 2
    remat: str = "none"  # "none" | "dots" | "full" (vit.REMAT_POLICIES)
    # Pad the embedding/head vocab dim up to a multiple (Megatron's
    # convention, typically 128): vocab-parallel TP needs V divisible by
    # the model axis, and real vocabs (GPT-2's 50257) divide nothing.
    # Logits are sliced back to vocab_size — numerics are unchanged.
    vocab_multiple: int = 1
    decode: bool = False  # KV-cache generation mode (see generate())
    ln_eps: float = 1e-6  # HF GPT-2 checkpoints: pass 1e-5 (ckpt/hf_import)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, *, train: bool = True,
                 features_only: bool = False):
        # Stem shared with GPipeGPT; share_scope keeps the param names
        # (token_embed/pos_embed) at this module's top level.
        embed = _GPTEmbed(vocab_size=self.vocab_size, max_len=self.max_len,
                          embed_dim=self.embed_dim, decode=self.decode,
                          vocab_multiple=self.vocab_multiple,
                          dtype=self.dtype, param_dtype=self.param_dtype)
        nn.share_scope(self, embed)
        x = embed(tokens)

        # Decode mutates the KV cache — remat would replay the mutation;
        # generation steps are tiny anyway, so remat only applies to the
        # training/full-forward path.
        block_cls = (TransformerBlock if self.decode
                     else remat_block(TransformerBlock, self.remat))
        for i in range(self.depth):
            moe = (self.moe_experts
                   if (self.depth - 1 - i) % self.moe_every == 0 else 0)
            x = block_cls(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, mesh=self.mesh, causal=True,
                decode=self.decode, max_decode_len=self.max_len,
                dropout=self.dropout, moe_experts=moe,
                moe_top_k=self.moe_top_k, ln_eps=self.ln_eps,
                dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train)  # positional: remat keeps arg 2 static

        # Head shared with GPipeGPT (ln_final/lm_head names preserved).
        head = _GPTHead(vocab_size=self.vocab_size,
                        vocab_multiple=self.vocab_multiple,
                        ln_eps=self.ln_eps,
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        features_only=features_only)
        nn.share_scope(self, head)
        return head(x)


class _GPTEmbed(nn.Module):
    """Token + positional embedding (the pre-pipeline LM stem).

    ``decode=True``: one token per call, positioned at a running index
    kept in the ``"cache"`` collection (generation mode)."""

    vocab_size: int
    max_len: int
    embed_dim: int
    decode: bool = False
    vocab_multiple: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        b, s = tokens.shape
        if s > self.max_len:
            raise ValueError(f"sequence {s} exceeds max_len {self.max_len}")
        padded_v = -(-self.vocab_size // self.vocab_multiple) * self.vocab_multiple
        x = nn.Embed(padded_v, self.embed_dim,
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="token_embed")(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.embed_dim), self.param_dtype)
        if self.decode:
            initialized = self.has_variable("cache", "pos_index")
            idx = self.variable("cache", "pos_index",
                                lambda: jnp.zeros((), jnp.int32))
            if idx.value.ndim:
                # Per-row [B] position vector (the serving engine's slot
                # model): each row reads its own position embedding.
                step_pos = jnp.take(
                    pos[0], idx.value[:, None] + jnp.arange(s), axis=0)
            else:
                step_pos = jax.lax.dynamic_slice_in_dim(
                    pos, idx.value, s, axis=1)
            if initialized:  # init() must return a pristine cache
                idx.value = idx.value + s
            return x + step_pos.astype(self.dtype)
        return x + pos[:, :s].astype(self.dtype)


class _GPTStage(nn.Module):
    """One pipeline stage: a run of causal transformer blocks."""

    num_heads: int
    blocks: int
    mlp_ratio: int = 4
    attention: str = "reference"
    ln_eps: float = 1e-6
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.blocks):
            x = TransformerBlock(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, causal=True, ln_eps=self.ln_eps,
                dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, False)
        return x


class _GPTHead(nn.Module):
    """Final LN + LM head (the post-pipeline projection to vocab)."""

    vocab_size: int
    vocab_multiple: int = 1
    ln_eps: float = 1e-6
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    features_only: bool = False  # stop after ln_final (fused-CE path)

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(epsilon=self.ln_eps, dtype=jnp.float32,
                         param_dtype=self.param_dtype, name="ln_final")(x)
        if self.features_only and not self.is_initializing():
            # Pre-head features for chunked/fused cross-entropy
            # (ops/large_vocab.py). init() falls through to the dense
            # below regardless, so lm_head params always exist.
            return x.astype(self.dtype)
        padded_v = -(-self.vocab_size // self.vocab_multiple) * self.vocab_multiple
        logits = nn.Dense(padded_v, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="lm_head")(x)
        # Slice the padding classes away: the function computed is exactly
        # the unpadded head's (padded kernel columns never reach the loss
        # or sampling).
        return logits[..., :self.vocab_size].astype(jnp.float32)


class GPipeGPT(GPipeModel):
    """Pipeline-parallel causal LM: PP x long-context — token/pos embed
    (replicated) → ``n_stages`` stacked causal-transformer stages through
    the GPipe schedule → LM head (replicated). See
    :class:`pddl_tpu.models.gpipe.GPipeModel`."""

    def __init__(self, *, vocab_size: int, n_stages: int,
                 blocks_per_stage: int, n_microbatches: int, mesh,
                 max_len: int = 1024, embed_dim: int = 256,
                 num_heads: int = 4, mlp_ratio: int = 4,
                 attention: str = "reference", ln_eps: float = 1e-6,
                 dtype: Any = jnp.float32, param_dtype: Any = jnp.float32):
        super().__init__(
            embed=_GPTEmbed(vocab_size=vocab_size, max_len=max_len,
                            embed_dim=embed_dim, dtype=dtype,
                            param_dtype=param_dtype),
            stage=_GPTStage(num_heads=num_heads, blocks=blocks_per_stage,
                            mlp_ratio=mlp_ratio, attention=attention,
                            ln_eps=ln_eps,
                            dtype=dtype, param_dtype=param_dtype),
            head=_GPTHead(vocab_size=vocab_size, ln_eps=ln_eps, dtype=dtype,
                          param_dtype=param_dtype),
            n_stages=n_stages, n_microbatches=n_microbatches, mesh=mesh,
        )


def sample_logits(rng, logits, *, temperature: float = 1.0,
                  top_k: Optional[int] = None, top_p: Optional[float] = None):
    """One sampling step over ``[B, V]`` logits (compiled-friendly).

    Filters compose the standard way (matching common reference
    implementations): temperature warps the distribution FIRST, then
    top-k truncates, then nucleus (top-p) keeps the smallest set reaching
    ``top_p`` of the *warped* mass, then one categorical draw. Static
    shapes throughout — ``top_k`` uses ``lax.top_k``'s threshold,
    ``top_p`` masks on the sorted CDF — so the whole step stays jittable.
    """
    # Validate every CONCRETE value (Python, NumPy, or device scalar); a
    # TRACED top_p under jit stays dynamic and skips the check rather
    # than breaking the trace. (top_k is necessarily static: lax.top_k
    # needs a concrete k.)
    if top_k is not None and int(top_k) < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if (top_p is not None and not isinstance(top_p, jax.core.Tracer)
            and not 0.0 < float(top_p) <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    logits = logits.astype(jnp.float32)
    if temperature <= 0:
        # Greedy limit (filters never change the argmax); avoids the /0.
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        rng, filtered_logits(logits, temperature=temperature,
                             top_k=top_k, top_p=top_p), axis=-1)


def filtered_logits(logits, *, temperature: float,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None):
    """The warp+filter pipeline of :func:`sample_logits` WITHOUT the
    draw: f32 logits whose softmax is the exact sampling distribution.
    Shared with speculative decoding's verifier, whose accept/residual
    probabilities must be computed from the same filtered distribution
    a plain sampler would draw from."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sort_idx = jnp.argsort(-logits, axis=-1)  # stable descending
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        cdf = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Smallest set whose mass >= top_p: keep entries whose CDF
        # *before* them is < top_p (the first token is always kept).
        keep_sorted = jnp.concatenate(
            [jnp.zeros_like(cdf[..., :1]), cdf[..., :-1]], axis=-1
        ) < top_p
        # Scatter the keep mask back to vocab order through the inverse
        # permutation. A value threshold would instead keep EVERY token
        # tied with the boundary logit, exceeding the nucleus; the stable
        # descending argsort resolves boundary ties toward lower vocab
        # ids, so the kept set is exactly the smallest one reaching top_p.
        inv_idx = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv_idx, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def batched_filtered_logits(logits, *, temperature, top_k, top_p):
    """Per-ROW warp+filter: the :func:`filtered_logits` pipeline with the
    sampling parameters as ``[B]`` RUNTIME arrays instead of statics —
    the serving engine's per-slot path, where every tick carries a mixed
    bag of requests and none of their parameters may enter the compiled
    program as constants.

    Disabled-filter sentinels (arrays can't carry None): ``top_k <= 0``
    disables top-k for that row, ``top_p >= 1`` disables nucleus.
    ``temperature <= 0`` rows are warped at 1.0 to stay finite — greedy
    selection for them happens in :func:`sample_logits_batched`, which
    ignores the filtered row entirely.

    Row-by-row this matches ``filtered_logits`` exactly for enabled
    filters: the top-k threshold is the k-th sorted value (ties at the
    boundary kept, like ``lax.top_k``'s), and the nucleus keep-set comes
    from the same stable-descending CDF rule. ``top_k`` trades
    ``lax.top_k`` (static k) for one full sort shared with the nucleus
    pass — the price of k as data.
    """
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    kk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    warped = logits / jnp.where(t > 0, t, 1.0)[:, None]
    sort_idx = jnp.argsort(-warped, axis=-1)  # stable descending
    sorted_l = jnp.take_along_axis(warped, sort_idx, axis=-1)
    # Top-k: per-row k-th sorted value as the threshold (same
    # keep-boundary-ties rule as lax.top_k in filtered_logits).
    kth = jnp.take_along_axis(
        sorted_l, (jnp.clip(kk, 1, v) - 1)[:, None], axis=-1)
    keep_topk = (kk[:, None] <= 0) | (warped >= kth)
    warped = jnp.where(keep_topk, warped, -jnp.inf)
    # Nucleus over the top-k-masked values. Masked entries are exactly
    # the tail of the descending order (values below the threshold), so
    # the one sort stays valid after masking — no re-sort.
    sorted_m = jnp.where(
        jnp.take_along_axis(keep_topk, sort_idx, axis=-1),
        sorted_l, -jnp.inf)
    cdf = jnp.cumsum(jax.nn.softmax(sorted_m, axis=-1), axis=-1)
    keep_sorted = jnp.concatenate(
        [jnp.zeros_like(cdf[:, :1]), cdf[:, :-1]], axis=-1) < pp[:, None]
    inv_idx = jnp.argsort(sort_idx, axis=-1)
    keep = (jnp.take_along_axis(keep_sorted, inv_idx, axis=-1)
            | (pp[:, None] >= 1.0))
    return jnp.where(keep, warped, -jnp.inf)


def sample_logits_batched(rng, logits, *, temperature, top_k, top_p):
    """One sampling step over ``[B, V]`` logits with PER-ROW parameters
    (``[B]`` arrays; sentinels as in :func:`batched_filtered_logits`).
    Rows with ``temperature <= 0`` take the greedy argmax of the RAW
    logits (filters never change an argmax); the rest draw one
    categorical sample from their filtered distribution. Returns int32
    ``[B]``."""
    logits = logits.astype(jnp.float32)
    t = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), (logits.shape[0],))
    sampled = jax.random.categorical(
        rng, batched_filtered_logits(logits, temperature=temperature,
                                     top_k=top_k, top_p=top_p), axis=-1)
    return jnp.where(t > 0, sampled,
                     jnp.argmax(logits, axis=-1)).astype(jnp.int32)


def generate(model: GPT, variables, prompt, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, rng=None, strategy=None,
             param_transform=None):
    """Autoregressive sampling with a KV cache.

    Args:
      model: the (trained) non-decode GPT; a decode twin sharing its params
        is constructed internally via ``model.clone(decode=True)``.
      variables: ``{"params": ...}`` from training.
      prompt: int32 ``[B, P]`` prompt tokens (``P >= 1``).
      max_new_tokens: tokens to append.
      temperature: 0 → greedy argmax; >0 → temperature sampling (``rng``
        required), optionally filtered by ``top_k`` and/or nucleus
        ``top_p`` (:func:`sample_logits`).
      strategy: optional :class:`~pddl_tpu.parallel.tensor_parallel.
        TensorParallelStrategy` (mesh already set up) for SHARDED
        inference: weights lay out Megatron-style over the ``model``
        axis, the KV cache splits by head alongside its q/k/v shards,
        and each decode step compiles with the two per-block
        all-reduces on ICI — models too big for one chip generate
        without any model change.
      param_transform: optional module-level function mapping
        ``variables["params"]`` to apply-ready weights inside the jitted
        programs — the int8 weight-only serving hook
        (:func:`pddl_tpu.ops.quant.dequantize`); see `ops/quant.py`.
        Unsharded path only.

    Returns int32 ``[B, P + max_new_tokens]`` (prompt + continuation).
    Execution model: one jitted batched prefill over the whole prompt,
    then the ENTIRE decode as a single on-device ``lax.scan`` dispatch
    (sampling included); parameters are jit arguments, so new checkpoints
    of the same shape reuse the compiled program.
    """
    b, p = prompt.shape
    total = p + max_new_tokens
    if p < 1:
        raise ValueError("generate() needs a non-empty prompt (P >= 1)")
    if total > model.max_len:
        raise ValueError(f"prompt+new tokens {total} exceed max_len {model.max_len}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if temperature <= 0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require temperature > 0 (greedy decoding would "
            "silently ignore them)"
        )
    dec = model.clone(decode=True)
    params = variables["params"]
    cache_shapes = _decode_cache_shapes(dec, b)

    def fresh_cache():
        return jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                            cache_shapes)

    # The prefill step runs ONCE (decode then scans on device) — no
    # donation: donating the just-created zero cache is never usable.
    if strategy is None:
        cache = fresh_cache()
        step, run = _decode_programs(dec, temperature, top_k, top_p,
                                     max_new_tokens, param_transform)
    else:
        if param_transform is not None:
            raise NotImplementedError(
                "param_transform (int8 serving) is unsharded-only: the "
                "sharding trees below describe the DENSE params layout")
        # One batched transfer for the whole tree; the same sharding tree
        # feeds the jits' in_shardings.
        param_sh = strategy.tree_sharding(params)
        params = jax.device_put(params, param_sh)
        cache_sh = strategy.decode_cache_sharding(cache_shapes)
        p_leaves, p_def = jax.tree_util.tree_flatten(param_sh)
        c_leaves, c_def = jax.tree_util.tree_flatten(cache_sh)
        step, run = _sharded_decode_programs(
            dec, temperature, top_k, top_p, max_new_tokens,
            p_def, tuple(p_leaves), c_def, tuple(c_leaves))
        cache = jax.jit(fresh_cache, out_shardings=cache_sh)()

    # Batched prefill: the whole prompt in ONE call (causal within the
    # block); then the ENTIRE decode runs as one compiled lax.scan — a
    # single dispatch for all max_new_tokens steps. A host-side
    # token-at-a-time loop costs one (or more) host→device round trips
    # per token, which dominates wall-clock wherever dispatch has
    # latency (remote/tunneled transports, busy hosts); on-device scan
    # makes generation latency the compute itself.
    cache, logits = step(params, cache, prompt)
    if rng is None:
        rng = jax.random.key(0)  # unused under greedy; scan needs a value
    return jnp.concatenate([prompt, run(params, cache, logits, rng)], axis=1)


def _decode_fns(dec, temperature, top_k, top_p, max_new_tokens,
                param_transform=None):
    """(step_fn, decode_all) python callables for a decode-mode model.

    params is an ARGUMENT of both functions, never a closure: closed-over
    arrays become program CONSTANTS, which bakes the full parameter set
    into the executable — gigabyte compile payloads (remote-compile
    transports reject them outright) and a recompile for every new
    checkpoint.

    ``param_transform`` (e.g. :func:`pddl_tpu.ops.quant.dequantize`)
    maps the passed params tree to apply-ready weights INSIDE the jitted
    programs — so what lives in HBM (and streams per tick) is the
    transformed-FROM representation, int8 for the quant case, with the
    convert fused into the consuming matmuls.
    """
    pt = param_transform or (lambda p: p)

    def step_fn(params, cache, tok):
        logits, mutated = dec.apply(
            {"params": pt(params), "cache": cache}, tok,
            train=False, mutable=["cache"],
        )
        return mutated["cache"], logits[:, -1]

    def sample_next(logits, rng):
        if temperature > 0:
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(sub, logits, temperature=temperature,
                                top_k=top_k, top_p=top_p)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        return nxt.astype(jnp.int32), rng

    def decode_all(params, cache, logits, rng):
        def body(carry, _):
            cache, logits, rng = carry
            nxt, rng = sample_next(logits, rng)
            tok = nxt[:, None]
            cache, logits = step_fn(params, cache, tok)
            return (cache, logits, rng), tok

        # The final iteration's step_fn is one token of dead compute (its
        # logits are never sampled) — the price of a uniform scan body.
        _, toks = jax.lax.scan(
            body, (cache, logits, rng), None, length=max_new_tokens)
        return jnp.moveaxis(toks[..., 0], 0, 1)  # [T, B, 1] -> [B, T]

    return step_fn, decode_all


# The cache collections' position-counter leaf names, across every
# family: GPT's embed keeps `pos_index`, the attention modules (vit MHA
# and llama GQA) keep `cache_index`. THE single registry — speculative
# decoding's rewind and the serving engine's slot machinery both match
# counters by these names (never by scalar-int32 duck typing, which
# would silently capture any future non-position scalar cache state).
CACHE_INDEX_KEYS = frozenset({"pos_index", "cache_index"})

# The paged-serving block-table leaf name (`ops/attention.paged_*`,
# `serve/kvcache/block_pool.paged_decode_cache`): its PRESENCE in a
# cache collection is what flips the attention modules onto the paged
# path, so the name is a registry constant like CACHE_INDEX_KEYS — the
# modules, the engine's stamp helper below, and the pool builder all
# match by it, never by shape duck typing.
BLOCK_TABLE_KEY = "block_table"


def is_cache_index_path(path) -> bool:
    """True when a cache-tree key path names a position counter leaf."""
    return bool(path) and (
        str(getattr(path[-1], "key", path[-1])) in CACHE_INDEX_KEYS)


def is_block_table_path(path) -> bool:
    """True when a cache-tree key path names a paged block-table leaf."""
    return bool(path) and (
        str(getattr(path[-1], "key", path[-1])) == BLOCK_TABLE_KEY)


def slot_decode_cache(dec, slots: int):
    """A pooled ``slots``-row decode cache for the serving engine.

    K/V leaves are the batch-1 cache's with the batch dim widened to
    ``slots`` (one row per request slot); position counters become
    ``[slots]`` int32 VECTORS — the per-row index form the decode
    modules and :func:`~pddl_tpu.ops.attention.decode_attention` accept,
    so every slot advances at its own depth inside one fused tick.
    """
    row = _decode_cache_shapes(dec, 1)
    return jax.tree_util.tree_map_with_path(
        lambda path, sd: (jnp.zeros((slots,), jnp.int32)
                          if is_cache_index_path(path)
                          else jnp.zeros((slots,) + sd.shape[1:], sd.dtype)),
        row)


def set_cache_positions(cache, positions):
    """Overwrite every position counter of a pooled cache with
    ``positions [slots]`` (the engine owns the authoritative per-slot
    positions; the tick program stamps them in before each apply)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: positions if is_cache_index_path(path) else leaf,
        cache)


def set_cache_block_tables(cache, tables):
    """Overwrite every ``block_table`` leaf of a PAGED cache with
    ``tables`` (``[slots, T]`` for the fused tick, ``[1, T]`` for a
    batch-1 chunk prefill). The engine owns the authoritative host-side
    tables exactly like the position counters — every paged program
    stamps them in before the apply and re-stamps a canonical
    placeholder on exit, so the resident donated tree keeps ONE
    structure across the whole program set (shape-stable donation =
    zero recompiles)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: tables if is_block_table_path(path) else leaf,
        cache)


def insert_cache_slot(cache, row_cache, slot, position):
    """Insert a finished batch-1 prefill (``row_cache``) as slot ``slot``
    of a pooled cache, and stamp the slot's position counter to
    ``position`` (the request's prompt length). K/V rows go through
    :func:`~pddl_tpu.ops.attention.cache_slot_insert`; the row cache's
    own scalar counters are discarded — the pool's vectors are
    authoritative. ``slot``/``position`` are runtime values: one
    compiled program admits into any slot."""
    from pddl_tpu.ops.attention import cache_slot_insert

    def _ins(path, pool, row):
        if is_cache_index_path(path):
            return pool.at[slot].set(jnp.asarray(position, pool.dtype))
        return cache_slot_insert(pool, row, slot)

    return jax.tree_util.tree_map_with_path(_ins, cache, row_cache)


def prefill_row(dec, params, prompt, length, *, param_transform=None):
    """One request's prefill on a FRESH batch-1 cache: the serving
    engine's admission building block (family-generic — duck-typed over
    GPT/Llama like :func:`generate`).

    ``prompt`` is int32 ``[1, P_pad]`` RIGHT-padded to the engine's
    fixed prefill width (one compiled program for all prompt lengths);
    ``length`` (traced int32) is the true token count. Padding is
    harmless by the same invariant speculative decoding relies on:
    causal attention means positions ``< length`` never see the junk
    suffix, the returned logits row is taken at ``length - 1``, and the
    junk K/V beyond ``length`` sits past the slot's position counter
    where the prefix-bounded sweep never reads it (decode overwrites it
    position by position as the request generates).

    Returns ``(row_cache, last_logits [1, V])``.
    """
    pt = param_transform or (lambda p: p)
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                         _decode_cache_shapes(dec, 1))
    logits, mutated = dec.apply(
        {"params": pt(params), "cache": cache}, prompt,
        train=False, mutable=["cache"])
    last = jax.lax.dynamic_slice(
        logits, (0, length - 1, 0), (1, 1, logits.shape[-1]))[:, 0]
    return mutated["cache"], last


def prefill_row_from(dec, params, prompt, length, row_cache, start, *,
                     param_transform=None):
    """Chunked prefill CONTINUING an existing batch-1 row cache: the
    prefix-cache admission building block (family-generic like
    :func:`prefill_row` — GPT's decode embed and the Llama/vit decode
    attention both run multi-token blocks at any starting index).

    ``row_cache`` already holds ``start`` valid tokens of K/V (e.g. a
    gathered shared-prefix chain); ``prompt`` is int32 ``[1, C]``
    RIGHT-padded, ``length <= C`` its true token count, both traced —
    one compiled program per chunk width. The chunk's tokens take global
    positions ``start .. start+C-1``, so the caller must keep
    ``start + C <= dec.max_len`` (the embed/cache dynamic slices CLAMP
    out-of-range starts, which would silently mis-position the block).
    Padding junk is harmless by the :func:`prefill_row` invariant:
    causal masking hides it from positions ``< start + length``, its K/V
    lands beyond the position counter the caller stamps at insert, and
    decode overwrites it before the counter crosses.

    Returns ``(row_cache, last_logits [1, V])`` with the logits row
    taken at ``length - 1`` (only the FINAL chunk's logits are
    meaningful to sample from).
    """
    pt = param_transform or (lambda p: p)
    cache = set_cache_positions(row_cache, jnp.asarray(start, jnp.int32))
    logits, mutated = dec.apply(
        {"params": pt(params), "cache": cache}, prompt,
        train=False, mutable=["cache"])
    last = jax.lax.dynamic_slice(
        logits, (0, length - 1, 0), (1, 1, logits.shape[-1]))[:, 0]
    return mutated["cache"], last


def lm_head_logits(model, params, feats):
    """The LM head applied OUTSIDE the module: pre-head features
    (``features_only=True`` apply output — post-final-norm, already in
    the model's compute dtype) → vocab logits, mirroring
    ``_GPTHead``/``_LlamaHead`` operation-for-operation (same
    ``dot_general`` contraction, same bias/padding-slice/f32-cast
    order), so the computed logits match the in-module head exactly.

    This is the multi-tenant serving hook point (`serve/tenant/`): the
    tenant engine's compiled programs run the model ``features_only``,
    apply the head here, and then ADD per-slot LoRA deltas
    (:func:`pddl_tpu.ops.lora.batched_lora_delta`) and grammar masks
    before sampling — all runtime data, no program-shape variation.
    ``params`` must already be transform-applied (the int8
    ``param_transform`` runs BEFORE this, like everywhere else).
    Bias-free heads (the Llama family) simply have no ``bias`` key.
    """
    head = params["lm_head"]
    x = feats.astype(model.dtype)
    kernel = head["kernel"].astype(model.dtype)
    logits = jax.lax.dot_general(
        x, kernel, (((x.ndim - 1,), (0,)), ((), ())))
    if "bias" in head:
        logits = logits + head["bias"].astype(model.dtype)
    return logits[..., :model.vocab_size].astype(jnp.float32)


def prefill_row_features(dec, params, prompt, length, row_cache, start, *,
                         param_transform=None):
    """The tenant twin of :func:`prefill_row`/:func:`prefill_row_from`:
    one prefill chunk that ALSO returns the last position's pre-head
    features, so the caller can compose LoRA deltas into the sampled
    logits. ``row_cache=None`` starts a fresh batch-1 cache (the
    whole-prompt ``prefill_row`` shape); otherwise the chunk continues
    the given cache at global offset ``start`` (``prefill_row_from``
    semantics, same clamping caveats).

    Returns ``(row_cache, last_logits [1, V], last_feats [1, d])``.
    The logits are computed through :func:`lm_head_logits` over the
    full chunk and sliced at ``length - 1`` — the identical op shapes
    the in-module head produces, so a no-adapter tenant admission is
    bit-identical to the plain prefill path.
    """
    pt = param_transform or (lambda p: p)
    p2 = pt(params)
    if row_cache is None:
        cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype),
                             _decode_cache_shapes(dec, 1))
    else:
        cache = set_cache_positions(row_cache,
                                    jnp.asarray(start, jnp.int32))
    feats, mutated = dec.apply(
        {"params": p2, "cache": cache}, prompt,
        train=False, mutable=["cache"], features_only=True)
    logits = lm_head_logits(dec, p2, feats)
    last = jax.lax.dynamic_slice(
        logits, (0, length - 1, 0), (1, 1, logits.shape[-1]))[:, 0]
    last_feats = jax.lax.dynamic_slice(
        feats, (0, length - 1, 0), (1, 1, feats.shape[-1]))[:, 0]
    return mutated["cache"], last, last_feats


@functools.lru_cache(maxsize=16)
def _decode_cache_shapes(dec, batch: int):
    """KV-cache ShapeDtypeStructs for a decode module at a batch size.

    The fresh cache is all zeros by construction; eval_shape over init
    gets its structure without materializing (and discarding) a full
    random parameter set. Cached: the abstract trace of init walks every
    block and is pure per-(dec, batch) overhead on the serving hot path.
    """
    dummy = jnp.zeros((batch, 1), jnp.int32)
    return jax.eval_shape(
        lambda: dec.init(jax.random.key(0), dummy, train=False)
    )["cache"]


@functools.lru_cache(maxsize=16)
def _decode_programs(dec, temperature, top_k, top_p, max_new_tokens,
                     param_transform=None):
    """Jitted (prefill_step, decode_scan) for the unsharded path, CACHED
    on the (hashable, frozen) decode module + sampling statics.

    Without this cache every generate() call would build fresh closures
    and re-trace/re-compile the whole decode scan — tens of seconds per
    request in a serving loop. With it, repeated calls (and new
    checkpoints of the same shape, which are just new jit arguments) hit
    the compiled programs. Entries keep the module and executables alive
    until LRU eviction (maxsize=16) or process exit — deliberate serving
    behavior, not a leak. ``param_transform`` participates in the key by
    identity — pass a module-level function (not a lambda) to hit.
    """
    step_fn, decode_all = _decode_fns(dec, temperature, top_k, top_p,
                                      max_new_tokens, param_transform)
    return jax.jit(step_fn), jax.jit(decode_all, donate_argnums=(1,))


@functools.lru_cache(maxsize=16)
def _sharded_decode_programs(dec, temperature, top_k, top_p, max_new_tokens,
                             param_sh_def, param_sh_leaves,
                             cache_sh_def, cache_sh_leaves):
    """(step, run) for tensor-parallel decoding, cached like
    :func:`_decode_programs` so sharded serving doesn't re-compile per
    request.

    Keys are VALUES, not identities: the flattened parameter and cache
    sharding trees (NamedShardings and treedefs hash by value, and the
    mesh is embedded in every leaf), so a strategy object rebuilt per
    request still hits; a different mesh, checkpoint structure, or
    sampling config misses. One lru_cache mechanism shared with the
    unsharded path — same true-LRU eviction.

    Retention: like :func:`_decode_programs`, cached entries hold strong
    references to the module, the NamedShardings (hence meshes and
    device handles) and the compiled executables until LRU-evicted or
    the process exits — the deliberate cost of not re-compiling per
    serving request (same caveat as ``core/sharding.py``'s lru_cache).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    if not param_sh_leaves:
        raise ValueError(
            "sharded decode needs a non-empty params tree (got zero "
            "parameter leaves — was the model initialized?)")
    param_sh = jax.tree_util.tree_unflatten(param_sh_def, param_sh_leaves)
    cache_sh = jax.tree_util.tree_unflatten(cache_sh_def, cache_sh_leaves)
    repl = NamedSharding(param_sh_leaves[0].mesh, PartitionSpec())
    step_fn, decode_all = _decode_fns(dec, temperature, top_k, top_p,
                                      max_new_tokens)
    step = jax.jit(step_fn,
                   in_shardings=(param_sh, cache_sh, repl),
                   out_shardings=(cache_sh, repl))
    run = jax.jit(decode_all, donate_argnums=(1,),
                  in_shardings=(param_sh, cache_sh, repl, repl),
                  out_shardings=repl)
    return step, run


GPT_Small = functools.partial(GPT, embed_dim=768, depth=12, num_heads=12)


def tiny_gpt(vocab_size: int = 64, **kwargs) -> GPT:
    """Miniature GPT for tests/dry-runs."""
    kwargs.setdefault("max_len", 128)
    kwargs.setdefault("embed_dim", 32)
    kwargs.setdefault("depth", 2)
    kwargs.setdefault("num_heads", 4)
    kwargs.setdefault("attention", "reference")
    return GPT(vocab_size=vocab_size, **kwargs)


def fused_lm_loss(model: GPT, variables, tokens, targets, *,
                  train: bool = True, rngs=None,
                  chunk_size: Optional[int] = None) -> jnp.ndarray:
    """Mean token cross-entropy without materializing the ``[B, S, V]`` logits.

    The standard LM loss writes ~``B*S*V`` logits to HBM, saves them (and
    softmax residuals) for the backward, writes d-logits, and reads them
    again in the head-matmul backward. The fused head
    (:func:`pddl_tpu.ops.large_vocab.chunked_cross_entropy`, custom VJP)
    saves only per-token logsumexp rows and recomputes chunk logits in
    the backward: measured 33.7 vs 39.7 ms for head+CE fwd+bwd on one
    v5e at GPT-2-small shapes (B8 S2048 V50257 bf16).

    Memory: the default (``chunk_size=None`` → whole vocab, one fused
    step) optimizes for SPEED — its forward still builds one transient
    ``[tokens, V]`` f32 chunk (~3.3 GB at the shapes above), though
    nothing logits-sized is saved across fwd/bwd. Pass ``chunk_size``
    below the vocab for the long-context/large-vocab memory valve: peak
    extra memory drops to ``tokens x chunk_size``.

    Gradients match the materialized path — to float tolerance in f32
    and to bf16 tolerance in bf16, where both paths run the head matmul
    from bf16 operands with f32 accumulation (``tests/test_gpt.py``).
    For metrics that need logits (accuracy, sampling), use the regular
    ``model.apply`` — this is the training-loss fast path.

    Args:
      model: the :class:`GPT` (its ``vocab_size``/``vocab_multiple``
        locate the real columns of a padded head).
      variables: ``{"params": ...}``.
      tokens: ``[B, S]`` int32 inputs.
      targets: ``[B, S]`` int32 next-token labels.
      train: forwarded to the model (dropout etc.).
      rngs: forwarded to ``model.apply`` (needed when dropout > 0).
      chunk_size: vocab slab per scan step; None = the whole (unpadded)
        vocab in one fused step — fastest when the logits would fit.
    """
    kwargs = {"rngs": rngs} if rngs is not None else {}
    feats = model.apply(variables, tokens, train=train,
                        features_only=True, **kwargs)
    head = variables["params"]["lm_head"]
    # Compute dtype like the materialized Dense(dtype=model.dtype) would:
    # the chunked matmuls run on these operands with f32 accumulation.
    kernel = head["kernel"][:, :model.vocab_size].astype(model.dtype)
    # Bias-free heads (the Llama family) simply skip the bias term.
    bias = head["bias"][:model.vocab_size].astype(jnp.float32) \
        if "bias" in head else None
    return chunked_cross_entropy(
        feats, kernel, targets, bias,
        chunk_size=chunk_size if chunk_size is not None else model.vocab_size,
    )
