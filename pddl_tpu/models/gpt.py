"""GPT family: decoder-only causal transformer (the long-context workload).

The reference is a vision-only repo (fixed 224x224 CNN,
``/root/reference/imagenet-resnet50.py:52`` — SURVEY.md §5 "Long-context:
absent"); this family exists because long-context training is first-class
in the TPU build. It is the model line that exercises *causal* flash
attention (:mod:`pddl_tpu.ops.attention`) and causal ring attention
(:mod:`pddl_tpu.ops.ring_attention`) on the training path, and it reuses
:class:`pddl_tpu.models.vit.TransformerBlock` — so Megatron TP
(``/attn/``-path rules), Switch-MoE and every distribution strategy apply
unchanged.

Batches are ``{"tokens": int32 [B, S], "targets": int32 [B, S]}`` (the
Trainer's ``input_key``/``target_key``); loss/metrics are the standard
sparse CE / accuracy, which broadcast over the sequence dim as-is.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from pddl_tpu.models.vit import TransformerBlock


class GPT(nn.Module):
    """Decoder-only transformer LM: tokens ``[B, S]`` → logits ``[B, S, V]``."""

    vocab_size: int
    max_len: int = 1024
    embed_dim: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_ratio: int = 4
    attention: str = "flash"  # "flash" | "reference" | "ring"
    mesh: Optional[Any] = None  # required for "ring"
    dropout: float = 0.0
    moe_experts: int = 0
    moe_every: int = 2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, *, train: bool = True):
        b, s = tokens.shape
        if s > self.max_len:
            raise ValueError(f"sequence {s} exceeds max_len {self.max_len}")
        x = nn.Embed(self.vocab_size, self.embed_dim,
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="token_embed")(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.embed_dim), self.param_dtype)
        x = x + pos[:, :s].astype(self.dtype)

        for i in range(self.depth):
            moe = (self.moe_experts
                   if (self.depth - 1 - i) % self.moe_every == 0 else 0)
            x = TransformerBlock(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, mesh=self.mesh, causal=True,
                dropout=self.dropout, moe_experts=moe, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train=train)

        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype,
                         name="ln_final")(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


GPT_Small = functools.partial(GPT, embed_dim=768, depth=12, num_heads=12)


def tiny_gpt(vocab_size: int = 64, **kwargs) -> GPT:
    """Miniature GPT for tests/dry-runs."""
    kwargs.setdefault("max_len", 128)
    kwargs.setdefault("embed_dim", 32)
    kwargs.setdefault("depth", 2)
    kwargs.setdefault("num_heads", 4)
    kwargs.setdefault("attention", "reference")
    return GPT(vocab_size=vocab_size, **kwargs)
