"""Llama family: RoPE + RMSNorm + SwiGLU + grouped-query attention.

The reference repo trains one CNN family end to end
(`/root/reference/imagenet-resnet50.py:52`); its TPU rebuild carries a
transformer LM line (:mod:`pddl_tpu.models.gpt`) as the long-context
workload. This module adds the *modern* decoder architecture — the
Llama/Mistral/Qwen lineage — on the same substrate:

- **RoPE** (:mod:`pddl_tpu.ops.rope`) instead of GPT-2's learned
  position table: no ``max_len``-sized parameter, positions enter
  through q/k rotation, HF half-split convention so
  :func:`pddl_tpu.ckpt.hf_import.load_hf_llama` checkpoints reproduce
  transformers' logits to f32 tolerance.
- **RMSNorm** (f32 compute, like the family's LayerNorms) pre-attention,
  pre-MLP, and final.
- **SwiGLU** MLP (``silu(gate)·up → down``), no biases anywhere
  (except Qwen2's q/k/v projection biases, ``qkv_bias=True``).
- **Grouped-query attention**: ``num_kv_heads <= num_heads`` K/V heads,
  consumed UNEXPANDED by every kernel (flash, ring, decode — the
  q-head → kv-head mapping lives inside them), so GQA's
  ``num_heads/num_kv_heads`` memory/bandwidth saving holds in
  training, prefill, sequence-parallel rotation, AND the decode cache.
- **Sliding-window attention** (Mistral): band-skipped in the flash
  kernel, composed with the ring/sequence-parallel path (out-of-band
  rotations skipped — O(window) compute and ICI), and a
  ``window``-sized rolling ring-buffer decode cache.
- **Routed experts** (Mixtral): ``moe_experts`` switches each
  ``moe_every``-th block's MLP to top-``moe_top_k`` SwiGLU experts
  (:class:`pddl_tpu.ops.moe.SwitchFFN`, ``expert_act="swiglu"``);
  import/export via :func:`pddl_tpu.ckpt.hf_import.load_hf_mixtral` /
  ``export_hf_llama``; shard with ``LLAMA_EP_RULES``.

Everything else — flash/ring attention, Megatron TP (use
``LLAMA_TP_RULES`` from :mod:`pddl_tpu.parallel.tensor_parallel`),
fused-CE training loss, KV-cache generation — is shared with the GPT
family: :func:`pddl_tpu.models.gpt.generate` and
:func:`pddl_tpu.models.gpt.fused_lm_loss` are duck-typed over both.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from pddl_tpu.models.gpipe import GPipeModel
from pddl_tpu.models.vit import remat_block
from pddl_tpu.ops.attention import (
    attention_reference,
    decode_attention,
    flash_attention,
)
from pddl_tpu.ops.rope import apply_rope_qk


def _default_intermediate_dim(embed_dim: int) -> int:
    """The SwiGLU convention: 2/3 of the 4E classic MLP width, rounded up
    to a multiple of 128 (lane-friendly). One definition shared by
    :class:`Llama` and :class:`GPipeLlama`."""
    return -(-(8 * embed_dim // 3) // 128) * 128


def ring_len(sliding_window: Optional[int],
             max_decode_len: int) -> Optional[int]:
    """Rolling-cache length for SWA decode: the window rounded up to a
    lane-friendly multiple of 128 (``>= window`` so the slot being
    overwritten each step is always already outside the band), or None
    when a full-length cache is smaller anyway.

    THE single definition of the ring decision — the attention module
    sizes its cache with it and ``Llama.uses_ring_cache`` (which
    speculative decoding consults to refuse unrewindable caches) answers
    from it, so the two can never diverge.
    """
    if sliding_window is None:
        return None
    ring = -(-sliding_window // 128) * 128
    return ring if ring < max_decode_len else None


def _rms_norm(eps: float, param_dtype, name: str):
    """Family-standard RMSNorm: f32 compute (stable under bf16), learned
    scale in ``param_dtype``."""
    return nn.RMSNorm(epsilon=eps, dtype=jnp.float32,
                      param_dtype=param_dtype, name=name)


class LlamaAttention(nn.Module):
    """Causal GQA with RoPE over the repo's attention kernels.

    Layout mirrors :class:`pddl_tpu.models.vit.MultiHeadAttention`
    (``query``/``key``/``value`` DenseGeneral, flattened ``out``) so the
    Megatron TP path rules apply unchanged. K/V carry ``num_kv_heads``
    and are consumed at that size by every kernel (flash, reference,
    ring): the q-head → kv-head mapping lives inside the kernels, so no
    expanded copy is materialized anywhere in training or prefill.
    """

    num_heads: int
    num_kv_heads: int
    rope_theta: float = 10000.0
    attention: str = "flash"  # "flash" | "reference" | "ring" | "ring_flash"
    sliding_window: Optional[int] = None  # Mistral-style SWA width
    qkv_bias: bool = False  # Qwen2-style q/k/v projection biases
    mesh: Optional[Any] = None
    decode: bool = False
    max_decode_len: int = 1024
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, e = x.shape
        if e % self.num_heads:
            raise ValueError(f"embed dim {e} not divisible by {self.num_heads} heads")
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {self.num_kv_heads}")
        if self.sliding_window is not None and self.sliding_window < 1:
            # Validate here so the decode path (which builds its own mask)
            # rejects it too, not just the flash/reference kernels.
            raise ValueError(
                f"sliding_window must be >= 1, got {self.sliding_window}")
        head_dim = e // self.num_heads
        dense = functools.partial(
            nn.DenseGeneral, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        # Qwen2 puts biases on q/k/v (only); the out projection is always
        # bias-free across the lineage.
        qkv = functools.partial(dense, use_bias=self.qkv_bias)
        q = qkv(features=(self.num_heads, head_dim), name="query")(x)
        k = qkv(features=(self.num_kv_heads, head_dim), name="key")(x)
        v = qkv(features=(self.num_kv_heads, head_dim), name="value")(x)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # [B, H, S, D]

        if self.decode:
            return self._decode_step(q, k, v, b, s, head_dim, dense)

        q, k = apply_rope_qk(q, k, jnp.arange(s), theta=self.rope_theta)

        # K/V stay at kv-head shape [B, H_kv, S, D] through every kernel:
        # the attention ops consume grouped K/V natively (q-head → kv-head
        # mapping in kernel index maps), so training/prefill get GQA's
        # full HBM-bandwidth and activation-memory saving — no
        # H/H_kv-times expansion is ever materialized.
        if self.attention == "flash":
            o = flash_attention(q, k, v, causal=True,
                                window=self.sliding_window)
        elif self.attention == "reference":
            o = attention_reference(q, k, v, causal=True,
                                    window=self.sliding_window)
        elif self.attention in ("ring", "ring_flash"):
            from pddl_tpu.ops.ring_attention import sequence_parallel_attention

            if self.mesh is None:
                raise ValueError(f"attention={self.attention!r} needs the mesh")
            # SWA composes with the ring: out-of-band rotations (and
            # their ppermute hops) are skipped, so long-context Mistral
            # under sequence parallelism pays O(window) per device.
            o = sequence_parallel_attention(
                q, k, v, self.mesh, causal=True,
                window=self.sliding_window,
                use_flash=self.attention == "ring_flash")
        else:
            raise ValueError(f"unknown attention {self.attention!r}")

        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        return dense(features=e, name="out")(o)

    def _ring_len(self) -> Optional[int]:
        """Rolling-cache length for SWA decode (see :func:`ring_len`)."""
        return ring_len(self.sliding_window, self.max_decode_len)

    def _decode_step(self, q, k, v, b, s, head_dim, dense):
        """KV-cache decoding at the bandwidth roofline.

        The cache holds POST-RoPE keys at KV-head granularity in the
        model's compute dtype (bf16 in serving — never cast up), and:

        - single-token steps sweep it with
          :func:`~pddl_tpu.ops.attention.decode_attention` — grouped
          (unexpanded) K/V, online softmax over chunks, HBM traffic
          bounded by the valid prefix;
        - with ``sliding_window`` the cache is a ``window``-sized RING
          buffer (:meth:`_ring_len`) instead of ``max_decode_len`` —
          Mistral's rolling cache — so decode memory and traffic are
          O(window), not O(max_len);
        - multi-token PREFILL (including chunked prefill at any starting
          index) runs the flash kernel on the block itself merged with a
          pre-write history sweep in logsumexp space — O(block) score
          memory, never ``[B,H,S,max_len]`` f32.

        Cache-content contract the serving layer builds on: the cache
        stores POST-RoPE keys rotated at their ABSOLUTE positions, so an
        entry depends only on (prompt tokens, position, params) — never
        on which request computed it. This is what makes the prefix
        cache's shared KV blocks (`pddl_tpu/serve/kvcache/`) bit-valid
        across requests, and what `gpt.prefill_row_from` relies on when
        it continues a row cache assembled from gathered blocks: a
        suffix chunk at starting index ``i`` reproduces exactly the K/V
        a full prefill would have written there. (The caller keeps
        ``i + s <= max_decode_len`` — the cache write's dynamic slice
        CLAMPS out-of-range starts rather than failing.)
        """
        hkv = self.num_kv_heads
        ring = self._ring_len()
        cache_len = ring or self.max_decode_len
        initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (b, hkv, cache_len, head_dim), self.dtype)
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (b, hkv, cache_len, head_dim), self.dtype)
        index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32))

        i = index.value
        if i.ndim and s != 1 and ring is not None:
            # Per-row [B] positions over a RING cache cannot take
            # multi-token blocks: a partially-rejected speculative
            # window would have overwritten in-window slots per row.
            # Full-length caches (the only kind the serving engine
            # admits) handle the vector multi-token write below.
            raise ValueError(
                "per-row cache_index over a rolling ring cache supports "
                f"single-token steps only (got a {s}-token block)")
        # [..., None] keeps one expression for both index ranks: scalar
        # i → positions [s]; per-row i → [B, s] (rope broadcasts a head
        # axis for the 2-D form).
        q, k = apply_rope_qk(q, k, i[..., None] + jnp.arange(s),
                             theta=self.rope_theta)
        k = k.astype(self.dtype)
        v = v.astype(self.dtype)
        if initialized and self.has_variable("cache", "block_table"):
            # PAGED serving (see the vit MHA twin): pool-shaped cache
            # leaves + an engine-stamped per-slot block table replace
            # the contiguous row cache. Post-RoPE keys are cached at
            # their ABSOLUTE positions like the row path, so a shared
            # pool block stays bit-valid for every referencing slot —
            # the same contract the prefix cache's copies relied on,
            # now without the copies. Rolling (ring) caches are never
            # paged; the serving engine refuses ring models outright.
            if ring is not None:
                raise NotImplementedError(
                    "paged attention requires a full-length cache; "
                    "rolling sliding-window caches are not paged")
            from pddl_tpu.ops.attention import (  # noqa: PLC0415
                paged_cache_insert,
                paged_decode_attention,
            )

            # Declared (not just read) so the mutated cache keeps the
            # leaf and the donated tree's structure stays stable.
            table = self.variable(
                "cache", "block_table",
                lambda: jnp.zeros((1, 1), jnp.int32)).value
            cached_k.value = paged_cache_insert(cached_k.value, k, table, i)
            cached_v.value = paged_cache_insert(cached_v.value, v, table, i)
            index.value = i + s
            o = paged_decode_attention(q, cached_k.value, cached_v.value,
                                       table, i, window=self.sliding_window)
            o = o.transpose(0, 2, 1, 3).reshape(
                b, s, self.num_heads * head_dim)
            return dense(features=self.num_heads * head_dim, name="out")(o)
        # Pre-write ring state: the multi-token ring path attends history
        # from here (the block's own writes below may overwrite in-window
        # history slots that this block's EARLY queries still need).
        hist_k, hist_v = cached_k.value, cached_v.value
        if initialized:
            if i.ndim:
                # Per-row scatter at i[b] + arange(s) (ring rows wrap
                # their slot; s > 1 is full-length-cache only — gated
                # above). Multi-token blocks are the speculative verify
                # write: out-of-range positions drop (jit scatter OOB),
                # so draft lookahead past the cache edge never lands.
                rows = jnp.arange(b)[:, None]          # [B, 1]
                pos = i[:, None] + jnp.arange(s)       # [B, s]
                slot = pos % ring if ring is not None else pos
                cached_k.value = cached_k.value.at[rows, :, slot].set(
                    jnp.moveaxis(k, 1, 2))
                cached_v.value = cached_v.value.at[rows, :, slot].set(
                    jnp.moveaxis(v, 1, 2))
            elif ring is None:
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k, (0, 0, i, 0))
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v, (0, 0, i, 0))
            elif s == 1:
                slot = i % ring
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k, (0, 0, slot, 0))
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v, (0, 0, slot, 0))
            else:
                # Prefill into the ring: only the last `ring` tokens can
                # survive; scatter them at their slots (consecutive
                # positions → distinct slots).
                keep = min(s, ring)
                slots = (i + jnp.arange(s)[s - keep:]) % ring
                cached_k.value = cached_k.value.at[:, :, slots].set(
                    k[:, :, s - keep:])
                cached_v.value = cached_v.value.at[:, :, slots].set(
                    v[:, :, s - keep:])
            index.value = i + s

        if s > 1:
            # Prefill / chunked prefill, exact for ANY starting index i.
            if ring is not None:
                # Ring path: the block attends within itself through the
                # flash kernel (O(block) memory) and strictly-pre-block
                # history through a sweep of the PRE-WRITE ring; the two
                # normalized partials merge in logsumexp space. At i == 0
                # the history term has -inf lse and zero weight.
                from pddl_tpu.ops.attention import flash_attention_lse

                o_blk, lse_blk = flash_attention_lse(
                    q, k, v, causal=True, window=self.sliding_window)
                o_hist, lse_hist = decode_attention(
                    q, hist_k, hist_v, i, window=self.sliding_window,
                    rolling=True, history_only=True, return_lse=True,
                    chunk=128)
                m = jnp.maximum(lse_blk, lse_hist)
                w_blk = jnp.exp(lse_blk - m)[..., None]
                w_hist = jnp.exp(lse_hist - m)[..., None]
                o = ((o_blk.astype(jnp.float32) * w_blk
                      + o_hist.astype(jnp.float32) * w_hist)
                     / (w_blk + w_hist)).astype(q.dtype)
            else:
                o = decode_attention(
                    q, cached_k.value, cached_v.value, i,
                    window=self.sliding_window, chunk=128)
        else:
            o = decode_attention(
                q, cached_k.value, cached_v.value, i,
                window=self.sliding_window, rolling=ring is not None)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, self.num_heads * head_dim)
        return dense(features=self.num_heads * head_dim, name="out")(o)


class LlamaBlock(nn.Module):
    """Pre-RMSNorm residual block: attention then a SwiGLU MLP — dense,
    or routed over ``moe_experts`` SwiGLU experts (the Mixtral block:
    ``block_sparse_moe`` with top-``moe_top_k`` routing)."""

    num_heads: int
    num_kv_heads: int
    intermediate_dim: int
    rope_theta: float = 10000.0
    attention: str = "flash"
    sliding_window: Optional[int] = None
    qkv_bias: bool = False
    mesh: Optional[Any] = None
    decode: bool = False
    max_decode_len: int = 1024
    moe_experts: int = 0  # >0: Mixtral-style routed SwiGLU experts
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_eval_dropless: bool = True  # eval/serving capacity = S (dropless)
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, /):
        # train is positional-only for remat static_argnums — see
        # vit.TransformerBlock. (SwiGLU has no dropout; train gates the
        # MoE capacity rule: routed blocks drop over-capacity tokens in
        # training but run DROPLESS at eval/serving.)
        e = x.shape[-1]
        h = _rms_norm(self.rms_eps, self.param_dtype, "ln1")(x)
        h = LlamaAttention(
            num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
            rope_theta=self.rope_theta, attention=self.attention,
            sliding_window=self.sliding_window, qkv_bias=self.qkv_bias,
            mesh=self.mesh, decode=self.decode,
            max_decode_len=self.max_decode_len, dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn",
        )(h.astype(self.dtype))
        x = x + h

        h = _rms_norm(self.rms_eps, self.param_dtype, "ln2")(x)
        h = h.astype(self.dtype)
        if self.moe_experts:
            from pddl_tpu.ops.moe import SwitchFFN

            h = SwitchFFN(
                num_experts=self.moe_experts,
                hidden_dim=self.intermediate_dim, top_k=self.moe_top_k,
                capacity_factor=self.moe_capacity_factor,
                eval_dropless=self.moe_eval_dropless,
                expert_act="swiglu", dtype=self.dtype,
                param_dtype=self.param_dtype, name="moe",
            )(h, train)
            return x + h
        dense = functools.partial(nn.Dense, use_bias=False, dtype=self.dtype,
                                  param_dtype=self.param_dtype)
        gate = dense(self.intermediate_dim, name="mlp_gate")(h)
        up = dense(self.intermediate_dim, name="mlp_up")(h)
        h = dense(e, name="mlp_down")(nn.silu(gate) * up)
        return x + h


class Llama(nn.Module):
    """Decoder-only Llama-architecture LM: tokens ``[B, S]`` → logits.

    Interface-compatible with :class:`pddl_tpu.models.gpt.GPT` where it
    matters — ``max_len``/``decode``/``vocab_size``/``vocab_multiple``/
    ``dtype`` attributes, ``features_only`` apply mode, ``lm_head``
    param naming — so :func:`pddl_tpu.models.gpt.generate` and
    :func:`pddl_tpu.models.gpt.fused_lm_loss` work on it unchanged.
    The same contract is the MULTI-TENANT serving hook
    (`serve/tenant/`): :func:`pddl_tpu.models.gpt.lm_head_logits` and
    :func:`~pddl_tpu.models.gpt.prefill_row_features` reproduce
    :class:`_LlamaHead` op-for-op from the ``features_only`` output
    (bias-free ``lm_head``, padded-vocab slice, f32 cast — keep the
    three in sync), which is what lets per-slot LoRA deltas and
    grammar masks compose onto Llama logits token-exactly.
    """

    vocab_size: int
    max_len: int = 2048
    embed_dim: int = 512
    depth: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # None → MHA (= num_heads)
    intermediate_dim: Optional[int] = None  # None → SwiGLU-standard ~8E/3
    rope_theta: float = 10000.0
    attention: str = "flash"
    sliding_window: Optional[int] = None  # Mistral-style SWA width
    qkv_bias: bool = False  # Qwen2-style q/k/v biases
    mesh: Optional[Any] = None
    remat: str = "none"
    vocab_multiple: int = 1  # pad V for vocab-parallel TP (see gpt.GPT)
    decode: bool = False
    moe_experts: int = 0  # >0: Mixtral — routed SwiGLU experts
    moe_top_k: int = 2  # Mixtral's num_experts_per_tok
    moe_every: int = 1  # Mixtral puts MoE in EVERY layer
    moe_capacity_factor: float = 2.0
    moe_eval_dropless: bool = True  # eval/serving capacity = S (dropless)
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def uses_ring_cache(self) -> bool:
        """True when SWA decode allocates a rolling ring cache (slots
        recycle — cannot be rewound; speculative decoding checks this).
        Same decision, same code as the cache allocation:
        :func:`ring_len` over the blocks' ``max_decode_len`` (=
        ``max_len``, line where the blocks are built)."""
        return ring_len(self.sliding_window, self.max_len) is not None

    @nn.compact
    def __call__(self, tokens, *, train: bool = True,
                 features_only: bool = False):
        kv = self.num_kv_heads or self.num_heads
        inter = self.intermediate_dim
        if inter is None:
            inter = _default_intermediate_dim(self.embed_dim)
        # Stem/head shared with GPipeLlama; share_scope keeps the param
        # names (embed/ln_final/lm_head) at this module's top level.
        embed = _LlamaEmbed(vocab_size=self.vocab_size,
                            embed_dim=self.embed_dim,
                            vocab_multiple=self.vocab_multiple,
                            dtype=self.dtype, param_dtype=self.param_dtype)
        nn.share_scope(self, embed)
        x = embed(tokens)

        block_cls = (LlamaBlock if self.decode
                     else remat_block(LlamaBlock, self.remat))
        for i in range(self.depth):
            # Interleave MoE blocks (every moe_every-th, counted from the
            # back like ViT — Mixtral's moe_every=1 makes every block
            # routed).
            moe = (self.moe_experts
                   if (self.depth - 1 - i) % self.moe_every == 0 else 0)
            x = block_cls(
                num_heads=self.num_heads, num_kv_heads=kv,
                intermediate_dim=inter, rope_theta=self.rope_theta,
                attention=self.attention,
                sliding_window=self.sliding_window,
                qkv_bias=self.qkv_bias, mesh=self.mesh,
                decode=self.decode, max_decode_len=self.max_len,
                moe_experts=moe, moe_top_k=self.moe_top_k,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_eval_dropless=self.moe_eval_dropless,
                rms_eps=self.rms_eps, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train)

        head = _LlamaHead(vocab_size=self.vocab_size,
                          vocab_multiple=self.vocab_multiple,
                          rms_eps=self.rms_eps, dtype=self.dtype,
                          param_dtype=self.param_dtype,
                          features_only=features_only)
        nn.share_scope(self, head)
        return head(x)


def tiny_llama(vocab_size: int = 64, **kwargs) -> Llama:
    """Miniature Llama for tests/dry-runs (GQA exercised: 4 q / 2 kv)."""
    kwargs.setdefault("max_len", 128)
    kwargs.setdefault("embed_dim", 32)
    kwargs.setdefault("depth", 2)
    kwargs.setdefault("num_heads", 4)
    kwargs.setdefault("num_kv_heads", 2)
    kwargs.setdefault("attention", "reference")
    return Llama(vocab_size=vocab_size, **kwargs)


# GPT-2-small-comparable shape (12x768, GQA 12/4) — the benchmark
# configuration (`benchmarks/gpt_train_bench.py --family llama`,
# `benchmarks/decode_bench.py`).
Llama_Small = functools.partial(
    Llama, embed_dim=768, depth=12, num_heads=12, num_kv_heads=4)

# ~300M-parameter mid-size shape (GQA 16/4): big enough that bf16
# parameter/optimizer storage meaningfully matters, small enough to train
# f32 on one chip with no remat — the f32-vs-bf16 convergence comparison
# shape (docs/CONVERGENCE.md).
Llama_300M = functools.partial(
    Llama, embed_dim=1280, depth=16, num_heads=20, num_kv_heads=4,
    intermediate_dim=3456)

# Llama-3.2-1B-shaped config (RoPE theta 500k, GQA 32/8). Fits one v5e
# chip in bf16 for training at moderate batch; the multi-chip strategies
# apply as with every family.
Llama_1B = functools.partial(
    Llama, embed_dim=2048, depth=16, num_heads=32, num_kv_heads=8,
    intermediate_dim=8192, rope_theta=500000.0, max_len=4096)


class _LlamaEmbed(nn.Module):
    """Token embedding (the pre-pipeline Llama stem; RoPE needs no
    positional parameters — positions enter inside each block)."""

    vocab_size: int
    embed_dim: int
    vocab_multiple: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        padded_v = -(-self.vocab_size // self.vocab_multiple) * self.vocab_multiple
        return nn.Embed(padded_v, self.embed_dim, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="embed")(tokens)


class _LlamaStage(nn.Module):
    """One pipeline stage: a run of Llama blocks.

    PP splits LAYERS, never the sequence, so each block's internal
    ``arange(S)`` RoPE positions stay correct on every stage."""

    num_heads: int
    num_kv_heads: int
    intermediate_dim: int
    blocks: int
    rope_theta: float = 10000.0
    attention: str = "reference"
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.blocks):
            x = LlamaBlock(
                num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
                intermediate_dim=self.intermediate_dim,
                rope_theta=self.rope_theta, attention=self.attention,
                rms_eps=self.rms_eps, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, False)
        return x


class _LlamaHead(nn.Module):
    """Final RMSNorm + bias-free LM head (shared by :class:`Llama` via
    ``share_scope`` and by :class:`GPipeLlama` as the post-pipeline
    projection)."""

    vocab_size: int
    vocab_multiple: int = 1
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    features_only: bool = False  # stop after ln_final (fused-CE path)

    @nn.compact
    def __call__(self, x):
        x = _rms_norm(self.rms_eps, self.param_dtype, "ln_final")(x)
        if self.features_only and not self.is_initializing():
            # Pre-head features for fused CE. init() falls through to the
            # Dense regardless (like gpt._GPTHead), so lm_head params
            # exist even when the first trace goes through fused_lm_loss.
            return x.astype(self.dtype)
        padded_v = -(-self.vocab_size // self.vocab_multiple) * self.vocab_multiple
        logits = nn.Dense(padded_v, use_bias=False, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="lm_head")(
                              x.astype(self.dtype))
        return logits[..., :self.vocab_size].astype(jnp.float32)


class GPipeLlama(GPipeModel):
    """Pipeline-parallel modern-decoder LM: PP x the Llama architecture —
    token embed (replicated) → ``n_stages`` stacked RoPE/RMSNorm/SwiGLU
    stages through the GPipe schedule → bias-free head (replicated).
    Completes the PP row of the parallelism x family matrix alongside
    :class:`pddl_tpu.models.vit.GPipeViT` and
    :class:`pddl_tpu.models.gpt.GPipeGPT`."""

    def __init__(self, *, vocab_size: int, n_stages: int,
                 blocks_per_stage: int, n_microbatches: int, mesh,
                 embed_dim: int = 256, num_heads: int = 4,
                 num_kv_heads: Optional[int] = None,
                 intermediate_dim: Optional[int] = None,
                 rope_theta: float = 10000.0,
                 attention: str = "reference", rms_eps: float = 1e-5,
                 remat_stages: bool = False,
                 dtype: Any = jnp.float32, param_dtype: Any = jnp.float32):
        kv = num_kv_heads or num_heads
        if intermediate_dim is None:
            intermediate_dim = _default_intermediate_dim(embed_dim)
        super().__init__(
            embed=_LlamaEmbed(vocab_size=vocab_size, embed_dim=embed_dim,
                              dtype=dtype, param_dtype=param_dtype),
            stage=_LlamaStage(num_heads=num_heads, num_kv_heads=kv,
                              intermediate_dim=intermediate_dim,
                              blocks=blocks_per_stage,
                              rope_theta=rope_theta, attention=attention,
                              rms_eps=rms_eps, dtype=dtype,
                              param_dtype=param_dtype),
            head=_LlamaHead(vocab_size=vocab_size, rms_eps=rms_eps,
                            dtype=dtype, param_dtype=param_dtype),
            n_stages=n_stages, n_microbatches=n_microbatches, mesh=mesh,
            remat_stages=remat_stages,
        )
