"""Tiny model registry so configs can name models by string."""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}

# Registry names whose models consume TOKEN batches (language models):
# run.py keys data/augmentation decisions off membership here — an exact
# per-name property, never a substring heuristic (a vision model whose
# name merely contains 'gpt' must not be fed token batches).
LM_MODELS: set = set()

# Registry names whose factories accept the ``remat`` kwarg (transformer
# families with rematerializable blocks) — same exact-membership rule.
REMAT_MODELS: set = set()


def register_model(name: str, factory: Callable | None = None, *,
                   is_lm: bool = False, supports_remat: bool = False):
    """Register a model factory; usable as a decorator or a call."""
    if is_lm:
        LM_MODELS.add(name)
        REMAT_MODELS.add(name)  # every LM family here is remat-capable
    if supports_remat:
        REMAT_MODELS.add(name)
    if factory is not None:
        _REGISTRY[name] = factory
        return factory

    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_model(name: str, **kwargs):
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def _populate() -> None:
    from pddl_tpu.models import resnet, vit

    register_model("resnet18", resnet.ResNet18)
    register_model("resnet34", resnet.ResNet34)
    register_model("resnet50", resnet.ResNet50)
    register_model("resnet101", resnet.ResNet101)
    register_model("resnet152", resnet.ResNet152)
    register_model("tiny_resnet", resnet.tiny_resnet)

    def _vit(factory):
        # ViTs take no bn_mode (no BatchNorm anywhere in a ViT); accept and
        # drop it so configs stay uniform across model families.
        def make(bn_mode: str = "train", **kwargs):
            return factory(**kwargs)

        return make

    register_model("vit_s16", _vit(vit.ViT_S16), supports_remat=True)
    register_model("vit_b16", _vit(vit.ViT_B16), supports_remat=True)
    register_model("vit_l16", _vit(vit.ViT_L16), supports_remat=True)
    register_model("tiny_vit", _vit(vit.tiny_vit), supports_remat=True)

    from pddl_tpu.models import gpt

    # GPT configs take no bn_mode; num_classes maps onto vocab_size so the
    # uniform ExperimentConfig drives LMs too (run.py sets the LM batch
    # keys and synthetic-text data for these names).
    def _gpt(factory):
        def make(bn_mode: str = "train", num_classes: int = 0, **kwargs):
            # Same fallback as run.py's synthetic-text vocab (num_classes
            # or 64), so the model and data always agree on vocab size.
            kwargs.setdefault("vocab_size", num_classes or 64)
            return factory(**kwargs)

        return make

    register_model("gpt_small", _gpt(gpt.GPT_Small), is_lm=True)
    register_model("tiny_gpt", _gpt(gpt.tiny_gpt), is_lm=True)

    from pddl_tpu.models import llama

    # Llama configs ride the same LM adapter (vocab from num_classes).
    register_model("llama_small", _gpt(llama.Llama_Small), is_lm=True)
    register_model("llama_300m", _gpt(llama.Llama_300M), is_lm=True)
    register_model("llama_1b", _gpt(llama.Llama_1B), is_lm=True)
    register_model("tiny_llama", _gpt(llama.tiny_llama), is_lm=True)


_populate()
