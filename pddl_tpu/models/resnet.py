"""Flax ResNet family with exact ``tf.keras.applications`` architecture parity.

The reference's only model is ``tf.keras.applications.ResNet50(include_top=
False, pooling='avg')`` plus a ``Dense(1000, softmax)`` head
(``/root/reference/imagenet-resnet50.py:51-61``). This module provides that
model natively in Flax — same layer structure, BN hyper-parameters
(``epsilon=1.001e-5``, ``momentum=0.99``) and downsampling placement as the
Keras v1 architecture so pretrained ``.h5`` weights import exactly
(:mod:`pddl_tpu.ckpt.keras_import`) — plus the rest of the family and a
TPU-friendlier v1.5 variant.

TPU-first design notes:

- NHWC layout and optional bfloat16 compute dtype: convs land on the MXU as
  large tiled contractions; params stay float32 for stable BN/optimizer math.
- BatchNorm mode is explicit, because the reference's most consequential quirk
  is calling the backbone with ``training=False`` even when training from
  scratch (``imagenet-resnet50.py:57`` — BN frozen in inference mode,
  moving averages never updated; SURVEY.md §0). ``bn_mode`` reproduces either
  behavior deliberately:

  * ``"train"``  — correct from-scratch training (batch stats + EMA update).
  * ``"frozen"`` — reference-faithful / fine-tune mode: running averages only.

- Cross-replica BN comes for free in the trainer's jit-with-shardings regime
  (a mean over the globally-sharded batch dim *is* a cross-replica reduction);
  ``axis_name`` is exposed for per-replica (shard_map) execution.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

# Keras BN hyper-parameters (keras.applications.resnet: epsilon 1.001e-5).
BN_EPSILON = 1.001e-5
BN_MOMENTUM = 0.99


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152).

    ``stride_in_3x3=False`` matches Keras v1 (downsample in the first 1x1,
    ``keras.applications.resnet.block1``); ``True`` is the v1.5 placement
    (better accuracy/FLOP, used by torchvision and MLPerf).
    """

    filters: int
    stride: int = 1
    conv_shortcut: bool = False
    stride_in_3x3: bool = False
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        s1 = 1 if self.stride_in_3x3 else self.stride
        s3 = self.stride if self.stride_in_3x3 else 1

        if self.conv_shortcut:
            shortcut = self.conv(4 * self.filters, (1, 1), strides=(self.stride,) * 2,
                                 name="shortcut_conv")(x)
            shortcut = self.norm(name="shortcut_bn")(shortcut)
        else:
            shortcut = x

        y = self.conv(self.filters, (1, 1), strides=(s1, s1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(s3, s3), padding="SAME",
                      name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(4 * self.filters, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        return self.act(y + shortcut)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    stride: int = 1
    conv_shortcut: bool = False
    stride_in_3x3: bool = False  # unused; kept for a uniform block signature
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        if self.conv_shortcut:
            shortcut = self.conv(self.filters, (1, 1), strides=(self.stride,) * 2,
                                 name="shortcut_conv")(x)
            shortcut = self.norm(name="shortcut_bn")(shortcut)
        else:
            shortcut = x
        y = self.conv(self.filters, (3, 3), strides=(self.stride,) * 2,
                      padding="SAME", name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding="SAME", name="conv2")(y)
        y = self.norm(name="bn2")(y)
        return self.act(y + shortcut)


class ResNet(nn.Module):
    """Configurable ResNet with Keras-v1 architecture parity.

    Args mirror the knobs the reference exercises:

    - ``num_classes`` + softmax-ready logits head: the reference's
      ``Dense(1000, activation='softmax')`` head
      (``imagenet-resnet50.py:60``) — we return *logits* (the loss applies
      log-softmax; numerically safer and XLA-fusable).
    - ``include_top=False`` + ``pooling='avg'`` behavior is available via
      ``num_classes=0`` (returns pooled features), matching
      ``imagenet-resnet50.py:56``.
    - ``bn_mode``: see module docstring.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    width_multiplier: float = 1.0
    stride_in_3x3: bool = False  # False = Keras v1 parity
    small_input_stem: bool = False  # 3x3/s1 stem, no maxpool (CIFAR/tests)
    dtype: Any = jnp.float32  # compute dtype; bfloat16 for TPU speed
    param_dtype: Any = jnp.float32
    bn_mode: str = "train"  # "train" | "frozen"
    axis_name: Optional[str] = None  # per-replica sync-BN axis (shard_map only)
    kernel_init: Callable = nn.initializers.he_normal()

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        use_running_average = (not train) or self.bn_mode == "frozen"
        conv = functools.partial(
            nn.Conv,
            use_bias=True,  # Keras Conv2D keeps bias even before BN
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=self.kernel_init,
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=use_running_average,
            momentum=BN_MOMENTUM,
            epsilon=BN_EPSILON,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            axis_name=self.axis_name if (train and self.bn_mode == "train") else None,
        )
        width = lambda f: max(8, int(f * self.width_multiplier))

        x = x.astype(self.dtype)
        if self.small_input_stem:
            x = conv(width(64), (3, 3), padding="SAME", name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        else:
            # Keras: ZeroPadding(3) -> 7x7/2 valid conv -> BN -> ReLU
            #        -> ZeroPadding(1) -> 3x3/2 valid maxpool.
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            x = conv(width(64), (7, 7), strides=(2, 2), padding="VALID",
                     name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            # Keras zero-pads then max-pools VALID; inputs are post-ReLU
            # (>= 0) so zero padding is exact.
            x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        for stage, n_blocks in enumerate(self.stage_sizes):
            filters = width(64 * 2 ** stage)
            for block in range(n_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = self.block_cls(
                    filters=filters,
                    stride=stride,
                    conv_shortcut=(block == 0),
                    stride_in_3x3=self.stride_in_3x3,
                    conv=conv,
                    norm=norm,
                    name=f"stage{stage + 1}_block{block + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool ('avg' pooling)
        if self.num_classes:
            x = nn.Dense(
                self.num_classes,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.initializers.glorot_uniform(),  # Keras Dense default
                name="head",
            )(x)
        return x.astype(jnp.float32)  # logits/features in f32 for stable loss


ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)


def tiny_resnet(num_classes: int = 10, **kwargs) -> ResNet:
    """A miniature ResNet for tests and dry-runs (fast on a CPU fake mesh)."""
    kwargs.setdefault("stage_sizes", (1, 1))
    kwargs.setdefault("block_cls", BasicBlock)
    kwargs.setdefault("width_multiplier", 0.125)
    kwargs.setdefault("small_input_stem", True)
    return ResNet(num_classes=num_classes, **kwargs)
