"""Flax ResNet family with exact ``tf.keras.applications`` architecture parity.

The reference's only model is ``tf.keras.applications.ResNet50(include_top=
False, pooling='avg')`` plus a ``Dense(1000, softmax)`` head
(``/root/reference/imagenet-resnet50.py:51-61``). This module provides that
model natively in Flax — same layer structure, BN hyper-parameters
(``epsilon=1.001e-5``, ``momentum=0.99``) and downsampling placement as the
Keras v1 architecture so pretrained ``.h5`` weights import exactly
(:mod:`pddl_tpu.ckpt.keras_import`) — plus the rest of the family and a
TPU-friendlier v1.5 variant.

TPU-first design notes:

- NHWC layout and optional bfloat16 compute dtype: convs land on the MXU as
  large tiled contractions; params stay float32 for stable BN/optimizer math.
- BatchNorm mode is explicit, because the reference's most consequential quirk
  is calling the backbone with ``training=False`` even when training from
  scratch (``imagenet-resnet50.py:57`` — BN frozen in inference mode,
  moving averages never updated; SURVEY.md §0). ``bn_mode`` reproduces either
  behavior deliberately:

  * ``"train"``  — correct from-scratch training (batch stats + EMA update).
  * ``"frozen"`` — reference-faithful / fine-tune mode: running averages only.

- Cross-replica BN comes for free in the trainer's jit-with-shardings regime
  (a mean over the globally-sharded batch dim *is* a cross-replica reduction);
  ``axis_name`` is exposed for per-replica (shard_map) execution.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

# Keras BN hyper-parameters (keras.applications.resnet: epsilon 1.001e-5).
BN_EPSILON = 1.001e-5
BN_MOMENTUM = 0.99


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152).

    ``stride_in_3x3=False`` matches Keras v1 (downsample in the first 1x1,
    ``keras.applications.resnet.block1``); ``True`` is the v1.5 placement
    (better accuracy/FLOP, used by torchvision and MLPerf).
    """

    filters: int
    stride: int = 1
    conv_shortcut: bool = False
    stride_in_3x3: bool = False
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        s1 = 1 if self.stride_in_3x3 else self.stride
        s3 = self.stride if self.stride_in_3x3 else 1

        if self.conv_shortcut:
            shortcut = self.conv(4 * self.filters, (1, 1), strides=(self.stride,) * 2,
                                 name="shortcut_conv")(x)
            shortcut = self.norm(name="shortcut_bn")(shortcut)
        else:
            shortcut = x

        y = self.conv(self.filters, (1, 1), strides=(s1, s1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(s3, s3), padding="SAME",
                      name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(4 * self.filters, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        return self.act(y + shortcut)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    stride: int = 1
    conv_shortcut: bool = False
    stride_in_3x3: bool = False  # unused; kept for a uniform block signature
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        if self.conv_shortcut:
            shortcut = self.conv(self.filters, (1, 1), strides=(self.stride,) * 2,
                                 name="shortcut_conv")(x)
            shortcut = self.norm(name="shortcut_bn")(shortcut)
        else:
            shortcut = x
        y = self.conv(self.filters, (3, 3), strides=(self.stride,) * 2,
                      padding="SAME", name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding="SAME", name="conv2")(y)
        y = self.norm(name="bn2")(y)
        return self.act(y + shortcut)


class ResNet(nn.Module):
    """Configurable ResNet with Keras-v1 architecture parity.

    Args mirror the knobs the reference exercises:

    - ``num_classes`` + softmax-ready logits head: the reference's
      ``Dense(1000, activation='softmax')`` head
      (``imagenet-resnet50.py:60``) — we return *logits* (the loss applies
      log-softmax; numerically safer and XLA-fusable).
    - ``include_top=False`` + ``pooling='avg'`` behavior is available via
      ``num_classes=0`` (returns pooled features), matching
      ``imagenet-resnet50.py:56``.
    - ``bn_mode``: see module docstring.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckBlock
    num_classes: int = 1000
    width_multiplier: float = 1.0
    stride_in_3x3: bool = False  # False = Keras v1 parity
    small_input_stem: bool = False  # 3x3/s1 stem, no maxpool (CIFAR/tests)
    # "keras" (7x7/s2, exact keras.applications parity) or
    # "space_to_depth": the MLPerf-style stem — block-2 space-to-depth on
    # the padded input followed by a 4x4/s1 VALID conv. The 4x4x12 kernel
    # space EQUALS the zero-padded-8x8x3 kernel space, so this computes
    # exactly the padded 7x7/s2 stem (see s2d_stem_kernel for the exact
    # Keras-weight transform) while feeding the MXU 12 input channels
    # instead of 3 and halving the stem's activation traffic. Opt-in:
    # throughput variant; the default stays import-parity-shaped.
    stem: str = "keras"
    dtype: Any = jnp.float32  # compute dtype; bfloat16 for TPU speed
    param_dtype: Any = jnp.float32
    bn_mode: str = "train"  # "train" | "frozen"
    # Keras-parity 0.99 by default. Lower it (e.g. 0.9) for SHORT runs:
    # inference-mode metrics read the moving averages, and at 0.99 they
    # carry ~[momentum^steps] of their zero/one init — ~45% after 80
    # updates — so val metrics on few-hundred-step runs measure stat
    # settling, not the model (the reference's 40k-step ImageNet epochs
    # never see this; tiny synthetic epochs do).
    bn_momentum: float = BN_MOMENTUM
    axis_name: Optional[str] = None  # per-replica sync-BN axis (shard_map only)
    kernel_init: Callable = nn.initializers.he_normal()

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        use_running_average = (not train) or self.bn_mode == "frozen"
        conv = functools.partial(
            nn.Conv,
            use_bias=True,  # Keras Conv2D keeps bias even before BN
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=self.kernel_init,
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=use_running_average,
            momentum=self.bn_momentum,
            epsilon=BN_EPSILON,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            axis_name=self.axis_name if (train and self.bn_mode == "train") else None,
        )
        width = lambda f: max(8, int(f * self.width_multiplier))

        if self.small_input_stem and self.stem != "keras":
            raise ValueError(
                f"small_input_stem=True conflicts with stem={self.stem!r}: "
                "the small 3x3/s1 stem would silently win; pick one"
            )
        x = x.astype(self.dtype)
        if self.small_input_stem:
            x = conv(width(64), (3, 3), padding="SAME", name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        elif self.stem == "space_to_depth":
            # Same function as the Keras stem below: with X the 3-padded
            # input, out(i,j) = sum_{u,v<7} X(2i+u,2j+v)K(u,v). Splitting
            # u=2a+p, v=2b+q (p,q in {0,1}) turns that into a 4x4 STRIDE-1
            # conv over the block-2 space-to-depth view Y(r,c,(p,q,ch)) =
            # X(2r+p,2c+q,ch) with kernel K2(a,b,(p,q,ch)) = K8(2a+p,2b+q,
            # ch), K8 = K zero-padded to 8x8 — so the trainable 4x4x12
            # kernel spans exactly the padded-7x7x3 function space.
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth stem needs even padded input dims, "
                    f"got {h}x{w} (input {h - 6}x{w - 6})"
                )
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
            x = conv(width(64), (4, 4), strides=(1, 1), padding="VALID",
                     name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        else:
            # Keras: ZeroPadding(3) -> 7x7/2 valid conv -> BN -> ReLU
            #        -> ZeroPadding(1) -> 3x3/2 valid maxpool.
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            x = conv(width(64), (7, 7), strides=(2, 2), padding="VALID",
                     name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            # Keras zero-pads then max-pools VALID; inputs are post-ReLU
            # (>= 0) so zero padding is exact.
            x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        for stage, n_blocks in enumerate(self.stage_sizes):
            filters = width(64 * 2 ** stage)
            for block in range(n_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = self.block_cls(
                    filters=filters,
                    stride=stride,
                    conv_shortcut=(block == 0),
                    stride_in_3x3=self.stride_in_3x3,
                    conv=conv,
                    norm=norm,
                    name=f"stage{stage + 1}_block{block + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool ('avg' pooling)
        if self.num_classes:
            x = nn.Dense(
                self.num_classes,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.initializers.glorot_uniform(),  # Keras Dense default
                name="head",
            )(x)
        return x.astype(jnp.float32)  # logits/features in f32 for stable loss


def s2d_stem_kernel(k7: jnp.ndarray) -> jnp.ndarray:
    """Exact transform of a Keras stem kernel to the space-to-depth stem.

    ``[7, 7, C, F] -> [4, 4, 4C, F]``: zero-pad the kernel to 8x8 at the
    trailing edge, then regroup ``K8(2a+p, 2b+q, ch)`` into
    ``K2(a, b, (p, q, ch))`` — the inverse of the activation regrouping in
    :class:`ResNet`'s ``space_to_depth`` stem, so
    ``conv_s2d(s2d(x), s2d_stem_kernel(K)) == conv_7x7_s2(x, K)`` exactly.
    Used by the ``.h5`` import path to load pretrained Keras weights into
    the throughput variant.
    """
    kh, kw, c, f = k7.shape
    if (kh, kw) != (7, 7):
        raise ValueError(f"expected a 7x7 stem kernel, got {k7.shape}")
    k8 = jnp.pad(k7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    k2 = k8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return k2.reshape(4, 4, 4 * c, f)


def s2d_stem_kernel_inverse(k2: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`s2d_stem_kernel`: ``[4, 4, 4C, F] -> [7, 7, C, F]``.

    Lets an ``.h5`` exported from a space-to-depth model load back into
    the Keras-shaped stem (and into real Keras via ``by_name``). The
    padded row/column (kernel taps 7 in each spatial dim) is sliced away;
    for kernels produced by :func:`s2d_stem_kernel` those taps are zero,
    and for TRAINED s2d kernels they carry the weights of input pixels the
    7x7 stem cannot see — dropping them is the closest 7x7 function.
    """
    kh, kw, c4, f = k2.shape
    if (kh, kw) != (4, 4) or c4 % 4:
        raise ValueError(f"expected a 4x4x(4C) s2d stem kernel, got {k2.shape}")
    c = c4 // 4
    k8 = k2.reshape(4, 4, 2, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return k8.reshape(8, 8, c, f)[:7, :7]


ResNet18 = functools.partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)


def tiny_resnet(num_classes: int = 10, **kwargs) -> ResNet:
    """A miniature ResNet for tests and dry-runs (fast on a CPU fake mesh)."""
    kwargs.setdefault("stage_sizes", (1, 1))
    kwargs.setdefault("block_cls", BasicBlock)
    kwargs.setdefault("width_multiplier", 0.125)
    kwargs.setdefault("small_input_stem", True)
    return ResNet(num_classes=num_classes, **kwargs)
