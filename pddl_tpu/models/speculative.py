"""Speculative decoding: multi-token ticks via prompt-lookup drafting.

Why this exists (the measured motivation): ARCHITECTURE.md §7e attributes
single-stream decode to a **0.289 ms per-tick FIXED serial-latency cost**
(scan tick machinery + the dependency-chain latency of ~130 small GEMV
ops) that is batch- and width-INDEPENDENT — the same tick that computes
one token's logits can compute eight tokens' logits for nearly the same
wall-clock, because the weight reads and the serial op chain are shared.
Single-token decode therefore pays the whole fixed cost per token; the
only lever left standing is fewer, wider ticks. This module is that
lever.

Scheme (prompt-lookup / n-gram self-drafting — no draft model):

1. DRAFT: find the most recent earlier occurrence of the last ``ngram``
   tokens in the sequence so far and propose the ``draft_len`` tokens
   that followed it. On repetitive text (code, logs — e.g. the byte-level
   Python corpus the convergence tracks train on) this guesses long runs
   correctly; on text with no self-similarity it simply proposes junk.
2. VERIFY: run ONE forward over the ``draft_len + 1`` block
   ``[current, d_1..d_k]`` through the ordinary KV-cache decode module —
   the same chunked-prefill path :func:`~pddl_tpu.models.gpt.generate`
   uses for prompts (causal within the block, K/V written at the running
   index, RoPE/positions from the index) — and greedy-decode every
   position: ``y_j = argmax(logits_j)``.
3. ACCEPT the longest prefix with ``d_{j+1} == y_j`` (``m`` drafts), emit
   ``y_0..y_m`` — ``m + 1`` tokens from one tick — and REWIND the cache
   index to the position after the last accepted token. Rejected
   positions hold stale K/V beyond the index; the prefix-bounded cache
   sweep (`ops/attention.py decode_attention`) never reads past the
   index, and the next tick's ``draft_len + 1``-wide write overwrites
   them before the index crosses.

Every emitted token is the argmax of the true model given the true
prefix, so the output is **bit-identical to greedy** ``generate()`` —
acceptance rate changes only the speed. Worst case (nothing ever
matches) each tick still emits one token, i.e. plain greedy decode at
one verify-width forward per tick. One hardware nuance, pinned by
`tests_tpu/`: the k+1-wide verify block and the one-token tick are
different COMPILED programs, so their bf16 logits can differ by ulps —
at a genuine numerical tie (untrained models; never trained margins)
the two argmaxes may break differently, and both outputs are then
valid greedy decodes. The trained-model chip benches assert
bit-equality every run.

Batching: acceptance is ``min`` over the batch (the KV caches share one
scalar index), which stays exact for every row — a row whose drafts
matched further simply re-derives those tokens next tick. The win is
largest at B=1, which is exactly where the fixed per-tick cost dominates
(§7e).

Tensor parallelism composes (``strategy=``): the verify forward runs
Megatron-sharded with its ICI all-reduces while the draft/accept/rewind
machinery stays on the replicated token buffer — acceptance depends
only on logits, which TP reproduces exactly.

Temperature sampling composes too: ``temperature > 0`` switches the
verifier to SPECULATIVE SAMPLING (rejection scheme — accept draft ``d``
with probability ``p(d)``, sample the masked residual on rejection, a
bonus draw when everything survives; see ``_spec_fns``), which draws
every token from exactly the filtered distribution
``gpt.sample_logits`` uses — unbiased, just fewer ticks.

Exclusions, all validated loudly: no sliding-window RING cache (a
partially rejected block has already overwritten ring slots that rolled
out of the window but are still inside it for the rewound position —
unsound to rewind; models whose ``sliding_window`` rounds up to
``>= max_len`` use a full cache and remain eligible); int8
``param_transform`` is unsharded-only.

Reference stake: the reference's endpoint is ``model.save`` then serve
(`/root/reference/imagenet-resnet50.py:72`); this is the serving path's
throughput story for the LM families.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pddl_tpu.models.gpt import _decode_cache_shapes

__all__ = ["generate_speculative", "ngram_drafts"]


def ngram_drafts(toks, cur_pos, ngram: int, draft_len: int):
    """Prompt-lookup draft: ``[B, draft_len]`` continuations of the most
    recent earlier occurrence of the trailing ``ngram``.

    ``toks`` is the full token buffer ``[B, L]`` (prompt + emitted so
    far; positions > ``cur_pos`` hold junk), ``cur_pos`` the position of
    the last known token — a SCALAR (the one-shot loop below, whose
    rows share one cache index) or a per-row ``[B]`` int32 vector (the
    serving engine's slot model, where every row is an independent
    request at its own depth). THE one drafter definition: the one-shot
    ``generate_speculative`` loop and ``ServeEngine``'s per-slot draft
    program both compile exactly this function, so the two paths cannot
    drift (pinned by an equivalence test). All shapes static;
    `dynamic_slice` clamping makes out-of-range starts harmless (they
    yield junk drafts, which verification rejects — exactness never
    depends on the draft).
    """
    b, length = toks.shape
    cur_pos = jnp.asarray(cur_pos, jnp.int32)
    pos_b = jnp.broadcast_to(cur_pos, (b,))  # [B] either way
    # Trailing n-gram ending at each row's cur_pos (clamped left at the
    # buffer edge). Per-row dynamic_slice via vmap — identical to the
    # historical shared-scalar slice when every row carries one value.
    query = jax.vmap(
        lambda row, p: jax.lax.dynamic_slice(
            row, (p - (ngram - 1),), (ngram,)))(toks, pos_b)  # [B, ngram]
    # All length-n windows: wins[i, :, w] = toks[:, w + i].
    n_win = length - ngram + 1
    wins = jnp.stack([toks[:, i:i + n_win] for i in range(ngram)], axis=0)
    hit = jnp.all(wins == query.T[:, :, None], axis=0)  # [B, n_win]
    # A usable window ends strictly before the row's cur_pos (the
    # window ending AT cur_pos is the query itself).
    starts = jnp.arange(n_win)[None, :]
    usable = hit & (starts <= pos_b[:, None] - ngram)
    best = jnp.max(jnp.where(usable, starts, -1), axis=1)  # [B]
    found = best >= 0

    def take(row, start):  # per-row continuation after the matched window
        return jax.lax.dynamic_slice(row, (start,), (draft_len,))

    drafts = jax.vmap(take)(toks, jnp.where(found, best + ngram, 0))
    # No match → propose the last token repeated: free (the tick runs
    # anyway) and occasionally right on run-length text.
    fallback = jnp.broadcast_to(query[:, -1:], (b, draft_len))
    return jnp.where(found[:, None], drafts, fallback)


# The historical private name (kept so long-lived call sites and tests
# keep working; the public name above is the API).
_ngram_drafts = ngram_drafts


def _rewind_index(cache, new_index):
    """Set every cache position counter to ``new_index``.

    Counters are matched BY NAME (``pos_index``/``cache_index`` —
    :data:`pddl_tpu.models.gpt.CACHE_INDEX_KEYS`, the same registry the
    serving engine's slot machinery uses), never by scalar-int32 duck
    typing: a future scalar int32 cache leaf that is NOT a position (a
    step counter, say) must not be silently rewound.
    ``tests/test_speculative.py`` enumerates the scalar int32 cache
    leaves of every family, so adding one forces a decision here. Stale
    K/V beyond the index is unreachable (prefix-bounded sweep) until
    overwritten by the next block write.
    """
    from pddl_tpu.models.gpt import is_cache_index_path

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (jnp.full_like(leaf, new_index)
                            if is_cache_index_path(path) else leaf),
        cache)


def _spec_fns(dec, draft_len: int, ngram: int, param_transform=None,
              temperature: float = 0.0, top_k=None, top_p=None):
    """(prefill, loop) python callables — the speculative twin of
    ``gpt._decode_fns``; the jit wrappers below (unsharded and
    tensor-parallel) compile exactly these.

    ``temperature > 0`` switches the verifier from exact-greedy
    acceptance to SPECULATIVE SAMPLING (the standard rejection scheme
    for a point-mass draft): draft ``d`` under target distribution
    ``p`` is accepted with probability ``p(d)``; on the first rejection
    the correction token samples the residual ``norm(max(p - 1_d, 0))``
    — i.e. ``p`` with ``d`` masked out — and when every draft survives,
    a bonus token samples ``p`` directly. Every emitted token is an
    exact draw from the model's (temperature/top-k/top-p filtered)
    conditional, the same distribution ``gpt.sample_logits`` draws from
    (the filter pipeline is literally shared: ``gpt.filtered_logits``),
    so speculation changes the speed, never the distribution. Min-over-
    batch truncation stays unbiased: a truncated row's later tokens are
    re-drawn next tick from the correct conditionals with fresh
    randomness, and its kept tokens used only coins at their own
    positions.
    """
    width = draft_len + 1
    buf_len = dec.max_len + width
    pt = param_transform or (lambda p: p)
    sampling = temperature > 0

    def _warp(logits):  # [..., V] -> f32 filtered sampling logits
        from pddl_tpu.models.gpt import filtered_logits

        return filtered_logits(logits, temperature=temperature,
                               top_k=top_k, top_p=top_p)

    def prefill(params, prompt):
        b, p = prompt.shape
        cache = jax.tree.map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype),
            _decode_cache_shapes(dec, b))
        # pt applies PER USE SITE (here and in the loop body), never
        # once up front: a pre-loop transform would be loop-invariant,
        # and XLA would hoist the dequantized dense weights out of the
        # while loop — materializing exactly the copy int8 storage is
        # meant to avoid.
        logits, mutated = dec.apply(
            {"params": pt(params), "cache": cache}, prompt,
            train=False, mutable=["cache"])
        toks = jnp.zeros((b, buf_len), jnp.int32)
        toks = jax.lax.dynamic_update_slice(toks, prompt, (0, 0))
        return mutated["cache"], toks, logits[:, -1]

    def loop(params, cache, toks, last_logits, prompt_len, max_new, rng):
        b = toks.shape[0]
        if sampling:
            rng, sub = jax.random.split(rng)
            first = jax.random.categorical(sub, _warp(last_logits), axis=-1)
        else:
            first = jnp.argmax(last_logits, axis=-1)
        toks = jax.lax.dynamic_update_slice(
            toks, first.astype(jnp.int32)[:, None], (0, prompt_len))

        def cond(state):
            _, n_out, _, _, _ = state
            return n_out < max_new

        def body(state):
            toks, n_out, cache, ticks, rng = state
            cur_pos = prompt_len + n_out - 1  # position of the last token
            drafts = ngram_drafts(toks, cur_pos, ngram, draft_len)
            cur = jax.lax.dynamic_slice(toks, (0, cur_pos), (b, 1))
            block = jnp.concatenate([cur, drafts], axis=1)  # [B, width]
            logits, mutated = dec.apply(
                {"params": pt(params), "cache": cache}, block,
                train=False, mutable=["cache"])
            cache = mutated["cache"]
            if not sampling:
                y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # Longest accepted draft prefix, min over the batch
                # (shared cache index): cumprod turns the first mismatch
                # into zeros.
                match = (block[:, 1:] == y[:, :-1]).astype(jnp.int32)
                accepted = jnp.min(
                    jnp.sum(jnp.cumprod(match, axis=1), axis=1))
                window = y
            else:
                flog = _warp(logits)  # [B, width, V]
                probs = jax.nn.softmax(flog, axis=-1)
                rng, k_coin, k_fix = jax.random.split(rng, 3)
                # Coin j tests draft d_{j+1} against p_j: accept w.p.
                # p_j(d_{j+1}) (point-mass draft => the accept ratio is
                # just the target probability).
                p_draft = jnp.take_along_axis(
                    probs[:, :-1], drafts[..., None], axis=-1)[..., 0]
                ok = (jax.random.uniform(k_coin, p_draft.shape)
                      < p_draft).astype(jnp.int32)
                m_row = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)
                accepted = jnp.min(m_row)
                # Token for slot `accepted`: rows whose own coin
                # rejected exactly there (m_row == accepted <
                # draft_len) draw the RESIDUAL (p with the rejected
                # draft masked); when every draft of every row survived
                # (accepted == draft_len, so m_row == accepted for all
                # rows), it's the bonus draw from p_k. Rows truncated
                # by the batch min (m_row > accepted) KEEP their
                # accepted draft — the write below is masked per row,
                # so an already-paid acceptance is never re-drawn.
                flog_last = jax.lax.dynamic_slice(
                    flog, (0, accepted, 0), (b, 1, flog.shape[-1]))[:, 0]
                d_next = jax.lax.dynamic_slice(
                    block, (0, jnp.minimum(accepted + 1, draft_len)),
                    (b, 1))[:, 0]
                rejected_here = (m_row == accepted) & (accepted < draft_len)
                vocab = flog.shape[-1]
                mask = (rejected_here[:, None]
                        & (jax.nn.one_hot(d_next, vocab, dtype=bool)))
                masked = jnp.where(mask, -jnp.inf, flog_last)
                # Degenerate residual (the draft carried ~all the mass,
                # e.g. top_k=1): fall back to the unmasked distribution
                # rather than sampling from all -inf.
                has_mass = jnp.any(masked > -jnp.inf, axis=-1,
                                   keepdims=True)
                masked = jnp.where(has_mass, masked, flog_last)
                fix = jax.random.categorical(k_fix, masked, axis=-1)
                # Write window: accepted drafts verbatim, the correction/
                # bonus at slot `accepted` ONLY for rows that need one
                # (m_row == accepted); truncated rows keep the draft
                # token already sitting in that slot. The stale tail
                # beyond it is overwritten before the frontier reaches
                # it (width >= tail), same invariant as the greedy path.
                window = jnp.concatenate(
                    [drafts, drafts[:, -1:]], axis=1).astype(jnp.int32)
                kept = jax.lax.dynamic_slice(
                    window, (0, accepted), (b, 1))[:, 0]
                slot_tok = jnp.where(m_row == accepted,
                                     fix.astype(jnp.int32), kept)
                window = jax.lax.dynamic_update_slice(
                    window, slot_tok[:, None], (0, accepted))
            toks = jax.lax.dynamic_update_slice(
                toks, window, (0, prompt_len + n_out))
            cache = _rewind_index(cache, cur_pos + accepted + 1)
            return toks, n_out + accepted + 1, cache, ticks + 1, rng

        toks, n_out, _, ticks, _ = jax.lax.while_loop(
            cond, body, (toks, jnp.int32(1), cache, jnp.int32(0), rng))
        return toks, n_out, ticks

    return prefill, loop


@functools.lru_cache(maxsize=16)
def _spec_programs(dec, draft_len: int, ngram: int, param_transform=None,
                   temperature: float = 0.0, top_k=None, top_p=None):
    """Jitted (prefill, loop) pair, cached on the frozen decode module +
    draft statics — like ``gpt._decode_programs``, params stay jit
    ARGUMENTS (never baked-in constants).

    The split mirrors ``generate()``: prefill re-traces per prompt
    SHAPE (it has to — the prompt is an array), while the speculative
    loop compiles ONCE per (module, batch, draft config) — the token
    buffer is fixed at ``max_len + width`` and prompt length / token
    budget enter as int32 runtime values, so varied-length serving
    traffic neither recompiles the loop nor thrashes the LRU. Each
    request is two dispatches (prefill, loop).

    ``param_transform`` (keyed by identity — pass a module-level
    function) maps the passed params to apply-ready weights inside the
    programs: int8 weight storage composes with speculation this way.
    """
    prefill, loop = _spec_fns(dec, draft_len, ngram, param_transform,
                              temperature, top_k, top_p)
    return jax.jit(prefill), jax.jit(loop, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=16)
def _sharded_spec_programs(dec, draft_len: int, ngram: int,
                           param_sh_def, param_sh_leaves,
                           cache_sh_def, cache_sh_leaves,
                           temperature: float = 0.0, top_k=None,
                           top_p=None):
    """Tensor-parallel twin of :func:`_spec_programs` — same body
    functions, compiled with the strategy's parameter/cache shardings
    (the SPMD partitioner inserts the per-block all-reduces on ICI,
    exactly as in ``gpt._sharded_decode_programs``); the token buffer,
    logits, and scalars stay replicated. Keys are sharding VALUES
    (NamedShardings hash by value), so a strategy rebuilt per request
    still hits the cache.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    if not param_sh_leaves:
        raise ValueError(
            "sharded speculative decode needs a non-empty params tree "
            "(got zero parameter leaves — was the model initialized?)")
    param_sh = jax.tree_util.tree_unflatten(param_sh_def, param_sh_leaves)
    cache_sh = jax.tree_util.tree_unflatten(cache_sh_def, cache_sh_leaves)
    repl = NamedSharding(param_sh_leaves[0].mesh, PartitionSpec())
    prefill, loop = _spec_fns(dec, draft_len, ngram, None,
                              temperature, top_k, top_p)
    prefill_j = jax.jit(prefill,
                        in_shardings=(param_sh, repl),
                        out_shardings=(cache_sh, repl, repl))
    loop_j = jax.jit(loop, donate_argnums=(1, 2),
                     in_shardings=(param_sh, cache_sh, repl, repl,
                                   repl, repl, repl),
                     out_shardings=(repl, repl, repl))
    return prefill_j, loop_j


def generate_speculative(
        model, variables, prompt, max_new_tokens: int, *,
        temperature: float = 0.0, top_k=None, top_p=None, rng=None,
        draft_len: int = 7, ngram: int = 3,
        return_stats: bool = False, param_transform=None,
        strategy=None):
    """Speculative generation: bit-identical to ``generate()`` under
    greedy, distribution-identical under sampling, in (often far) fewer
    decode ticks. See the module docstring.

    Args:
      model: a non-decode :class:`~pddl_tpu.models.gpt.GPT` or
        :class:`~pddl_tpu.models.llama.Llama` (anything
        ``generate()``-compatible with a full-length KV cache).
      variables: ``{"params": ...}`` from training / checkpoint import.
      prompt: int32 ``[B, P]``, ``P >= 1``.
      max_new_tokens: tokens to append (exact — same contract as
        ``generate``).
      temperature / top_k / top_p / rng: the ``generate()`` sampling
        surface. 0 → greedy (bit-exact vs ``generate``); > 0 →
        speculative SAMPLING (rejection scheme, ``_spec_fns`` docstring)
        — every token is an exact draw from the same filtered
        conditional ``sample_logits`` uses, but the draw SEQUENCE
        differs from ``generate``'s (different rng consumption), so
        compare distributions, not token strings.
      draft_len: drafted tokens per tick; the verify block is
        ``draft_len + 1`` wide. 7 keeps the block at 8 (MXU-lane
        friendly) and caps the stale-cache tail at one block.
      ngram: lookup key length. 3 balances precision (fewer spurious
        matches) against recall on byte-level corpora.
      return_stats: also return ``{"ticks", "emitted", "tokens_per_tick"}``
        — the acceptance telemetry a serving stack wants on its dash.
      param_transform: optional module-level function mapping
        ``variables["params"]`` to apply-ready weights inside the jitted
        program (int8 weight-only serving,
        :func:`pddl_tpu.ops.quant.dequantize`) — same hook as
        ``generate()``. Unsharded path only.
      strategy: optional tensor-parallel strategy (mesh already set up),
        same contract as ``generate()``: weights and KV cache shard
        Megatron-style over the ``model`` axis, the verify forward runs
        with the per-block all-reduces on ICI, and the draft/accept/
        rewind machinery operates on the replicated token buffer —
        speculation and TP compose because acceptance depends only on
        logits, which TP reproduces exactly.

    Returns ``[B, P + max_new_tokens]`` int32, or ``(tokens, stats)``
    with ``return_stats=True``.
    """
    b, p = prompt.shape
    total = p + max_new_tokens
    if p < 1:
        raise ValueError("generate_speculative() needs a non-empty prompt")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if temperature <= 0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require temperature > 0 (greedy decoding would "
            "silently ignore them)")
    # Cache writes reach index draft_len past the last emitted position.
    if total + draft_len > model.max_len:
        raise ValueError(
            f"prompt + new tokens + draft_len {total + draft_len} exceed "
            f"max_len {model.max_len} (speculative blocks write "
            f"draft_len={draft_len} positions of lookahead)")
    if getattr(model, "uses_ring_cache", False):
        # Ring cache: block writes reuse slots of positions that rolled
        # out of the window — after a partial rejection those slots are
        # back INSIDE the rewound position's window, and their history
        # is gone. Not recoverable; refuse rather than silently corrupt.
        # (The decision comes from the model — llama.ring_len, the same
        # function that sizes the cache — so this gate cannot drift.)
        raise NotImplementedError(
            "speculative decoding needs a full-length KV cache; "
            f"sliding_window={model.sliding_window} uses a ring cache "
            "whose slots cannot be rewound")

    dec = model.clone(decode=True)
    params = variables["params"]
    sampling = (float(temperature), top_k, top_p)
    if strategy is None:
        prefill, loop = _spec_programs(dec, int(draft_len), int(ngram),
                                       param_transform, *sampling)
    else:
        if param_transform is not None:
            raise NotImplementedError(
                "param_transform (int8 serving) is unsharded-only: the "
                "strategy's sharding trees describe the DENSE params "
                "layout")
        param_sh = strategy.tree_sharding(params)
        params = jax.device_put(params, param_sh)
        cache_sh = strategy.decode_cache_sharding(
            _decode_cache_shapes(dec, b))
        p_leaves, p_def = jax.tree_util.tree_flatten(param_sh)
        c_leaves, c_def = jax.tree_util.tree_flatten(cache_sh)
        prefill, loop = _sharded_spec_programs(
            dec, int(draft_len), int(ngram),
            p_def, tuple(p_leaves), c_def, tuple(c_leaves), *sampling)
    if rng is None:
        rng = jax.random.key(0)  # unused under greedy; loop needs a value
    cache, toks, last_logits = prefill(params, prompt)
    toks, n_out, ticks = loop(params, cache, toks, last_logits,
                              jnp.int32(p), jnp.int32(max_new_tokens), rng)
    out = toks[:, :total]
    if not return_stats:
        return out
    # The final tick may overshoot the budget by up to draft_len tokens
    # that the slice above discards — report only DELIVERED tokens, so
    # tokens_per_tick is the serving-visible rate, not the raw
    # acceptance rate.
    emitted = min(int(n_out), int(max_new_tokens))
    ticks = int(ticks)
    return out, {
        "ticks": ticks,
        "emitted": emitted,
        "tokens_per_tick": emitted / max(ticks, 1),
    }
