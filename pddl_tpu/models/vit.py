"""Vision Transformer family: the framework's attention-bearing model line.

The reference repo is ResNet-only (``tf.keras.applications.ResNet50``,
``/root/reference/imagenet-resnet50.py:56``); the ViT family exists because
the TPU build treats long-context/attention workloads as first-class
(SURVEY.md §5 "Long-context") — it is the model that exercises
:mod:`pddl_tpu.ops.attention` (flash kernel) and
:mod:`pddl_tpu.ops.ring_attention` (sequence parallelism), and it trains
under every distribution strategy exactly like the ResNets (same Trainer,
same data pipeline, same ``{"image", "label"}`` batches).

TPU-first choices:

- token count = (image/patch)² stays MXU-friendly (multiples of 128 for
  standard configs: 224/16 → 196 tokens + padding-free mean-pool head).
- bf16 compute / f32 params, f32 LayerNorm and softmax (numerics).
- ``attention="flash"`` routes through the Pallas kernel on TPU and the
  reference path elsewhere; ``attention="ring"`` shard-maps over the
  ``seq`` mesh axis for sequence-parallel long-context runs
  (``"ring_flash"``: same, with the flash kernel per rotation).
- no data-dependent control flow; everything jits to one XLA program.
"""

from __future__ import annotations

import functools
from typing import Any, Optional


import jax
import jax.numpy as jnp
from flax import linen as nn

from pddl_tpu.models.gpipe import GPipeModel
from pddl_tpu.ops.attention import (
    attention_reference,
    decode_attention,
    flash_attention,
)


class MultiHeadAttention(nn.Module):
    """MHA over our attention ops (``[B, S, E]`` in/out).

    ``decode=True`` enables single-token autoregressive decoding with a KV
    cache (``"cache"`` variable collection): each call consumes one token
    (``S == 1``), appends its K/V at the running index, and attends over
    the cached prefix — the generation path of the GPT family.
    """

    num_heads: int
    attention: str = "flash"  # "flash" | "reference" | "ring" | "ring_flash"
    mesh: Optional[Any] = None  # required for "ring"
    causal: bool = False  # decoder-style masking (the GPT family)
    decode: bool = False  # KV-cache single-token decoding
    max_decode_len: int = 1024
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, e = x.shape
        if e % self.num_heads:
            raise ValueError(f"embed dim {e} not divisible by {self.num_heads} heads")
        head_dim = e // self.num_heads
        dense = functools.partial(
            nn.DenseGeneral, dtype=self.dtype, param_dtype=self.param_dtype,
        )
        # [B, S, H, D] then transpose to the kernel layout [B, H, S, D].
        q = dense(features=(self.num_heads, head_dim), name="query")(x)
        k = dense(features=(self.num_heads, head_dim), name="key")(x)
        v = dense(features=(self.num_heads, head_dim), name="value")(x)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        if self.decode:
            return self._decode_step(q, k, v, b, s, head_dim, dense)

        if self.attention == "flash":
            o = flash_attention(q, k, v, causal=self.causal)
        elif self.attention == "reference":
            o = attention_reference(q, k, v, causal=self.causal)
        elif self.attention in ("ring", "ring_flash"):
            from pddl_tpu.ops.ring_attention import sequence_parallel_attention

            if self.mesh is None:
                raise ValueError(f'attention={self.attention!r} needs the mesh')
            o = sequence_parallel_attention(
                q, k, v, self.mesh, causal=self.causal,
                use_flash=self.attention == "ring_flash")
        else:
            raise ValueError(f"unknown attention {self.attention!r}")

        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        return dense(features=e, name="out")(o)

    def _decode_step(self, q, k, v, b, s, head_dim, dense):
        """Autoregressive decoding with a KV cache.

        Handles both the batched prefill (``s`` prompt tokens in one call,
        causal within the block) and single-token steps (``s == 1``): the
        block's K/V land at the running index, then
        :func:`~pddl_tpu.ops.attention.decode_attention` sweeps the cache
        in its STORAGE dtype with online softmax, traffic and compute
        bounded by the valid prefix — never an f32 copy of the cache nor
        an ``[s, max_decode_len]`` f32 score materialization.

        ``cache_index`` may be a PER-ROW ``[B]`` vector instead of the
        scalar the cache initializes with — the continuous-batching
        serving engine's slot model, where each batch row is an
        independent request at its own depth. Each row's K/V then lands
        at its own position(s) and the masking in ``decode_attention``
        is per row. Multi-token blocks compose with the vector index
        (the speculative verify step: every slot writes ``s`` tokens at
        ``i[b] .. i[b]+s-1``, causal within the block); positions
        beyond ``max_decode_len`` are DROPPED by the scatter — padding
        or rejected-draft junk past the cache edge never lands.
        """
        h = self.num_heads
        # During init() the cache variables don't exist yet: create them
        # but DON'T mutate, so init returns a pristine cache (index 0).
        initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (b, h, self.max_decode_len, head_dim), self.dtype)
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (b, h, self.max_decode_len, head_dim), self.dtype)
        index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32))

        i = index.value
        if initialized and self.has_variable("cache", "block_table"):
            # PAGED serving: the cache leaves are the engine's shared
            # block POOL ``[N, H, block_size, D]`` and the per-slot
            # block table (engine-stamped, like the position counters)
            # resolves every read/write — K/V of a shared prefix exists
            # once regardless of how many slots reference it. Writes
            # land in the slot's private tail block (or the scratch
            # sink for parked slots / padding junk) by the engine's
            # table discipline; reads sweep the table with the same
            # masking as the row path below.
            from pddl_tpu.ops.attention import (  # noqa: PLC0415
                paged_cache_insert,
                paged_decode_attention,
            )

            # Declared (not just read) so the mutated cache keeps the
            # leaf and the donated tree's structure stays stable.
            table = self.variable(
                "cache", "block_table",
                lambda: jnp.zeros((1, 1), jnp.int32)).value
            cached_k.value = paged_cache_insert(
                cached_k.value, k.astype(self.dtype), table, i)
            cached_v.value = paged_cache_insert(
                cached_v.value, v.astype(self.dtype), table, i)
            index.value = i + s
            o = paged_decode_attention(q, cached_k.value, cached_v.value,
                                       table, i)
            o = o.transpose(0, 2, 1, 3).reshape(b, s, h * head_dim)
            return dense(features=h * head_dim, name="out")(o)
        if initialized:
            if i.ndim:
                # Per-row scatter at i[b] + arange(s): single-token
                # decode ticks and multi-token speculative verify blocks
                # share one write (out-of-range positions drop — the
                # scatter's jit OOB rule — so draft lookahead past the
                # cache edge is junk-safe by construction).
                rows = jnp.arange(b)[:, None]          # [B, 1]
                pos = i[:, None] + jnp.arange(s)       # [B, s]
                cached_k.value = cached_k.value.at[rows, :, pos].set(
                    jnp.moveaxis(k, 1, 2).astype(self.dtype))
                cached_v.value = cached_v.value.at[rows, :, pos].set(
                    jnp.moveaxis(v, 1, 2).astype(self.dtype))
            else:
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k.astype(self.dtype), (0, 0, i, 0))
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v.astype(self.dtype), (0, 0, i, 0))
            index.value = i + s

        o = decode_attention(q, cached_k.value, cached_v.value, i,
                             chunk=512 if s == 1 else 128)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, h * head_dim)
        # Same `dense` partial as the training path: one definition of the
        # 'out' projection, so the two can never diverge.
        return dense(features=h * head_dim, name="out")(o)


class TransformerBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attention: str = "flash"
    mesh: Optional[Any] = None
    causal: bool = False
    decode: bool = False  # KV-cache decoding (see MultiHeadAttention)
    max_decode_len: int = 1024
    dropout: float = 0.0
    moe_experts: int = 0  # >0: Switch-MoE FFN instead of the dense MLP
    moe_top_k: int = 1  # experts per token (1=Switch, 2=GShard/Mixtral)
    ln_eps: float = 1e-6  # flax default; HF GPT-2 checkpoints use 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, /):
        # train is positional-ONLY: under nn.remat, static_argnums points
        # at position 2, and a keyword `train=` would silently shift past
        # it — better a loud TypeError at every call site.
        e = x.shape[-1]
        # Pre-LN (f32 for stability even under bf16 compute).
        h = nn.LayerNorm(epsilon=self.ln_eps, dtype=jnp.float32,
                         param_dtype=self.param_dtype, name="ln1")(x)
        h = MultiHeadAttention(
            num_heads=self.num_heads, attention=self.attention,
            mesh=self.mesh, causal=self.causal, decode=self.decode,
            max_decode_len=self.max_decode_len, dtype=self.dtype,
            param_dtype=self.param_dtype, name="attn",
        )(h.astype(self.dtype))
        if self.dropout:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h

        h = nn.LayerNorm(epsilon=self.ln_eps, dtype=jnp.float32,
                         param_dtype=self.param_dtype, name="ln2")(x)
        if self.moe_experts:
            from pddl_tpu.ops.moe import SwitchFFN

            h = SwitchFFN(
                num_experts=self.moe_experts, mlp_ratio=self.mlp_ratio,
                top_k=self.moe_top_k,
                dtype=self.dtype, param_dtype=self.param_dtype, name="moe",
            )(h.astype(self.dtype), train)
        else:
            h = nn.Dense(e * self.mlp_ratio, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="mlp1")(h.astype(self.dtype))
            h = nn.gelu(h)
            h = nn.Dense(e, dtype=self.dtype, param_dtype=self.param_dtype,
                         name="mlp2")(h)
        if self.dropout:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


# Rematerialization policies for the transformer families: trade FLOPs
# for HBM so longer sequences / deeper stacks fit (SURVEY has no analogue;
# this is the jax.checkpoint lever the TPU build exposes).
#   none  — store all activations (fastest, most memory)
#   dots  — save matmul outputs, recompute elementwise (the usual sweet
#           spot: most of the win, little recompute)
#   full  — save only block boundaries, recompute everything inside
REMAT_POLICIES = {
    "none": "none",  # sentinel: no wrapping at all
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "full": None,  # jax.checkpoint default: save nothing inside the block
}


def remat_block(block_cls, remat: str):
    """Wrap a transformer block class per the named remat policy.

    Call wrapped blocks with ``train`` POSITIONAL (``block(x, train)``):
    ``static_argnums`` counts positional args, and flax's lifted remat
    appends keywords after them, so a ``train=`` keyword fails at init
    with jax's static_argnums ValueError (loudly, but cryptically — the
    unwrapped block's positional-only signature gives the clear
    TypeError).
    """
    if remat not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat!r}; known: {sorted(REMAT_POLICIES)}"
        )
    if remat == "none":
        return block_cls
    # train (arg index 2, after self/x) is a Python bool — keep it static.
    # prevent_cse stays at its True default: the blocks run Python-unrolled
    # under jit (not scan), where XLA CSE would otherwise eliminate the
    # recompute and silently restore the saved activations.
    return nn.remat(block_cls, policy=REMAT_POLICIES[remat],
                    static_argnums=(2,))


class ViT(nn.Module):
    """Vision Transformer (patch embed → blocks → mean-pool → head).

    Mean-pool head instead of a CLS token: one fewer ragged token keeps the
    sequence length a clean multiple for flash blocks and seq sharding.
    """

    patch_size: int = 16
    embed_dim: int = 384
    depth: int = 12
    num_heads: int = 6
    num_classes: int = 1000
    mlp_ratio: int = 4
    attention: str = "flash"
    mesh: Optional[Any] = None
    dropout: float = 0.0
    moe_experts: int = 0  # >0: every `moe_every`-th block uses Switch-MoE
    moe_top_k: int = 1
    moe_every: int = 2
    remat: str = "none"  # "none" | "dots" | "full" (REMAT_POLICIES)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        # Stem shared with GPipeViT; share_scope keeps the historical param
        # names (patch_embed/pos_embed) at this module's top level.
        embed = _ViTEmbed(patch_size=self.patch_size,
                          embed_dim=self.embed_dim, dtype=self.dtype,
                          param_dtype=self.param_dtype)
        nn.share_scope(self, embed)
        x = embed(x)

        block_cls = remat_block(TransformerBlock, self.remat)
        for i in range(self.depth):
            # Interleave MoE FFN blocks (every moe_every-th, from the back
            # so depth=1 test models still get one) with dense MLP blocks —
            # the standard Switch/GShard placement.
            moe = (self.moe_experts
                   if (self.depth - 1 - i) % self.moe_every == 0 else 0)
            x = block_cls(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, mesh=self.mesh,
                dropout=self.dropout, moe_experts=moe,
                moe_top_k=self.moe_top_k, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, train)  # positional: remat keeps arg 2 static

        # Head shared with GPipeViT (ln_final/head names preserved).
        head = _ViTHead(num_classes=self.num_classes, dtype=self.dtype,
                        param_dtype=self.param_dtype)
        nn.share_scope(self, head)
        return head(x)


class _ViTEmbed(nn.Module):
    """Patch embed + positional embedding (ViT stem; also the pre-pipeline
    stem of :class:`GPipeViT`). Single source of truth — ``ViT.__call__``
    delegates here via ``nn.share_scope`` so param names are identical."""

    patch_size: int
    embed_dim: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        p = self.patch_size
        if x.shape[1] % p or x.shape[2] % p:
            raise ValueError(f"image {x.shape[1]}x{x.shape[2]} not divisible "
                             f"by patch {p}")
        x = x.astype(self.dtype)
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x)
        b, gh, gw, e = x.shape
        x = x.reshape(b, gh * gw, e)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, gh * gw, e), self.param_dtype)
        return x + pos.astype(self.dtype)


class _ViTStage(nn.Module):
    """One pipeline stage: a run of transformer blocks (identical across
    stages so their params stack on a leading ``[n_stages, ...]`` dim)."""

    num_heads: int
    blocks: int
    mlp_ratio: int = 4
    attention: str = "reference"  # "flash" uses the Pallas kernel per stage
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.blocks):
            x = TransformerBlock(
                num_heads=self.num_heads, mlp_ratio=self.mlp_ratio,
                attention=self.attention, dtype=self.dtype,
                param_dtype=self.param_dtype, name=f"block{i}",
            )(x, False)
        return x


class _ViTHead(nn.Module):
    """Final LN + mean pool + classifier (the post-pipeline head)."""

    num_classes: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype,
                         name="ln_final")(x)
        x = jnp.mean(x, axis=1)
        if self.num_classes:
            x = nn.Dense(self.num_classes, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="head")(x)
        return x.astype(jnp.float32)


class GPipeViT(GPipeModel):
    """Pipeline-parallel ViT: patch embed (replicated) → ``n_stages``
    stacked transformer stages through the GPipe schedule → head
    (replicated). See :class:`pddl_tpu.models.gpipe.GPipeModel`."""

    def __init__(self, *, n_stages: int, blocks_per_stage: int,
                 n_microbatches: int, mesh,
                 patch_size: int = 16, embed_dim: int = 384,
                 num_heads: int = 6, num_classes: int = 1000,
                 mlp_ratio: int = 4, attention: str = "reference",
                 dtype: Any = jnp.float32, param_dtype: Any = jnp.float32):
        super().__init__(
            embed=_ViTEmbed(patch_size=patch_size, embed_dim=embed_dim,
                            dtype=dtype, param_dtype=param_dtype),
            stage=_ViTStage(num_heads=num_heads, blocks=blocks_per_stage,
                            mlp_ratio=mlp_ratio, attention=attention,
                            dtype=dtype, param_dtype=param_dtype),
            head=_ViTHead(num_classes=num_classes, dtype=dtype,
                          param_dtype=param_dtype),
            n_stages=n_stages, n_microbatches=n_microbatches, mesh=mesh,
        )


ViT_S16 = functools.partial(ViT, patch_size=16, embed_dim=384, depth=12,
                            num_heads=6)
ViT_B16 = functools.partial(ViT, patch_size=16, embed_dim=768, depth=12,
                            num_heads=12)
ViT_L16 = functools.partial(ViT, patch_size=16, embed_dim=1024, depth=24,
                            num_heads=16)


def tiny_vit(num_classes: int = 10, **kwargs) -> ViT:
    """Miniature ViT for tests/dry-runs (8x8 patches on 32px inputs)."""
    kwargs.setdefault("patch_size", 8)
    kwargs.setdefault("embed_dim", 32)
    kwargs.setdefault("depth", 2)
    kwargs.setdefault("num_heads", 4)
    kwargs.setdefault("attention", "reference")
    return ViT(num_classes=num_classes, **kwargs)
