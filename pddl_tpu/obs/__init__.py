"""Unified observability for the serving (and training) stack.

`trace.py` — Dapper-style per-request spans (queue → admission →
prefix match → prefill chunks → decode ticks → retries/replays →
finish), zero-cost when disabled via the no-op :class:`NullTracer`.
`ring.py` — the engine's fixed-capacity per-tick telemetry ring.
`export.py` — dependency-free exporters: atomic-append JSONL event
log, Prometheus text exposition over ``ServeMetrics`` + engine +
StepTimer + device-memory gauges, and an optional stdlib ``/metrics``
HTTP endpoint. See docs/OPERATIONS.md § "Observability (serving)".

Fleet-wide distributed tracing (ISSUE 19): `propagate.py` — wire
trace contexts, the worker span shipper, and the router-side
:class:`TraceCollector`; `assemble.py` — stitching, gap checking, and
TTFT critical-path attribution (CLI: ``python -m
pddl_tpu.obs.assemble``); `flightrec.py` — the SIGKILL-surviving
per-worker flight recorder (imported directly, not re-exported here:
it depends on the fleet journal's VFS shim and `obs` must stay
importable without the serving stack).
"""

from pddl_tpu.obs.export import (
    FLEET_COUNTER_KEYS,
    SERVE_COUNTER_KEYS,
    TOKEN_LATENCY_BUCKETS_S,
    TRAIN_COUNTER_KEYS,
    TTFT_BUCKETS_S,
    JsonlEventLog,
    MetricsHTTPServer,
    device_memory_gauges,
    engine_gauges,
    fleet_exposition,
    parse_prometheus_text,
    read_jsonl,
    render_prometheus,
    reservoir_histogram,
    serve_exposition,
    train_exposition,
)
from pddl_tpu.obs.assemble import TRACE_EVENTS, TRACE_SEGMENTS, Trace, stitch
from pddl_tpu.obs.propagate import (
    ClockAligner,
    SpanShipper,
    TraceCollector,
    estimate_offset,
)
from pddl_tpu.obs.ring import TelemetryRing
from pddl_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    RequestTracer,
    Span,
)

__all__ = [
    "ClockAligner",
    "FLEET_COUNTER_KEYS",
    "JsonlEventLog",
    "MetricsHTTPServer",
    "NULL_TRACER",
    "NullTracer",
    "RequestTracer",
    "SERVE_COUNTER_KEYS",
    "Span",
    "SpanShipper",
    "TRACE_EVENTS",
    "TRACE_SEGMENTS",
    "Trace",
    "TraceCollector",
    "estimate_offset",
    "stitch",
    "TelemetryRing",
    "device_memory_gauges",
    "engine_gauges",
    "fleet_exposition",
    "parse_prometheus_text",
    "read_jsonl",
    "render_prometheus",
    "reservoir_histogram",
    "serve_exposition",
    "TOKEN_LATENCY_BUCKETS_S",
    "TTFT_BUCKETS_S",
    "train_exposition",
    "TRAIN_COUNTER_KEYS",
]
