"""Unified observability for the serving (and training) stack.

`trace.py` — Dapper-style per-request spans (queue → admission →
prefix match → prefill chunks → decode ticks → retries/replays →
finish), zero-cost when disabled via the no-op :class:`NullTracer`.
`ring.py` — the engine's fixed-capacity per-tick telemetry ring.
`export.py` — dependency-free exporters: atomic-append JSONL event
log, Prometheus text exposition over ``ServeMetrics`` + engine +
StepTimer + device-memory gauges, and an optional stdlib ``/metrics``
HTTP endpoint. See docs/OPERATIONS.md § "Observability (serving)".
"""

from pddl_tpu.obs.export import (
    FLEET_COUNTER_KEYS,
    SERVE_COUNTER_KEYS,
    TRAIN_COUNTER_KEYS,
    JsonlEventLog,
    MetricsHTTPServer,
    device_memory_gauges,
    engine_gauges,
    fleet_exposition,
    parse_prometheus_text,
    read_jsonl,
    render_prometheus,
    serve_exposition,
    train_exposition,
)
from pddl_tpu.obs.ring import TelemetryRing
from pddl_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    RequestTracer,
    Span,
)

__all__ = [
    "FLEET_COUNTER_KEYS",
    "JsonlEventLog",
    "MetricsHTTPServer",
    "NULL_TRACER",
    "NullTracer",
    "RequestTracer",
    "SERVE_COUNTER_KEYS",
    "Span",
    "TelemetryRing",
    "device_memory_gauges",
    "engine_gauges",
    "fleet_exposition",
    "parse_prometheus_text",
    "read_jsonl",
    "render_prometheus",
    "serve_exposition",
    "train_exposition",
    "TRAIN_COUNTER_KEYS",
]
