"""Trace assembler: stitch fleet trace records, find gaps, attribute
TTFT (ISSUE 19).

Input is the flat record stream a
:class:`pddl_tpu.obs.propagate.TraceCollector` accumulates (or its
JSONL dump): one ``kind="fleet_span"`` record per stream on the
router's clock, plus every replica's ``kind="span"`` records on their
own monotonic clocks. :func:`stitch` groups them by trace id —
hand-off rebinds and hedge copies already share one id thanks to the
collector's alias discipline — and each :class:`Trace` can then

- judge itself **gap-free** (:meth:`Trace.gaps`): router record
  terminal, at least one replica span for every finished stream,
  token coverage matching the acked token count, and both sides of
  every hand-off present;
- attribute its TTFT to **segments** (:meth:`Trace.critical_path`):
  queue wait, admission, prefix match, host-tier promotion, prefill
  compute, hand-off export/import, and the residual first tick. All
  segment arithmetic is same-clock-domain (walls measured inside one
  process); the per-replica clock offsets are only used to place
  spans on the router's axis for display.

:func:`aggregate` folds many traces into fleet-level percentiles per
segment — the "where does TTFT go" table the CLI
(``python -m pddl_tpu.obs.assemble records.jsonl``) prints.

``TRACE_EVENTS`` below is the authoritative event-name vocabulary:
graftlint's ``trace-vocab`` rule checks every literal the tracer and
the propagation layer emit against it, both directions.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional, Sequence

# The recognized trace-event vocabulary. Engine-side span events come
# from obs/trace.py, chain-wire transfer spans and router fleet_span
# events from obs/propagate.py. graftlint (trace-vocab) enforces that
# emitters use only these names and that none of them is stale.
TRACE_EVENTS = (
    # engine-side span events (obs/trace.py)
    "queued",
    "admitted",
    "prefix_match",
    "prefill_chunk",
    "first_token",
    "decode",
    "deadline_shed",
    "preempted",
    "replay",
    "restored",
    # chain-wire transfer spans (obs/propagate.py)
    "chain_export",
    "chain_import",
    # router-side fleet_span events (obs/propagate.py)
    "submit",
    "route",
    "hedge",
    "restore",
    "handoff",
    "handoff_export",
    "handoff_import",
    "finish",
)

# TTFT critical-path segments, in pipeline order. Values are seconds;
# they sum to the stream's TTFT (first_tick absorbs the residual).
TRACE_SEGMENTS = (
    "queue_wait",
    "admission",
    "prefix_match",
    "host_promote",
    "prefill",
    "handoff_export",
    "handoff_import",
    "first_tick",
)


def _named(events: Sequence[Dict[str, object]],
           name: str) -> List[Dict[str, object]]:
    return [e for e in events if e.get("name") == name]


def _pct(values: List[float], q: float) -> float:
    """Nearest-rank percentile (matches serve/metrics.py)."""
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


class Trace:
    """One stream's stitched records: the router's fleet_span plus
    every replica/chain span sharing its trace id."""

    __slots__ = ("trace_id", "router", "spans")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.router: Optional[Dict[str, object]] = None
        self.spans: List[Dict[str, object]] = []

    # ------------------------------------------------------- accessors
    def replica_spans(self) -> List[Dict[str, object]]:
        """Engine request spans only (chain transfer spans excluded)."""
        return [s for s in self.spans
                if s.get("name") not in ("chain_export", "chain_import")]

    def chain_spans(self) -> List[Dict[str, object]]:
        return [s for s in self.spans
                if s.get("name") in ("chain_export", "chain_import")]

    def replicas(self) -> List[int]:
        seen: List[int] = []
        for s in self.spans:
            r = s.get("replica")
            if r is not None and r not in seen:
                seen.append(r)  # insertion order = arrival order
        return seen

    # ------------------------------------------------------------ gaps
    def gaps(self) -> List[str]:
        """Why this trace is NOT gap-free (empty list == clean).

        A finished stream must have the router's terminal record, at
        least one replica span, token coverage >= the acked token
        count (max across spans — a restored handle carries the full
        token list, so the final span covers replays and hand-offs),
        and, when the router recorded a hand-off, spans from both the
        prefill and the decode replica.
        """
        out: List[str] = []
        if self.router is None:
            return ["no_router_record"]
        state = self.router.get("state")
        if state is None:
            return ["router_not_terminal"]
        if state != "finished":
            # Failed/cancelled/shed streams end wherever they ended;
            # only token-bearing completions owe full coverage.
            return out
        spans = self.replica_spans()
        if not spans:
            out.append("no_replica_span")
        else:
            acked = int(self.router.get("n_tokens") or 0)
            cover = max(
                int((s.get("attrs") or {}).get("tokens_emitted") or 0)
                for s in spans)
            if cover < acked:
                out.append(f"token_coverage:{cover}/{acked}")
        events = self.router.get("events") or []
        for h in _named(events, "handoff"):
            src = h.get("from_replica")
            dst = h.get("to_replica")
            have = {s.get("replica") for s in spans}
            if src not in have:
                out.append(f"no_prefill_span:replica{src}")
            if dst not in have:
                out.append(f"no_decode_span:replica{dst}")
            if h.get("blocks"):
                names = {s.get("name") for s in self.chain_spans()}
                if "chain_export" not in names:
                    out.append("no_chain_export_span")
                if "chain_import" not in names:
                    out.append("no_chain_import_span")
        return out

    # --------------------------------------------------- critical path
    def critical_path(self) -> Optional[Dict[str, float]]:
        """Attribute this stream's TTFT to ``TRACE_SEGMENTS``.

        Anchored on the replica span that contains the ``first_token``
        event — its own events carry queue wait, admission time,
        per-chunk prefill walls (site-tagged: ``gather`` is prefix-
        cache reuse, ``host_promote`` the host-tier climb) all on ONE
        clock. Hand-off export/import walls count only when the router
        saw the hand-off before first token (a mid-prefill migration);
        the usual post-first-token hand-off is not TTFT. ``first_tick``
        is the residual, clamped at zero.
        """
        ft_span = None
        ft_ev = None
        for s in self.replica_spans():
            hits = _named(s.get("events") or [], "first_token")
            if hits:
                ft_span, ft_ev = s, hits[0]
                break
        if ft_span is None or ft_ev is None:
            return None
        evs = ft_span.get("events") or []
        ft_t = float(ft_ev.get("t_s") or 0.0)
        ttft = ft_ev.get("ttft_s")
        if ttft is None and self.router is not None:
            ttft = self.router.get("ttft_s")
        if ttft is None:
            ttft = ft_t - float(ft_span.get("start_s") or ft_t)
        ttft = float(ttft)

        seg = {name: 0.0 for name in TRACE_SEGMENTS}
        admits = [e for e in _named(evs, "admitted")
                  if float(e.get("t_s") or 0.0) <= ft_t]
        admit_t = None
        if admits:
            admit = admits[-1]  # last admission before first token
            admit_t = float(admit.get("t_s") or 0.0)
            seg["queue_wait"] = max(
                0.0, float(admit.get("queue_wait_s") or 0.0))
        first_chunk_t = None
        for e in _named(evs, "prefill_chunk"):
            t = float(e.get("t_s") or 0.0)
            if t > ft_t:
                continue
            wall = max(0.0, float(e.get("wall_s") or 0.0))
            site = e.get("site")
            if site == "gather":
                seg["prefix_match"] += wall
            elif site == "host_promote":
                seg["host_promote"] += wall
            else:
                seg["prefill"] += wall
            if first_chunk_t is None or t - wall < first_chunk_t:
                first_chunk_t = t - wall
        if admit_t is not None and first_chunk_t is not None:
            seg["admission"] = max(0.0, first_chunk_t - admit_t)
        if self.router is not None:
            revents = self.router.get("events") or []
            ft_router = _named(revents, "first_token")
            ft_router_t = (float(ft_router[0].get("t_s") or 0.0)
                           if ft_router else None)
            for name in ("handoff_export", "handoff_import"):
                for e in _named(revents, name):
                    if (ft_router_t is not None
                            and float(e.get("t_s") or 0.0) > ft_router_t):
                        continue
                    seg[name] += max(0.0, float(e.get("wall_s") or 0.0))
        spent = sum(seg.values())
        seg["first_tick"] = max(0.0, ttft - spent)
        seg["ttft_s"] = ttft
        return seg


def stitch(records: Iterable[Dict[str, object]], *,
           apply_offsets: bool = False) -> Dict[str, Trace]:
    """Group a flat record stream into traces by trace id.

    With ``apply_offsets=True``, replica span timestamps (``start_s``,
    ``end_s``, event ``t_s``) are shifted into the router's clock
    domain using each record's ``clock_offset_s`` tag — wanted for
    cross-process timeline display, unnecessary for gap checks and
    segment math (those stay within one clock).
    """
    traces: Dict[str, Trace] = {}
    for rec in records:
        tid = rec.get("trace_id")
        if not tid:
            continue
        trace = traces.setdefault(str(tid), Trace(str(tid)))
        kind = rec.get("kind")
        if kind == "fleet_span":
            # Prefer the record that reached a terminal state (a
            # recovered router may contribute a second, live one).
            if (trace.router is None
                    or trace.router.get("state") is None):
                trace.router = rec
        elif kind == "span":
            if apply_offsets and rec.get("clock_offset_s") is not None:
                rec = _shift(rec, -float(rec["clock_offset_s"]))
            trace.spans.append(rec)
    return traces


def _shift(rec: Dict[str, object], delta: float) -> Dict[str, object]:
    out = dict(rec)
    for key in ("start_s", "end_s"):
        if out.get(key) is not None:
            out[key] = float(out[key]) + delta
    evs = []
    for e in out.get("events") or []:
        e = dict(e)
        if e.get("t_s") is not None:
            e["t_s"] = float(e["t_s"]) + delta
        evs.append(e)
    out["events"] = evs
    return out


def aggregate(traces: Iterable[Trace]) -> Dict[str, object]:
    """Fleet-level TTFT attribution: per-segment mean/p50/p95/p99
    seconds over every trace with a resolvable critical path, plus
    trace counts and gap totals."""
    paths: List[Dict[str, float]] = []
    n_traces = 0
    gappy = 0
    for t in traces:
        n_traces += 1
        if t.gaps():
            gappy += 1
        cp = t.critical_path()
        if cp is not None:
            paths.append(cp)
    segments: Dict[str, Dict[str, float]] = {}
    for name in TRACE_SEGMENTS + ("ttft_s",):
        vals = [p[name] for p in paths if name in p]
        if not vals:
            continue
        segments[name] = {
            "mean_s": sum(vals) / len(vals),
            "p50_s": _pct(vals, 0.50),
            "p95_s": _pct(vals, 0.95),
            "p99_s": _pct(vals, 0.99),
        }
    return {
        "traces": n_traces,
        "attributed": len(paths),
        "gappy": gappy,
        "segments": segments,
    }


def read_jsonl(path: str) -> List[Dict[str, object]]:
    records: List[Dict[str, object]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def render_report(traces: Dict[str, Trace]) -> str:
    """The human-facing attribution report: one line per trace (state,
    tokens, TTFT, gap verdict), then the fleet segment table."""
    lines: List[str] = []
    agg = aggregate(traces.values())
    lines.append(f"traces={agg['traces']} attributed={agg['attributed']}"
                 f" gappy={agg['gappy']}")
    lines.append("")
    lines.append(f"{'trace_id':>18} {'state':>10} {'tokens':>7}"
                 f" {'ttft_ms':>9} {'replicas':>9} gaps")
    for tid in sorted(traces):
        t = traces[tid]
        state = "?" if t.router is None else (
            t.router.get("state") or "live")
        toks = 0 if t.router is None else int(
            t.router.get("n_tokens") or 0)
        cp = t.critical_path()
        ttft = "-" if cp is None else f"{cp['ttft_s'] * 1e3:.2f}"
        reps = ",".join(str(r) for r in t.replicas()) or "-"
        gaps = ";".join(t.gaps()) or "ok"
        lines.append(f"{tid:>18} {state:>10} {toks:>7}"
                     f" {ttft:>9} {reps:>9} {gaps}")
    lines.append("")
    lines.append(f"{'segment':>16} {'mean_ms':>9} {'p50_ms':>9}"
                 f" {'p95_ms':>9} {'p99_ms':>9}")
    for name in TRACE_SEGMENTS + ("ttft_s",):
        stats = agg["segments"].get(name)  # type: ignore[union-attr]
        if stats is None:
            continue
        lines.append(
            f"{name:>16} {stats['mean_s'] * 1e3:>9.3f}"
            f" {stats['p50_s'] * 1e3:>9.3f}"
            f" {stats['p95_s'] * 1e3:>9.3f}"
            f" {stats['p99_s'] * 1e3:>9.3f}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pddl_tpu.obs.assemble",
        description="Stitch fleet trace records and attribute TTFT.")
    parser.add_argument("records", help="JSONL trace-record dump "
                        "(TraceCollector.dump output)")
    parser.add_argument("--json", action="store_true",
                        help="emit the aggregate as JSON instead of "
                        "the report table")
    args = parser.parse_args(argv)
    traces = stitch(read_jsonl(args.records))
    if args.json:
        print(json.dumps(aggregate(traces.values()), indent=2,
                         sort_keys=True))
    else:
        print(render_report(traces))
    return 0


if __name__ == "__main__":
    sys.exit(main())
