"""Dependency-free exporters for the observability layer.

Two consumers, two formats, zero new dependencies:

- **JSONL event log** (:class:`JsonlEventLog`): one schema-versioned
  JSON object per line — span records from `obs/trace.py`, tick
  records from the engine's telemetry ring, whatever a bench wants to
  append. Writes are single ``os.write`` calls on an ``O_APPEND``
  descriptor, so concurrent writers interleave at LINE granularity
  (the same torn-write discipline `serve/drain.py` applies to its
  snapshot) and ``tail -f`` always sees whole records.
- **Prometheus text exposition** (:func:`render_prometheus` and the
  :func:`serve_exposition` convenience): the v0.0.4 text format over
  ``ServeMetrics.snapshot()`` plus engine gauges (`engine_gauges`:
  ``prefix_pool_nbytes``, ``live_slots``, ``degraded``, per-site
  ``compile_counts``), the training `StepTimer` snapshot, and
  device-memory stats (`device_memory_gauges`) — training and serving
  share one export path. The renderer enumerates EVERY key of the
  snapshot it is handed (unknown keys render as gauges), which is what
  makes the snapshot-drift guard in `tests/test_obs.py` structural: a
  new counter cannot silently skip export.

:func:`parse_prometheus_text` is the strict round-trip parser the
tests pin the renderer against (and a convenience for scrape tooling);
:class:`MetricsHTTPServer` serves ``collect()`` at ``/metrics`` from a
stdlib ``http.server`` daemon thread for anything that scrapes.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

SCHEMA_VERSION = 1

# ---------------------------------------------------------------- JSONL


class JsonlEventLog:
    """Atomic-append JSONL writer: one record, one line, one write.

    Each record gains ``schema`` (the event-log schema version) unless
    it already carries one. The descriptor is opened ``O_APPEND`` and
    every line lands in a single ``os.write``, so a reader (or a
    second writer) never sees a torn line.
    """

    def __init__(self, path: str):
        self.path = path
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._fd: Optional[int] = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self.records_written = 0

    def write(self, record: Mapping[str, object]) -> None:
        if self._fd is None:
            raise ValueError(f"event log {self.path!r} is closed")
        rec = dict(record)
        rec.setdefault("schema", SCHEMA_VERSION)
        line = json.dumps(rec, separators=(",", ":"),
                          allow_nan=False, default=_json_default)
        data = (line + "\n").encode("utf-8")
        # os.write may land a partial write (ENOSPC, signals); finish
        # the line before counting the record as written.
        while data:
            n = os.write(self._fd, data)
            data = data[n:]
        self.records_written += 1

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "JsonlEventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_default(obj):
    """Tolerate numpy scalars riding in telemetry records."""
    item = getattr(obj, "item", None)
    if callable(item):
        return item()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


def read_jsonl(path: str):
    """Parse every line of an event log (tooling/test convenience)."""
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ----------------------------------------------------------- Prometheus

# ServeMetrics.snapshot() keys that are monotonic counters (everything
# else renders as a gauge). Keep in sync with
# `pddl_tpu/serve/metrics.py` — the drift guard asserts every snapshot
# key is exported either way, so a missing entry here degrades a
# counter to a gauge, never drops it.
SERVE_COUNTER_KEYS = frozenset({
    "requests_finished", "requests_rejected", "requests_timed_out",
    "requests_cancelled", "requests_failed", "requests_deadline_shed",
    "tokens_emitted", "prefix_lookups", "prefix_hits",
    "prefill_tokens_saved", "prefix_evictions", "retries", "replays",
    "preemptions", "degraded_entries", "degraded_time_s",
    "copy_bytes_avoided",
    # Multi-tenant counters (`serve/tenant/`): adapter pool traffic and
    # constrained-decoding volume. (adapter_hit_rate / the residency
    # gauge / requests_by_adapter stay gauges.)
    "adapter_hits", "adapter_loads", "adapter_evictions",
    "constrained_requests", "requests_grammar_complete",
    # Speculative serving (engine ``spec_k > 0``): verify windows and
    # the drafted/accepted token volume behind the acceptance-rate
    # gauge (the rate itself stays a gauge).
    "spec_ticks", "spec_drafted_tokens", "spec_accepted_tokens",
    # Tiered KV cache (`serve/kvcache/hosttier.py`): demotion/promotion
    # traffic and the promotion budget charge (the residency gauge
    # host_tier_bytes_resident stays a gauge).
    "host_tier_spills", "host_tier_hits", "host_tier_promotions",
    "host_tier_promote_tokens_charged",
})

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _fmt_value(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if isinstance(v, float) else str(int(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


# Latency histogram bucket edges (seconds): the conventional
# Prometheus latency ladder, clipped to the ranges the serving SLOs
# actually alarm on. TTFT spans queue wait + prefill (up to seconds
# under load); per-token decode latency is an order of magnitude
# tighter.
TTFT_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0, 10.0)
TOKEN_LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 1.0)


def reservoir_histogram(reservoir,
                        buckets: Sequence[float]) -> Dict[str, object]:
    """A :class:`~pddl_tpu.serve.metrics.Reservoir` (or any iterable
    of floats) folded into the renderer's histogram spec: CUMULATIVE
    per-``le`` counts in ascending edge order plus the implicit
    ``+Inf`` bucket, with ``sum``/``count`` over the same samples —
    so ``le="+Inf"`` always equals ``count``, the consistency the
    round-trip test pins."""
    edges = sorted(float(b) for b in buckets)
    samples = sorted(float(v) for v in reservoir)
    cum: Dict[str, int] = {}
    i = 0
    for edge in edges:
        while i < len(samples) and samples[i] <= edge:
            i += 1
        cum[format(edge, "g")] = i
    cum["+Inf"] = len(samples)
    return {"buckets": cum, "sum": float(sum(samples)),
            "count": len(samples)}


def render_prometheus(snapshot: Mapping[str, object], *,
                      prefix: str = "pddl",
                      counters: frozenset = frozenset(),
                      help_text: Optional[Mapping[str, str]] = None,
                      histograms: Optional[Mapping[str, Mapping]] = None,
                      ) -> str:
    """Render a flat snapshot dict as Prometheus text exposition.

    EVERY key renders: scalars become ``{prefix}_{key}`` (counters per
    ``counters`` get the conventional ``_total`` suffix), ``None``
    renders as ``NaN`` (present-but-unobserved beats absent — a scrape
    can tell "no samples yet" from "metric vanished"), booleans as
    0/1, and Mapping values become one labeled series
    ``{prefix}_{key}{{key="..."}}`` per entry (``compile_counts``,
    per-device memory). Keys must already be exposition-legal
    (``[a-zA-Z0-9_]``) — snapshots in this repo are.

    ``histograms`` maps extra metric names to
    :func:`reservoir_histogram` specs, rendered as conventional
    cumulative histograms (``{name}_bucket{{le="..."}}`` ascending,
    ``le="+Inf"`` == ``{name}_count``, plus ``_sum``/``_count``) —
    the shape every Prometheus quantile/burn-rate recipe expects.
    """
    lines = []
    for key in snapshot:
        value = snapshot[key]
        name = f"{prefix}_{key}"
        if not _NAME_RE.match(name):
            raise ValueError(f"metric name {name!r} is not "
                             "exposition-legal")
        is_counter = key in counters
        if is_counter and not name.endswith("_total"):
            name += "_total"
        if help_text and key in help_text:
            lines.append(f"# HELP {name} {help_text[key]}")
        if isinstance(value, Mapping):
            lines.append(f"# TYPE {name} gauge")
            if not value:
                # An OPEN label set with no members yet (e.g.
                # requests_by_adapter before any tenant traffic) still
                # exports its metric name — one NaN sample under an
                # empty label, the same present-but-unobserved
                # philosophy as None -> NaN — so the snapshot-drift
                # guard (and a scrape differ) can tell "no labels yet"
                # from "metric vanished".
                lines.append(f'{name}{{key=""}} NaN')
            for label_val in sorted(value):
                lines.append(
                    f'{name}{{key="{_escape_label(str(label_val))}"}} '
                    f"{_fmt_value(value[label_val])}")
        else:
            lines.append(f"# TYPE {name} "
                         f"{'counter' if is_counter else 'gauge'}")
            lines.append(f"{name} {_fmt_value(value)}")
    for key in (histograms or {}):
        spec = histograms[key]
        name = f"{prefix}_{key}"
        if not _NAME_RE.match(name):
            raise ValueError(f"metric name {name!r} is not "
                             "exposition-legal")
        lines.append(f"# TYPE {name} histogram")
        buckets = spec["buckets"]
        for le in buckets:
            lines.append(f'{name}_bucket{{le="{le}"}} '
                         f"{int(buckets[le])}")
        lines.append(f"{name}_sum {_fmt_value(float(spec['sum']))}")
        lines.append(f"{name}_count {int(spec['count'])}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{([^{}]*)\})?"                     # optional label set
    r" (NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")


def parse_prometheus_text(text: str) -> Tuple[
        Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float],
        Dict[str, str]]:
    """STRICT parse of the text exposition format.

    Returns ``(samples, types)``: ``samples`` maps
    ``(name, sorted-label-pairs)`` to the float value, ``types`` maps
    metric name to its declared ``# TYPE``. Any line that is neither a
    well-formed sample, a ``# TYPE``/``# HELP`` comment, nor blank
    raises ``ValueError`` — this is the round-trip referee for
    :func:`render_prometheus`, so leniency here would hide renderer
    bugs.
    """
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                if m.group(1) in types:
                    raise ValueError(
                        f"line {lineno}: duplicate # TYPE for "
                        f"{m.group(1)!r}")
                types[m.group(1)] = m.group(2)
                continue
            if line.startswith("# HELP "):
                continue
            raise ValueError(f"line {lineno}: malformed comment "
                             f"{line!r}")
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, labels_raw, value = m.group(1), m.group(2), m.group(3)
        labels: Tuple[Tuple[str, str], ...] = ()
        if labels_raw:
            parsed = _LABEL_RE.findall(labels_raw)
            # Re-render to catch trailing junk the findall skipped.
            rebuilt = ",".join(f'{k}="{v}"' for k, v in parsed)
            if rebuilt != labels_raw.rstrip(","):
                raise ValueError(
                    f"line {lineno}: malformed labels {labels_raw!r}")
            labels = tuple(sorted(parsed))
        key = (name, labels)
        if key in samples:
            raise ValueError(f"line {lineno}: duplicate sample {key}")
        samples[key] = float(value)
    return samples, types


# ------------------------------------------------------- gauge sources


def engine_gauges(engine) -> Dict[str, object]:
    """The live-engine gauges the exposition carries beyond
    ``ServeMetrics``: slot occupancy, queue depth, the degraded flag,
    the sheddable prefix-pool HBM, drain state, and the per-site
    compiled-executable counts (any value above 1 in a scrape is a
    recompile — the zero-recompile contract as a dashboard line)."""
    return {
        "live_slots": engine.live_slots,
        "max_slots": engine.max_slots,
        "queue_depth": engine.scheduler.depth,
        "degraded": engine.degraded,
        "drained": engine.drained,
        "prefix_pool_nbytes": engine.prefix_pool_nbytes,
        # Paged-attention gauges (0 / 0.0 on a copy-mode engine): live
        # cross-slot sharing and table occupancy, the dashboard's view
        # of the in-place prefix sharing (`ServeEngine.paged`).
        "paged": getattr(engine, "paged", False),
        "blocks_shared": getattr(engine, "blocks_shared", 0),
        "block_table_fill": getattr(engine, "block_table_fill", 0.0),
        # Speculative-serving gauges (0 on a classic engine): the
        # compiled draft width and whether a draft model (second paged
        # cache tree) is doing the drafting.
        "spec_k": getattr(engine, "spec_k", 0),
        "spec_draft_model": getattr(engine, "spec_draft_model_enabled",
                                    False),
        # Tiered-KV-cache gauges (False/0 without a host tier): whether
        # the spill tier is armed and its live host-side residency —
        # the "Host tier sizing" runbook's watchlist lines.
        "host_tier": getattr(engine, "host_tier_enabled", False),
        "host_tier_bytes_resident": getattr(
            engine, "host_tier_bytes_resident", 0),
        "host_tier_blocks_resident": getattr(
            engine, "host_tier_blocks_resident", 0),
        # Multi-tenant gauges (False/0 on a plain engine): whether the
        # tenant path is compiled in, and how many adapters are
        # device-resident right now (`serve/tenant/`).
        "tenant": getattr(engine, "tenant_enabled", False),
        "adapter_pool_resident": getattr(engine, "adapter_pool_resident",
                                         0),
        "compile_counts": engine.compile_counts(),
    }


def device_memory_gauges() -> Dict[str, object]:
    """`utils/profiling.device_memory_stats` reshaped for the renderer:
    one labeled series per stat, one label per device."""
    from pddl_tpu.utils.profiling import device_memory_stats

    stats = device_memory_stats()
    out: Dict[str, Dict[str, int]] = {
        "bytes_in_use": {}, "peak_bytes_in_use": {}, "bytes_limit": {}}
    for dev, fields in stats.items():
        for k in out:
            out[k][dev] = fields[k]
    return out


TRAIN_COUNTER_KEYS = frozenset({
    # Trainer.fault_snapshot() keys that are monotonic counters; the
    # rest render as gauges. The drift guard in tests/test_train_faults
    # asserts every snapshot key exports either way.
    "retries", "recoveries", "replayed_steps", "checkpoints_saved",
    "checkpoint_wall_s",
})


def train_exposition(trainer, *, step_timer=None,
                     device_memory: bool = False) -> str:
    """The training scrape body: the Trainer's fault/recovery snapshot
    (retries, in-process recoveries, replayed steps, checkpoint count
    and wall time, per-kind injections, per-site dispatch wall,
    compile counts — any ``compile_counts`` value above 1 on a scrape
    is a recompile, the zero-recompile contract as a dashboard line),
    optionally the `StepTimer` percentiles and per-device memory —
    the SAME renderer and text format the serving engine exports
    through, so one Prometheus config scrapes both."""
    parts = [render_prometheus(trainer.fault_snapshot(),
                               prefix="pddl_train",
                               counters=TRAIN_COUNTER_KEYS)]
    if step_timer is not None:
        parts.append(render_prometheus(
            step_timer.snapshot(), prefix="pddl_train_step",
            counters=frozenset({"steps_timed"})))
    if device_memory:
        parts.append(render_prometheus(device_memory_gauges(),
                                       prefix="pddl_device_memory"))
    return "".join(parts)


# The canonical fleet-counter vocabulary: FleetMetrics.snapshot()
# derives its keys from this set (and render's counter typing reads
# it), so there is exactly one list to extend per new counter.
FLEET_COUNTER_KEYS = frozenset({
    "replica_up_events", "replica_down_events", "migrations",
    "requests_migrated", "migrated_via_drain", "migrated_via_replay",
    "requests_routed", "routed_sticky", "routed_affinity", "routed_hash",
    "routed_load_balanced", "routed_adapter",
    "shed_rerouted", "shed_rejected", "requests_finished",
    "requests_failed", "requests_orphaned", "heartbeat_failures",
    "probes", "probe_failures", "tokens_streamed",
    # Admission control / brownout (`serve/fleet/admission.py`): the
    # front-door rejections and ladder movement. Per-class splits
    # flatten to admission_rejected_<class>, typed counters below like
    # the circuit_* transitions.
    "admission_rate_limited", "brownout_shed_best_effort",
    "brownout_rejected_cold", "brownout_capped_output",
    "brownout_escalations", "brownout_deescalations",
    # Elastic scaling mechanism counters (`serve/fleet/autoscaler.py`
    # is the policy; the router executes): replicas added/retired at
    # runtime, and the requests scale-downs live-migrated. Per-class
    # delivery splits flatten to tokens_streamed_<class>, typed
    # counters below like the circuit_* transitions.
    "scale_up_events", "scale_down_events", "scale_down_migrated",
    # Tiered KV cache at fleet level (ISSUE 13): prefix-affinity routes
    # taken because a replica held the chain in HOST RAM (no replica
    # had it in HBM), and replica-to-replica chain pulls — the
    # duplicate-prefill eliminator — with the tokens they moved.
    "routed_host_tier", "chain_pulls", "chain_pull_tokens",
    # Control-plane durability & gray failure (ISSUE 14): interactive
    # hedges launched off suspected-gray replicas / won by the hedge
    # copy / duplicate copies cancelled, suspects proactively retired
    # through the scale_down migration path, and the framed
    # transport's resend rounds + CRC/length rejects aggregated from
    # every process replica's wire stats.
    "hedges_launched", "hedge_wins", "hedge_cancelled", "gray_drains",
    "wire_retries", "wire_crc_rejects",
    # Disaggregated prefill/decode serving (ISSUE 17,
    # `serve/fleet/disagg.py`): admissions routed to the prefill pool,
    # prefill->decode stream hand-offs completed/failed, and the chain
    # payload they moved. (`decode_long_prompt_stalls` is deliberately
    # NOT here: it exports as a gauge, NaN while the fleet is not
    # disaggregation-armed.)
    "routed_prefill", "handoffs_completed", "handoffs_failed",
    "handoff_bytes", "handoff_tokens",
    # Journal storage health (ISSUE 18, `serve/fleet/journal.py`):
    # every OSError the WAL's VFS shim surfaced (bounded-backoff
    # retries included), entries into the NON_DURABLE degraded mode,
    # and re-arms back to durable. The live alarmed state is the
    # `journal_non_durable` gauge below.
    "journal_storage_errors", "journal_degraded_events",
    "journal_rearms",
    # Router high availability (ISSUE 20, `serve/fleet/standby.py`):
    # standby promotions to primary, worker-side epoch refusals of a
    # deposed router's commands (each one is a split-brain write that
    # did NOT happen — any nonzero value during steady state is a
    # page), and WAL-tail catch-up resyncs (checkpoint+segment reads
    # covering stream gaps or NON_DURABLE backlogs). The live
    # `router_epoch` / `lease_age_s` / `standby_lag_records` gauges
    # ride below.
    "takeovers", "fenced_commands_refused", "standby_catchups",
})


# The controller-side vocabulary (`serve/fleet/autoscaler.py`):
# AutoscaleMetrics.snapshot() derives its keys from this set, exactly
# the FLEET_COUNTER_KEYS discipline — one list to extend per counter.
# Decision-tick splits flatten to decision_ticks_<decision>.
AUTOSCALE_COUNTER_KEYS = frozenset({
    "scale_up_started", "scale_up_completed", "scale_up_failed",
    "scale_down_completed", "scale_down_vetoed", "spawn_timeouts",
})


def fleet_exposition(router, autoscaler=None) -> str:
    """The fleet-router scrape body: :class:`~pddl_tpu.serve.fleet.
    FleetMetrics` counters (circuit transitions and per-class
    ``tokens_streamed_<class>`` splits included as flattened counters)
    plus live per-replica gauges — lifecycle, breaker state, and
    assigned load as labeled series keyed by replica id. Same
    renderer/text format as serving and training, so one Prometheus
    config scrapes all three tiers.

    ``autoscaler`` (defaults to the router's attached one, if any)
    appends the elastic-scaling series under ``pddl_fleet_autoscale_``:
    the controller counters (scale attempts/completions/vetoes, spawn
    timeouts, decision-tick splits) and its live gauges (fleet size,
    pending spawns, pressure, per-class goodput rates) — the scale
    events the runbook reads during a capacity page."""
    snap = dict(router.metrics.snapshot())
    counters = FLEET_COUNTER_KEYS | {
        k for k in snap
        if k.startswith(("circuit_", "admission_rejected_",
                         "tokens_streamed_"))}
    snap["replicas"] = len(router.replicas)
    snap["replicas_healthy"] = router.healthy_replicas
    # Disaggregation (ISSUE 17): pool sizes as a role-labeled series
    # (every vocabulary role present, so a dashboard's query shape
    # does not depend on the fleet's), and the decode-side stall gauge
    # — NaN while the fleet is not disaggregation-armed, the same
    # present-but-unobserved philosophy as the journal gauges below.
    role_counts = {role: 0 for role in ("prefill", "decode", "unified")}
    for s in router.replicas:
        role = getattr(s.driver, "role", "unified")
        role_counts[role] = role_counts.get(role, 0) + 1
    snap["replicas_by_role"] = role_counts
    armed = bool(getattr(router, "disagg_armed", False))
    snap["decode_long_prompt_stalls"] = (
        router.metrics.decode_long_prompt_stalls if armed else None)
    # Control-plane durability gauges (ISSUE 14). Present even when
    # the subsystem is unarmed — None renders NaN, the same
    # present-but-unobserved philosophy as every other gauge, so a
    # dashboard can tell "journal off" from "metric vanished".
    journal = getattr(router, "journal", None)
    snap["journal_bytes"] = (journal.wal_bytes
                             if journal is not None else None)
    snap["journal_lag_records"] = (journal.records_since_checkpoint
                                   if journal is not None else None)
    # The widened loss-on-crash window, live (ISSUE 18): 1 while the
    # WAL runs NON_DURABLE (acks flowing, backlog in memory), 0 while
    # durable, NaN when no journal is armed. THE disk-failure pager.
    snap["journal_non_durable"] = (
        int(bool(getattr(journal, "non_durable", False)))
        if journal is not None else None)
    gray = getattr(router, "gray", None)
    snap["replicas_suspected_gray"] = (len(gray.suspected)
                                       if gray is not None else None)
    # Router HA gauges (ISSUE 20): the armed fencing epoch (NaN on an
    # epoch-free router — the pre-HA deployment shape), the lease's
    # age since last renewal (read against its TTL: age approaching
    # TTL means the holder's renewal loop is wedged), and the hot
    # standby's replication lag in WAL records (0 = promotable with an
    # empty loss window). `router.ha` duck-types either side of the
    # pair: a primary's LeaseKeeper or a promoted HotStandby.
    snap["router_epoch"] = getattr(router, "epoch", None)
    ha = getattr(router, "ha", None)
    lease_age = getattr(ha, "lease_age_s", None)
    snap["lease_age_s"] = lease_age() if callable(lease_age) else None
    lag = getattr(ha, "lag_records", None)
    snap["standby_lag_records"] = lag() if callable(lag) else None
    if router.admission is not None:
        # The ladder rung as a gauge: 0 NORMAL … 3 REJECT_COLD. The
        # runbook's first stop during an overload page.
        snap["brownout_rung"] = int(router.admission.rung)
    snap["replica_state"] = {
        f"r{s.replica_id}": 1 if s.state.value == "up" else 0
        for s in router.replicas}
    snap["replica_breaker_open"] = {
        f"r{s.replica_id}": 0 if s.breaker.allows_traffic else 1
        for s in router.replicas}
    snap["replica_load"] = {
        f"r{s.replica_id}": s.load for s in router.replicas}
    parts = [render_prometheus(snap, prefix="pddl_fleet",
                               counters=frozenset(counters))]
    if autoscaler is None:
        autoscaler = getattr(router, "autoscaler", None)
    if autoscaler is not None:
        auto = dict(autoscaler.metrics.snapshot())
        auto_counters = AUTOSCALE_COUNTER_KEYS | {
            k for k in auto if k.startswith("decision_ticks_")}
        auto.update(autoscaler.gauges())
        parts.append(render_prometheus(
            auto, prefix="pddl_fleet_autoscale",
            counters=frozenset(auto_counters)))
    return "".join(parts)


def serve_exposition(metrics, engine=None, *,
                     step_timer=None,
                     device_memory: bool = False) -> str:
    """The one scrape body: serving metrics (+ engine gauges + ring
    summary when an engine is given), optionally the training
    `StepTimer` snapshot and per-device memory — training and serving
    through a single export path."""
    parts = [render_prometheus(
        metrics.snapshot(), prefix="pddl_serve",
        counters=SERVE_COUNTER_KEYS,
        # Cumulative latency histograms over the same reservoirs the
        # p50/p99 gauges estimate from — the dashboard's
        # histogram_quantile() and SLO burn-rate source.
        histograms={
            "ttft_seconds": reservoir_histogram(
                metrics.ttft_s, TTFT_BUCKETS_S),
            "token_latency_seconds": reservoir_histogram(
                metrics.token_latency_s, TOKEN_LATENCY_BUCKETS_S),
        })]
    if engine is not None:
        parts.append(render_prometheus(engine_gauges(engine),
                                       prefix="pddl_serve_engine"))
        summary = engine.telemetry.summary()
        # The ring summary's non-scalar fields are labeled series
        # already shaped for the renderer; drop the step window (ids,
        # not measurements).
        summary.pop("window_first_step", None)
        summary.pop("window_last_step", None)
        parts.append(render_prometheus(summary, prefix="pddl_serve_ring"))
    if step_timer is not None:
        parts.append(render_prometheus(
            step_timer.snapshot(), prefix="pddl_train_step",
            counters=frozenset({"steps_timed"})))
    if device_memory:
        parts.append(render_prometheus(device_memory_gauges(),
                                       prefix="pddl_device_memory"))
    return "".join(parts)


# ------------------------------------------------------- HTTP endpoint


class MetricsHTTPServer:
    """``/metrics`` on a stdlib HTTP server (daemon thread).

    ``collect`` is called per scrape and must return the exposition
    text (build it with :func:`serve_exposition`); a raising collect
    answers 500 with the error text instead of killing the thread.
    ``port=0`` binds an ephemeral port (tests); read it back from
    ``server.port``.
    """

    def __init__(self, collect: Callable[[], str],
                 host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API name
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404, "only /metrics is served")
                    return
                try:
                    body = collect().encode("utf-8")
                except Exception as e:  # noqa: BLE001 - scrape must not kill
                    body = f"collect failed: {e}\n".encode("utf-8")
                    self.send_response(500)
                else:
                    self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes are chatty
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="pddl-metrics-http")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsHTTPServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
