"""Crash-durable flight recorder: a worker's last words (ISSUE 19).

A SIGKILL'd worker takes its in-memory telemetry ring and any
unshipped spans with it — exactly the records an operator needs to
explain the death. The flight recorder writes them to a per-worker
file as they happen: CRC-framed JSON records appended to a
``current.frec`` segment, atomically rotated (fsync + rename) into
numbered ``seg-NNNNNN.frec`` files with the oldest pruned. Plain
appends are enough for process-death durability — the page cache
survives SIGKILL — so the hot path never fsyncs; rotation is the
machine-crash checkpoint.

Every file operation routes through the r21 journal VFS shim
(:class:`pddl_tpu.serve.fleet.journal._JournalVFS`), so a
StorageFaultPlan covers the recorder exactly like the WAL and a
failing disk degrades it to counted no-export (``records_dropped``,
then ``disabled``) — it must NEVER crash serving.

:func:`harvest` is the router's side: read every segment of a dead
worker's directory, CRC-verify, stop cleanly at a torn tail, and
return the records for the postmortem bundle
(:func:`write_postmortem`) alongside the WAL and drain mirrors.
"""

from __future__ import annotations

import json
import os
import re
import struct
import time
import zlib
from typing import Dict, List, Optional

from pddl_tpu.serve.fleet.journal import _JournalVFS

_MAGIC = b"PFR1"
# Frame: magic, payload length, crc32(payload) — then the payload.
_HEADER = struct.Struct(">4sII")
_SEG_RE = re.compile(r"^seg-(\d{6})\.frec$")

CURRENT_NAME = "current.frec"


class FlightRecorder:
    """Append-only, bounded, fault-degrading record sink for one
    worker process.

    ``append`` never raises: an ``OSError`` (real disk trouble or an
    injected storage fault) is counted, and after ``error_limit``
    strikes the recorder disables itself — every later append is a
    counted drop. Serving never notices.
    """

    def __init__(self, dirpath: str, *, storage_plan=None,
                 max_segment_bytes: int = 262144,
                 max_segments: int = 4,
                 error_limit: int = 3,
                 tracer=None,
                 clock=time.monotonic):
        self.dir = str(dirpath)
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segments = int(max_segments)
        self.error_limit = int(error_limit)
        self._vfs = _JournalVFS(storage_plan)
        self._tracer = tracer
        self._clock = clock
        self._fd: Optional[int] = None
        self._cur_bytes = 0
        self.records_written = 0
        self.records_dropped = 0
        self.bytes_written = 0
        self.rotations = 0
        self.errors = 0
        self.disabled = False
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._open_current()
        except OSError:
            self._note_error(fatal=True)

    # ------------------------------------------------------- file plumbing
    @property
    def current_path(self) -> str:
        return os.path.join(self.dir, CURRENT_NAME)

    def _segment_seqs(self) -> List[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        seqs = []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                seqs.append(int(m.group(1)))
        return sorted(seqs)

    def _open_current(self) -> None:
        self._fd = self._vfs.open(
            self.current_path,
            os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        self._cur_bytes = self._vfs.fstat(self._fd).st_size

    def _close_fd(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def _note_error(self, fatal: bool = False) -> None:
        self.errors += 1
        if fatal or self.errors >= self.error_limit:
            self.disabled = True
            self._close_fd()

    # ------------------------------------------------------------- writes
    def append(self, record: Dict[str, object]) -> bool:
        """Frame and append one record; ``False`` means dropped
        (disabled recorder or a counted write failure)."""
        if self.disabled or self._fd is None:
            self.records_dropped += 1
            return False
        try:
            payload = json.dumps(record, separators=(",", ":"),
                                 default=_json_default).encode("utf-8")
            frame = _HEADER.pack(_MAGIC, len(payload),
                                 zlib.crc32(payload)) + payload
            self._vfs.write(self._fd, frame)
        except (OSError, TypeError, ValueError):
            self.records_dropped += 1
            self._note_error()
            return False
        self._cur_bytes += len(frame)
        self.bytes_written += len(frame)
        self.records_written += 1
        if self._cur_bytes >= self.max_segment_bytes:
            self._rotate()
        return True

    def _rotate(self) -> None:
        """Seal ``current`` into a numbered segment: fsync (the
        machine-crash checkpoint), atomic rename, prune the oldest
        beyond ``max_segments``, reopen a fresh current."""
        if self._fd is None:
            return
        try:
            self._vfs.fsync(self._fd)
            self._close_fd()
            seqs = self._segment_seqs()
            next_seq = (seqs[-1] + 1) if seqs else 1
            seg_path = os.path.join(self.dir,
                                    f"seg-{next_seq:06d}.frec")
            self._vfs.replace(self.current_path, seg_path)
            self._open_current()
        except OSError:
            self._note_error()
            return
        self.rotations += 1
        for seq in self._segment_seqs()[:-self.max_segments]:
            try:
                os.unlink(os.path.join(self.dir,
                                       f"seg-{seq:06d}.frec"))
            except OSError:
                pass
        if self._tracer is not None:
            self._tracer.on_flight_rotate(self.rotations,
                                          self.bytes_written)

    def close(self) -> None:
        if self._fd is not None:
            try:
                self._vfs.fsync(self._fd)
            except OSError:
                pass
            self._close_fd()

    def counts(self) -> Dict[str, int]:
        return {
            "records_written": self.records_written,
            "records_dropped": self.records_dropped,
            "bytes_written": self.bytes_written,
            "rotations": self.rotations,
            "errors": self.errors,
            "disabled": int(self.disabled),
        }


def _json_default(obj):
    item = getattr(obj, "item", None)
    if callable(item):
        return item()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


# ----------------------------------------------------------- harvesting


def readable_records(data: bytes) -> List[Dict[str, object]]:
    """Decode the readable prefix of one segment's bytes: frames are
    trusted until the first torn/corrupt one (short header, bad magic,
    truncated payload, CRC mismatch), then reading stops — the same
    prefix discipline the WAL applies."""
    out: List[Dict[str, object]] = []
    off = 0
    n = len(data)
    while off + _HEADER.size <= n:
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC:
            break
        start = off + _HEADER.size
        end = start + length
        if end > n:
            break  # torn tail: the crash cut this frame short
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            break
        try:
            out.append(json.loads(payload.decode("utf-8")))
        except (UnicodeDecodeError, ValueError):
            break
        off = end
    return out


def harvest(dirpath: str) -> List[Dict[str, object]]:
    """Read a (dead) worker's flight-recorder directory: every sealed
    segment in sequence order, then the unsealed ``current`` tail.
    Unreadable files are skipped — harvest returns whatever survived,
    it never raises."""
    records: List[Dict[str, object]] = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return records
    ordered = sorted(n for n in names if _SEG_RE.match(n))
    if CURRENT_NAME in names:
        ordered.append(CURRENT_NAME)
    for name in ordered:
        try:
            with open(os.path.join(dirpath, name), "rb") as f:
                data = f.read()
        except OSError:
            continue
        records.extend(readable_records(data))
    return records


def write_postmortem(dirpath: str,
                     bundle: Dict[str, object]) -> Optional[str]:
    """Drop the router's postmortem bundle next to the segments it
    was harvested from (``postmortem-NNN.json``, never overwriting an
    earlier death's bundle). Returns the path, or ``None`` if the
    directory is as dead as the worker."""
    try:
        existing = [n for n in os.listdir(dirpath)
                    if n.startswith("postmortem-")]
        path = os.path.join(
            dirpath, f"postmortem-{len(existing):03d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=2, sort_keys=True,
                      default=_json_default)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
