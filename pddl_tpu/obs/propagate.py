"""Cross-process trace-context propagation for the fleet (ISSUE 19).

`obs/trace.py` gives ONE engine Dapper-style spans; this module is the
glue that makes one trace span the whole fleet. Three pieces, all
dependency-free and transport-agnostic:

- **Context minting** (:func:`trace_id_for_rid`,
  :meth:`TraceCollector.context_for`): the router stamps
  ``(trace_id, parent_span_id)`` onto every submit/restore/chain pipe
  command. The trace id is the PRIMARY rid (hedge copies and the r20
  hand-off's fresh rid alias back to it — the same alias discipline
  the journal uses), so every process's records key to one trace
  without any cross-process id negotiation.
- **Span shipping** (:class:`SpanShipper`): a worker buffers its
  finished span records — bounded, drops counted, never blocking the
  engine loop — and ships them back piggybacked on the pong/event
  reads the pipe already does.
- **Collection** (:class:`TraceCollector`): the router-side ledger —
  one ``kind="fleet_span"`` record per stream (submit/route/hedge/
  restore/hand-off/finish events on the ROUTER's clock) plus every
  replica span record received, each tagged with the replica's
  estimated clock offset so `obs/assemble.py` can place all the
  timelines on one axis.

Clock alignment is NTP-style off the existing ping/pong heartbeat:
the router stamps its send time on each ping, the worker echoes it
back with its own ``time.monotonic()``, and the sample taken at the
smallest round-trip wins (:class:`ClockAligner`) — minimal RTT means
minimal asymmetry error.

Every event-name literal emitted here is machine-checked against the
assembler's ``TRACE_EVENTS`` vocabulary (graftlint ``trace-vocab``).
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

# A trace context on the wire: (trace_id, parent_span_id).
TraceContext = Tuple[str, str]


def trace_id_for_rid(rid: int) -> str:
    """The fleet trace id: the PRIMARY router rid, zero-padded hex —
    the same shape engine-local spans mint from their request ids, so
    a record's trace id never needs a second id namespace."""
    return f"{int(rid):016x}"


# ------------------------------------------------------ clock alignment


def estimate_offset(send_s: float, recv_s: float,
                    remote_mono_s: float) -> Tuple[float, float]:
    """One NTP-style sample: ``(offset_s, rtt_s)`` where ``offset_s``
    is the remote monotonic clock minus the local one (midpoint
    assumption: the remote read its clock halfway through the round
    trip). ``local_time = remote_time - offset_s``."""
    rtt = recv_s - send_s
    offset = remote_mono_s - (send_s + recv_s) / 2.0
    return offset, rtt


class ClockAligner:
    """Minimal-RTT offset keeper for one remote process: of every
    ping/pong sample observed, the one with the smallest round trip
    carries the smallest asymmetry error, so it wins outright —
    the classic NTP filter, one float of state per replica."""

    __slots__ = ("offset_s", "best_rtt_s", "samples")

    def __init__(self):
        self.offset_s: Optional[float] = None
        self.best_rtt_s: Optional[float] = None
        self.samples = 0

    def observe(self, send_s: float, recv_s: float,
                remote_mono_s: float) -> None:
        offset, rtt = estimate_offset(send_s, recv_s, remote_mono_s)
        if rtt < 0.0:
            return  # clock went backwards across the sample: discard
        self.samples += 1
        if self.best_rtt_s is None or rtt < self.best_rtt_s:
            self.best_rtt_s = rtt
            self.offset_s = offset


# --------------------------------------------------------- span shipping


class SpanShipper:
    """The worker-side span buffer: bounded (a stalled pipe must never
    balloon a worker), drops counted (the assembler reports them as a
    known blind spot instead of a silent one), drained in batches onto
    whatever event the transport is already sending."""

    __slots__ = ("_buf", "capacity", "dropped", "shipped")

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._buf: Deque[Dict[str, object]] = deque()
        self.dropped = 0
        self.shipped = 0

    def add(self, record: Dict[str, object]) -> bool:
        if len(self._buf) >= self.capacity:
            self.dropped += 1
            return False
        self._buf.append(record)
        return True

    def drain(self, max_records: Optional[int] = 64
              ) -> List[Dict[str, object]]:
        out: List[Dict[str, object]] = []
        while self._buf and (max_records is None
                             or len(out) < max_records):
            out.append(self._buf.popleft())
        self.shipped += len(out)
        return out

    def __len__(self) -> int:
        return len(self._buf)


def chain_export_span(ctx: Optional[TraceContext], t0: float, t1: float,
                      n_blocks: int, *, replica=None,
                      role: Optional[str] = None) -> Dict[str, object]:
    """The chain-wire transfer's OUT half as a span record — minted by
    the worker around ``export_prefix_chain`` so a hand-off's trace
    shows the D2H export wall on the prefill replica's own clock."""
    return _chain_span("chain_export", ctx, t0, t1, n_blocks,
                       replica, role)


def chain_import_span(ctx: Optional[TraceContext], t0: float, t1: float,
                      n_blocks: int, *, replica=None,
                      role: Optional[str] = None) -> Dict[str, object]:
    """The transfer's IN half: the host-tier landing on the decode
    replica."""
    return _chain_span("chain_import", ctx, t0, t1, n_blocks,
                       replica, role)


def _chain_span(name: str, ctx: Optional[TraceContext], t0: float,
                t1: float, n_blocks: int, replica,
                role: Optional[str]) -> Dict[str, object]:
    tid, psid = (ctx[0], ctx[1]) if ctx else (None, None)
    return {
        "schema": SCHEMA_VERSION,
        "kind": "span",
        "trace_id": tid,
        "span_id": name,
        "name": name,
        "request_id": None,
        "start_s": t0,
        "end_s": t1,
        "duration_s": t1 - t0,
        "finish_reason": "transferred",
        "attrs": {"parent_span_id": psid, "n_blocks": int(n_blocks)},
        "events": [{"t_s": t1, "name": name, "n_blocks": int(n_blocks),
                    "wall_s": t1 - t0}],
        "events_dropped": 0,
        "replica": replica,
        "role": role,
    }


# ------------------------------------------------------------ collection


class TraceCollector:
    """The router-side trace ledger (armed via ``FleetRouter(...,
    dtrace=True)``).

    One ``kind="fleet_span"`` record per stream, keyed by PRIMARY rid
    — hedge copies and hand-off rebinds alias to it, so the router's
    submit/route/hedge/restore/hand-off/finish events and every
    replica's span records share one trace id. Replica records land
    via :meth:`add_replica_records` (pipe batches or a dead worker's
    flight-recorder harvest) tagged with the replica's estimated
    clock offset when one is known.

    Bounded everywhere: at most ``max_traces`` live router records
    (oldest TERMINAL records retire first) and ``max_replica_records``
    replica spans, overflow counted in ``records_dropped`` — tracing
    must never become the router's memory leak.
    """

    def __init__(self, clock=time.monotonic, *,
                 max_traces: int = 8192,
                 max_replica_records: int = 65536):
        self._clock = clock
        self._records: "Dict[int, Dict[str, object]]" = {}
        self._order: Deque[int] = deque()
        self._alias: Dict[int, int] = {}
        self._replica_records: Deque[Dict[str, object]] = deque(
            maxlen=int(max_replica_records))
        self._aligners: Dict[int, ClockAligner] = {}
        self._max_traces = int(max_traces)
        self.records_dropped = 0
        self.flight_records = 0
        self.spans_dropped_remote = 0

    # ------------------------------------------------------- identity
    def primary_rid(self, rid: int) -> int:
        return self._alias.get(int(rid), int(rid))

    def context_for(self, rid: int) -> TraceContext:
        """The wire context for a submit/restore/chain command keyed
        by ``rid`` — pure (no record is opened), so a failed routing
        attempt leaves no phantom trace."""
        return (trace_id_for_rid(self.primary_rid(rid)), "router")

    def alias(self, rid: int, primary_rid: int) -> None:
        """Bind a secondary rid (hedge copy) to its primary."""
        self._alias[int(rid)] = self.primary_rid(primary_rid)

    def rebind(self, old_rid: int, new_rid: int) -> None:
        """The r20 hand-off rebind: the stream continues under a FRESH
        rid; its records keep flowing into the original trace."""
        self._alias[int(new_rid)] = self.primary_rid(old_rid)

    # ------------------------------------------------- router records
    def _record(self, rid: int) -> Dict[str, object]:
        primary = self.primary_rid(rid)
        rec = self._records.get(primary)
        if rec is None:
            rec = {
                "schema": SCHEMA_VERSION,
                "kind": "fleet_span",
                "trace_id": trace_id_for_rid(primary),
                "rid": primary,
                "start_s": self._clock(),
                "end_s": None,
                "state": None,
                "reason": None,
                "n_tokens": 0,
                "ttft_s": None,
                "events": [],
            }
            self._records[primary] = rec
            self._order.append(primary)
            self._evict()
        return rec

    def _evict(self) -> None:
        while len(self._records) > self._max_traces:
            # Retire the oldest TERMINAL record first; a fleet holding
            # more than max_traces LIVE streams loses the oldest live
            # one (counted) rather than growing without bound.
            victim = None
            for rid in self._order:
                rec = self._records.get(rid)
                if rec is not None and rec["state"] is not None:
                    victim = rid
                    break
            if victim is None:
                victim = self._order[0]
            self._order.remove(victim)
            self._records.pop(victim, None)
            self.records_dropped += 1

    def _event(self, rid: int, name: str, **attrs) -> Dict[str, object]:
        rec = self._record(rid)
        ev: Dict[str, object] = {"t_s": self._clock(), "name": name}
        if attrs:
            ev.update(attrs)
        rec["events"].append(ev)  # type: ignore[union-attr]
        return rec

    def on_submit(self, rid: int, *, prompt_len: int, priority: str,
                  session: Optional[str] = None) -> None:
        rec = self._event(rid, "submit", prompt_len=int(prompt_len),
                          priority=priority)
        if session is not None:
            rec["session"] = session

    def on_route(self, rid: int, replica_id: int, how: str) -> None:
        self._event(rid, "route", replica=int(replica_id), how=how)

    def on_hedge(self, hedge_rid: int, primary_rid: int,
                 replica_id: int) -> None:
        self.alias(hedge_rid, primary_rid)
        self._event(primary_rid, "hedge", replica=int(replica_id),
                    hedge_rid=int(hedge_rid))

    def on_restore(self, rid: int, replica_id: int, via: str) -> None:
        self._event(rid, "restore", replica=int(replica_id), via=via)

    def on_first_token(self, rid: int, ttft_s: float) -> None:
        rec = self._record(rid)
        if rec["ttft_s"] is None:
            rec["ttft_s"] = float(ttft_s)
            self._event(rid, "first_token", ttft_s=float(ttft_s))

    def on_handoff(self, rid: int, from_replica: int, to_replica: int,
                   export_s: float, import_s: float,
                   blocks: int) -> None:
        """Stamp a completed prefill->decode hand-off (``rid`` is the
        FRESH rid, already rebound to the original trace)."""
        self._event(rid, "handoff", from_replica=int(from_replica),
                    to_replica=int(to_replica), blocks=int(blocks))
        self._event(rid, "handoff_export", wall_s=float(export_s))
        self._event(rid, "handoff_import", wall_s=float(import_s))

    def on_finish(self, rid: int, state: str, reason: Optional[str],
                  n_tokens: int, ttft_s: Optional[float] = None) -> None:
        rec = self._event(rid, "finish", state=state)
        rec["state"] = state
        rec["reason"] = reason
        rec["n_tokens"] = max(int(rec["n_tokens"] or 0), int(n_tokens))
        rec["end_s"] = self._clock()
        if ttft_s is not None:
            # The engine-measured TTFT outranks the router's event-
            # arrival stamp (same adoption rule the router applies).
            rec["ttft_s"] = float(ttft_s)

    # ------------------------------------------------ replica records
    def observe_clock(self, replica_id: int, send_s: float,
                      recv_s: float, remote_mono_s: float) -> None:
        self._aligners.setdefault(
            int(replica_id), ClockAligner()).observe(
            send_s, recv_s, remote_mono_s)

    def set_offset(self, replica_id: int,
                   offset_s: Optional[float]) -> None:
        """Adopt a driver-estimated offset (`ProcessReplica` keeps its
        own min-RTT estimate off the heartbeat it already runs)."""
        if offset_s is None:
            return
        aligner = self._aligners.setdefault(int(replica_id),
                                            ClockAligner())
        aligner.offset_s = float(offset_s)
        aligner.samples += 1

    def clock_offset(self, replica_id: int) -> Optional[float]:
        aligner = self._aligners.get(int(replica_id))
        return None if aligner is None else aligner.offset_s

    def add_replica_records(self, replica_id: int,
                            records: List[Dict[str, object]], *,
                            source: str = "pipe") -> int:
        """Fold a batch of worker span records in, tagged with their
        replica, transport (``pipe`` vs ``flightrec``), and the
        replica's current clock-offset estimate."""
        added = 0
        offset = self.clock_offset(replica_id)
        for rec in records:
            rec = dict(rec)
            rec.setdefault("replica", int(replica_id))
            rec["source"] = source
            if offset is not None:
                rec.setdefault("clock_offset_s", offset)
            self._replica_records.append(rec)
            added += 1
            if source == "flightrec":
                self.flight_records += 1
        return added

    def note_remote_drops(self, dropped: int) -> None:
        """Adopt a worker shipper's cumulative drop counter (the max
        across reports — it only grows on the worker's side)."""
        self.spans_dropped_remote = max(self.spans_dropped_remote,
                                        int(dropped))

    # ----------------------------------------------------- inspection
    def records(self) -> List[Dict[str, object]]:
        """Every record the collector holds — router fleet_spans (in
        submit order) then replica spans — ready for
        :func:`pddl_tpu.obs.assemble.stitch`."""
        out: List[Dict[str, object]] = [
            dict(self._records[rid]) for rid in self._order
            if rid in self._records]
        out.extend(dict(r) for r in self._replica_records)
        return out

    def trace_ids(self) -> List[str]:
        return [trace_id_for_rid(rid) for rid in self._order
                if rid in self._records]

    def dump(self, path: str) -> int:
        """Write every record as JSONL (the assembler CLI's input);
        returns the record count."""
        records = self.records()
        with open(path, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec, separators=(",", ":"),
                                   default=_json_default) + "\n")
        return len(records)


def _json_default(obj):
    item = getattr(obj, "item", None)
    if callable(item):
        return item()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")
