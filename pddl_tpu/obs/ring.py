"""Fixed-capacity per-tick telemetry ring for the serving engine.

`ServeMetrics` keeps lifetime aggregates; a live incident needs the
RECENT per-tick shape of the engine — was occupancy pinned, did one
site's dispatch wall time spike, did retries cluster — without an
unbounded log. This ring is that window: the engine appends one record
per ``step()`` (occupancy, queue depth, tokens emitted, per-site
``_device_call`` wall time, retries, degraded flag), capacity is fixed
at construction, and the oldest record is overwritten in place.
``snapshot()`` hands benches and the drain path a stable oldest→newest
copy; ``summary()`` collapses the window into the handful of gauges the
Prometheus exposition and the drain snapshot embed.

Host-side only: records are plain dicts of scalars the engine already
computed — appending can never add a device sync.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class TelemetryRing:
    """Ring buffer of per-tick telemetry records.

    A preallocated slot list plus a rolling write index (not a deque):
    capacity is enforced by construction, append is O(1) with no
    resizing, and the memory high-water mark is ``capacity`` records
    forever — the property the "bounded under sustained load" contract
    needs to be structural, not amortized.
    """

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._slots: List[Optional[Dict[str, object]]] = \
            [None] * self.capacity
        self._next = 0          # write position
        self._count = 0         # total records ever appended

    def __len__(self) -> int:
        return min(self._count, self.capacity)

    @property
    def total_appended(self) -> int:
        """Records ever appended (>= ``len`` once the ring wrapped)."""
        return self._count

    def append(self, record: Dict[str, object]) -> None:
        self._slots[self._next] = record
        self._next = (self._next + 1) % self.capacity
        self._count += 1

    def snapshot(self) -> List[Dict[str, object]]:
        """Oldest→newest copy of the current window (safe to mutate —
        the nested ``site_wall_s`` dict is copied too, so
        post-processing a snapshot can never corrupt the live ring)."""
        if self._count < self.capacity:
            window = self._slots[:self._count]
        else:
            window = self._slots[self._next:] + self._slots[:self._next]
        out = []
        for r in window:
            c = dict(r)
            sw = c.get("site_wall_s")
            if isinstance(sw, dict):
                c["site_wall_s"] = dict(sw)
            out.append(c)
        return out

    def last(self) -> Optional[Dict[str, object]]:
        """Newest record — copied like :meth:`snapshot`, so a caller
        post-processing it can never corrupt the live ring."""
        if self._count == 0:
            return None
        rec = dict(self._slots[(self._next - 1) % self.capacity])
        sw = rec.get("site_wall_s")
        if isinstance(sw, dict):
            rec["site_wall_s"] = dict(sw)
        return rec

    def summary(self) -> Dict[str, object]:
        """The window collapsed to export gauges: tick-wall percentiles,
        mean queue/occupancy, totals, and per-site wall-time sums —
        what the drain snapshot embeds and ``/metrics`` exposes without
        shipping every record."""
        window = self.snapshot()
        if not window:
            return {"ticks": 0}
        walls = sorted(float(r.get("tick_wall_s", 0.0)) for r in window)
        n = len(walls)
        site_wall: Dict[str, float] = {}
        for r in window:
            for site, w in (r.get("site_wall_s") or {}).items():
                site_wall[site] = site_wall.get(site, 0.0) + float(w)
        return {
            "ticks": n,
            "window_first_step": window[0].get("step"),
            "window_last_step": window[-1].get("step"),
            "tick_wall_p50_s": walls[n // 2],
            "tick_wall_p99_s": walls[min(n - 1, int(0.99 * n))],
            "mean_queue_depth": (sum(float(r.get("queue_depth", 0))
                                     for r in window) / n),
            "mean_live_slots": (sum(float(r.get("live_slots", 0))
                                    for r in window) / n),
            "tokens_emitted": sum(int(r.get("tokens", 0)) for r in window),
            "retries": sum(int(r.get("retries", 0)) for r in window),
            "degraded_ticks": sum(bool(r.get("degraded"))
                                  for r in window),
            "site_wall_s": {k: round(v, 6)
                            for k, v in sorted(site_wall.items())},
        }
